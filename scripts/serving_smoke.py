#!/usr/bin/env python
"""CPU-fallback serving smoke for the tier-1 gate (docs/SERVING.md).

Drives the full continuous-batching stack on the simulated-CPU backend with
a tiny GPT: a mixed-length open-loop request stream through admit -> chunked
prefill -> paged decode -> evict, under enough pool pressure to force
preemption, plus an eos-terminated request. Asserts:

1. every request finishes and the slot/allocator state fully drains;
2. greedy serving output is EXACTLY ``InferenceEngine.generate``'s output
   for the same prompts (continuous batching must be invisible to results);
3. the ``serving/unbucketed-decode-shape`` dslint rule stays silent on the
   serving loop's compile log and fires on a synthetic per-step recompile.

The main smoke serves from int8 KV pages (``kv_bits=8``) — the quantized
pools, scatter-time quantization, and fused-dequant decode path are on the
tier-1 gate, and the greedy-equivalence assertion IS the documented
quantization-tolerance bar (no argmax flips on this model).

``--chaos`` (docs/SERVING.md "Overload & failure") runs the recovery
contract against the REAL engine instead: one injected dispatch-failure
episode (every retry raises -> preempt-and-requeue -> heal) and one request
deadline expiry under load, asserting greedy outputs stay IDENTICAL to
``InferenceEngine.generate``, the page-conservation audit is clean, and the
recovery events (``dispatch_error``/``dispatch_failed``/``deadline_miss``)
were recorded.

``--prefix`` (docs/SERVING.md "KV quantization & prefix caching") drives a
chat-style mixed stream where every request opens with the same system
prompt through a copy-on-write prefix-cache engine: physical pages
allocated must undercut the sum of logical pages, greedy outputs must stay
generate-identical, and the refcount audit must be clean after the drain.

``--spec`` (docs/SERVING.md "Speculative decoding") drives the REAL engine
with both drafters: an n-gram self-drafting run (whose early random
histories force >= 1 full-reject window) and a draft-model run with the
draft == the target (forcing >= 1 full-accept window in fewer dispatches),
asserting greedy outputs stay IDENTICAL to ``InferenceEngine.generate``
under both, the page audit is clean, and the adaptive-k/accept-rate ledger
flowed.

``--fleet`` (docs/SERVING.md "Fleet") runs TWO real-engine replicas as
separate worker PROCESSES behind the fleet router and SIGKILLs one of them
mid-stream: the router must detect the death (pipe EOF), re-route the dead
replica's in-flight requests to the survivor with their streamed tokens
kept, finish every request generate-identical, and leave the survivor's
page-conservation audit clean.

``--disagg`` (docs/SERVING.md "Tensor parallel & disaggregation") runs a
prefill-specialist and a decode-specialist worker PROCESS behind the
role-aware router: every request must prefill on one replica, hand its
quantized KV pages off over the wire (ownership transfer — the prefill
side frees only after the decode side imports), and finish decoding on
the other, generate-identical, with BOTH replicas' page audits clean
after the drain.

``--tiers`` (docs/SERVING.md "Multi-tenancy & SLO tiers") runs a 3-tier
mixed-tenant stream on the REAL engine and injects a noisy-neighbor batch
flood (``FaultPlan.tenant_flood_at``) mid-stream: interactive/standard
outputs must stay generate-identical through the flood, the degradation
ladder must run >= 1 full brownout cycle (typed ``tier_brownout``
enter AND exit events, each page-audited), the flood must be bounded —
shed with typed verdicts but never fully starved — the per-tenant ledger
must attribute every tenant, and the pools must drain to zero.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deepspeed_tpu.analysis import analyze_compile_log  # noqa: E402
from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,  # noqa: E402
                                     InferenceEngine)
from deepspeed_tpu.inference.engine import for_gpt  # noqa: E402
from deepspeed_tpu.inference.serving import (Request, ServingConfig,  # noqa: E402
                                             ServingEngine,
                                             make_open_loop_workload,
                                             run_continuous)
from deepspeed_tpu.models import gpt as G  # noqa: E402


def main() -> int:
    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    # pool deliberately too small for all slots to max out -> preemption;
    # max_queue armed = the overload-safe config (and what keeps the
    # serving/unbounded-admission rule silent below); kv_bits=8 = the
    # quantized-pool config (the greedy-equivalence assert below is the
    # documented quantization-tolerance bar, and the
    # serving/dense-kv-at-capacity rule stays silent under pool pressure)
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        num_pages=12, dtype="float32", decode_block=4, max_queue=32,
        kv_bits=8))
    eng.warmup()

    wl = make_open_loop_workload(8, rate_rps=500.0, prompt_len=(3, 30),
                                 max_new=(4, 16), vocab_size=64, seed=7)
    # one long prompt exercising the chunked (multi-dispatch) prefill path
    wl.append(Request(prompt=np.arange(40, dtype=np.int32) % 64,
                      max_new_tokens=6, arrival_time=0.01))
    rep = run_continuous(eng, wl)
    assert rep["finished"] == len(wl), rep
    assert eng.paged_cache["k_pages"].dtype.name == "int8", "kv8 pool"
    print(f"[smoke] {rep['finished']} finished (int8 KV pages), "
          f"{rep['preemptions']} preemptions, "
          f"{rep['compiled_programs']} compiled programs, "
          f"tokens/s={rep['tokens_per_sec']}")

    # greedy equivalence vs the static engine
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in wl:
        ref = np.asarray(ie.generate(
            np.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
        got = np.asarray(r.tokens[:r.max_new_tokens])
        assert np.array_equal(ref, got), (r.rid, ref, got)
    print("[smoke] greedy outputs identical to InferenceEngine.generate")

    # eos termination frees the slot early
    sched = eng.make_scheduler()
    probe = Request(prompt=np.zeros(4, np.int32), max_new_tokens=50,
                    eos_token_id=None)
    sched.submit(probe)
    sched.step()
    eos_req = Request(prompt=np.zeros(4, np.int32), max_new_tokens=50,
                      eos_token_id=int(probe.tokens[1]))
    sched2 = eng.make_scheduler()
    sched2.submit(eos_req)
    sched2.run_to_completion()
    assert eos_req.tokens[-1] == eos_req.eos_token_id
    assert len(eos_req.tokens) < 50, "eos did not cut generation short"
    assert sched2.allocator.allocated_pages == 0, "pages leaked after eos"
    print(f"[smoke] eos terminated at {len(eos_req.tokens)} tokens, "
          f"pages drained")

    # dslint: silent on the serving loop, fires on a per-step recompile log
    assert not analyze_compile_log(eng).findings
    broken = [{"kind": "decode", "shape": (1, 5 + i)} for i in range(5)]
    errs = analyze_compile_log(broken).errors()
    assert errs and errs[0].rule_id == "serving/unbucketed-decode-shape"
    print("[smoke] dslint serving rule: silent on loop, fires on regression")

    print("serving_smoke: PASS")
    return 0


def chaos_main() -> int:
    """End-to-end recovery on the real engine: an injected dispatch-failure
    episode and a deadline expiry, both healing with zero page leaks and
    generate-identical outputs for every surviving request."""
    from deepspeed_tpu.resilience import FaultPlan, RecoveryLog, install_plan

    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        dtype="float32", decode_block=4, max_queue=32, dispatch_retries=2))
    eng.warmup()
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))

    def assert_generate_identical(requests):
        for r in requests:
            ref = np.asarray(ie.generate(
                np.asarray(r.prompt)[None],
                max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
            got = np.asarray(r.tokens[:r.max_new_tokens])
            assert np.array_equal(ref, got), (r.rid, ref, got)

    # 1) dispatch-failure episode: dispatches 3..5 raise — with 2 retries
    #    (3 attempts) one whole episode fails, so the recovery path is the
    #    full preempt-and-requeue + audit, not just an in-place retry
    log = RecoveryLog(role="serving", prefix="Serving")
    wl = make_open_loop_workload(6, rate_rps=500.0, prompt_len=(3, 20),
                                 max_new=(4, 12), vocab_size=64, seed=13)
    install_plan(FaultPlan(dispatch_raise_at=3, dispatch_raise_times=3))
    try:
        sched = eng.make_scheduler(recovery_log=log)
        for r in wl:
            assert sched.submit(r), r.rid
        sched.run_to_completion()
    finally:
        install_plan(None)
    assert log.count("dispatch_error") == 3, log.counters
    assert log.count("dispatch_failed") == 1, log.counters
    rep = sched.audit()
    assert rep["ok"] and sched.allocator.allocated_pages == 0, rep
    assert_generate_identical(wl)
    print(f"[chaos] dispatch-failure episode healed "
          f"({log.count('dispatch_error')} errors, 1 failed episode, "
          f"{sum(r.preemptions for r in wl)} requeues), outputs identical, "
          f"pool audit clean")

    # 2) deadline expiry under load: a zero-deadline request expires at the
    #    first scheduler step; its neighbors finish untouched
    log2 = RecoveryLog(role="serving", prefix="Serving")
    sched2 = eng.make_scheduler(recovery_log=log2)
    doomed = Request(prompt=np.arange(1, 6, dtype=np.int32) % 64,
                     max_new_tokens=30, deadline_s=0.0)
    survivors = [Request(prompt=np.arange(1, 8, dtype=np.int32) % 64,
                         max_new_tokens=8) for _ in range(2)]
    assert sched2.submit(doomed)
    for r in survivors:
        assert sched2.submit(r)
    sched2.run_to_completion()
    from deepspeed_tpu.inference.serving import RequestState

    assert doomed.state is RequestState.EXPIRED, doomed.state
    assert log2.count("deadline_miss") == 1, log2.counters
    rep2 = sched2.audit()
    assert rep2["ok"] and sched2.allocator.allocated_pages == 0, rep2
    assert all(r.state is RequestState.FINISHED for r in survivors)
    assert_generate_identical(survivors)
    print("[chaos] deadline expiry evicted the doomed request, pages "
          "drained, survivors identical to generate")

    # 3) stalled dispatch: an injected 0.3s stall inside a serving phase
    #    must trip the armed watchdog deadline (stall + recovery recorded)
    #    while the run completes unharmed
    log3 = RecoveryLog(role="serving", prefix="Serving")
    eng.serving.prefill_deadline_s = 0.08
    eng.serving.decode_deadline_s = 0.08
    eng.serving.watchdog_poll_s = 0.02
    install_plan(FaultPlan(dispatch_stall_at=1, dispatch_stall_seconds=0.3))
    try:
        sched3 = eng.make_scheduler(recovery_log=log3)
        wl3 = make_open_loop_workload(3, rate_rps=500.0, prompt_len=(3, 10),
                                      max_new=(4, 8), vocab_size=64, seed=17)
        for r in wl3:
            assert sched3.submit(r)
        sched3.run_to_completion()
        sched3.close()
    finally:
        install_plan(None)
        eng.serving.prefill_deadline_s = None
        eng.serving.decode_deadline_s = None
    assert log3.count("watchdog_stall") == 1, log3.counters
    assert log3.count("watchdog_recovered") == 1, log3.counters
    rep3 = sched3.audit()
    assert rep3["ok"] and sched3.allocator.allocated_pages == 0, rep3
    assert_generate_identical(wl3)
    print("[chaos] stalled dispatch flagged by the serving watchdog "
          "(stall + recovery events), outputs identical, pool audit clean")

    # 4) pool-pressure overload: a pool too small for every slot forces
    #    recompute-preemption; the audit must stay clean through it
    eng2 = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        num_pages=12, dtype="float32", decode_block=4, max_queue=32))
    eng2.warmup()
    sched4 = eng2.make_scheduler()
    wl4 = make_open_loop_workload(6, rate_rps=500.0, prompt_len=(10, 30),
                                  max_new=(8, 16), vocab_size=64, seed=19)
    for r in wl4:
        assert sched4.submit(r)
    sched4.run_to_completion()
    assert sum(r.preemptions for r in wl4) >= 1, "pool pressure never bit"
    rep4 = sched4.audit()
    assert rep4["ok"] and sched4.allocator.allocated_pages == 0, rep4
    assert_generate_identical(wl4)
    print(f"[chaos] pool-pressure overload healed by recompute-preemption "
          f"({sum(r.preemptions for r in wl4)} preemptions), outputs "
          f"identical, pool audit clean")

    print("serving_smoke[chaos]: PASS")
    return 0


def prefix_main() -> int:
    """Copy-on-write prefix caching end to end (docs/SERVING.md "KV
    quantization & prefix caching"): a mixed chat-style stream where every
    request opens with the same system prompt must allocate FEWER physical
    pages than the sum of logical pages, keep outputs generate-identical,
    and drain with a clean refcount audit."""
    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=96, prefill_chunk=64,
        dtype="float32", decode_block=4, max_queue=32,
        enable_prefix_cache=True))
    eng.warmup()

    # every request opens with the same 16-token system prompt (2 full
    # pages at page_size 8) + its own suffix
    sysp = (np.arange(16, dtype=np.int32) * 7 + 3) % 64
    rng = np.random.default_rng(11)
    wl, t = [], 0.0
    for _ in range(10):
        t += 0.002
        n = int(rng.integers(2, 24))
        wl.append(Request(
            prompt=np.concatenate([sysp,
                                   rng.integers(0, 64, (n,)).astype(np.int32)]),
            max_new_tokens=int(rng.integers(3, 10)), arrival_time=t))
    rep = run_continuous(eng, wl)
    assert rep["finished"] == len(wl), rep
    stats = rep["page_stats"]
    assert stats["shared"] > 0, stats
    assert stats["physical"] < stats["logical"], \
        f"prefix caching shared nothing: {stats}"
    print(f"[prefix] {rep['finished']} finished; physical pages "
          f"{stats['physical']} < logical {stats['logical']} "
          f"(ratio {rep['physical_logical_page_ratio']}, "
          f"{stats['shared']} borrowed)")

    # greedy equivalence: page sharing must be invisible in the outputs
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=96))
    for r in wl:
        ref = np.asarray(ie.generate(
            np.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
        got = np.asarray(r.tokens[:r.max_new_tokens])
        assert np.array_equal(ref, got), (r.rid, ref, got)
    print("[prefix] greedy outputs identical to InferenceEngine.generate")

    sched = eng.last_scheduler
    rep_audit = sched.audit()
    assert rep_audit["ok"], rep_audit
    assert sched.allocator.allocated_pages == 0, "pages leaked"
    assert len(sched.prefix_cache) == 0, "index entries outlived their pages"
    print("[prefix] refcount audit clean, pool drained, index empty")

    print("serving_smoke[prefix]: PASS")
    return 0


def spec_main() -> int:
    """Speculative decoding end to end on the real engine (docs/SERVING.md
    "Speculative decoding"): both drafters, >= 1 full-reject and >= 1
    full-accept window, generate-identical outputs, clean page audit."""
    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))

    def run(drafter, draft=None):
        eng = ServingEngine(cfg, params, ServingConfig(
            num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
            dtype="float32", decode_block=4, max_queue=32,
            spec_drafter=drafter, spec_k=4), draft=draft)
        eng.warmup()
        wl = make_open_loop_workload(8, rate_rps=500.0, prompt_len=(3, 30),
                                     max_new=(4, 16), vocab_size=64, seed=7)
        rep = run_continuous(eng, wl)
        assert rep["finished"] == len(wl), rep
        sched = eng.last_scheduler
        audit = sched.audit()
        assert audit["ok"] and sched.allocator.allocated_pages == 0, audit
        for r in wl:
            ref = np.asarray(ie.generate(
                np.asarray(r.prompt)[None],
                max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
            got = np.asarray(r.tokens[:r.max_new_tokens])
            assert np.array_equal(ref, got), (r.rid, ref, got)
        return rep["spec"], rep

    # n-gram self-drafting: random prompts give degenerate early matches,
    # so full-reject windows MUST occur; greedy loops then lock in accepts
    ngram, rep_n = run("ngram")
    assert ngram["windows"] > 0 and ngram["drafted"] > 0, ngram
    assert ngram["full_reject_windows"] >= 1, ngram
    assert 0.0 <= ngram["accept_rate"] <= 1.0, ngram
    print(f"[spec] ngram: {ngram['windows']} windows, accept_rate "
          f"{ngram['accept_rate']}, tokens/dispatch "
          f"{ngram['tokens_per_dispatch']}, "
          f"{ngram['full_reject_windows']} full-reject window(s), "
          f"outputs identical to generate, audit clean")

    # draft model == target: proposals are the target's own greedy
    # continuations, so full-accept windows MUST occur and the stream
    # finishes in fewer dispatches than one-token-per-step would need
    dm, rep_d = run("draft_model", draft=(cfg, params))
    assert dm["full_accept_windows"] >= 1, dm
    assert dm["accept_rate"] > 0.5, dm
    assert (rep_d["decode_steps"] < rep_n["decode_steps"]
            or dm["tokens_per_dispatch"] > ngram["tokens_per_dispatch"]), \
        (dm, ngram)
    print(f"[spec] draft_model: {dm['windows']} windows, accept_rate "
          f"{dm['accept_rate']}, tokens/dispatch "
          f"{dm['tokens_per_dispatch']}, "
          f"{dm['full_accept_windows']} full-accept window(s), "
          f"outputs identical to generate, audit clean")

    print("serving_smoke[spec]: PASS")
    return 0


def fleet_main() -> int:
    """Fleet failover end to end (docs/SERVING.md "Fleet"): two real-engine
    replica processes, one SIGKILL'd mid-stream. The router re-routes the
    dead replica's requests (kept tokens preserved), every request finishes
    generate-identical, and the survivor's page audit is clean."""
    import signal

    from deepspeed_tpu.inference.fleet import (FleetConfig, ReplicaRouter,
                                               SubprocessReplica)
    from deepspeed_tpu.inference.serving import RequestState

    model = dict(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                 max_seq_len=128)
    serving = dict(num_slots=2, page_size=8, max_model_len=64,
                   prefill_chunk=16, dtype="float32", decode_block=4,
                   max_queue=32)
    replicas = [SubprocessReplica(f"r{i}", model, serving, seed=0)
                for i in range(2)]
    router = ReplicaRouter(replicas, FleetConfig(reroute_budget=2,
                                                 heartbeat_deadline_s=60.0))
    print(f"[fleet] 2 worker processes up "
          f"(pids {[r.pid for r in replicas]})")

    rng = np.random.default_rng(23)
    wl = [Request(prompt=rng.integers(0, 64, (int(rng.integers(4, 24)),))
                  .astype(np.int32),
                  max_new_tokens=int(rng.integers(8, 16)))
          for _ in range(6)]
    for r in wl:
        assert router.submit(r), r.rid
    assert len({router._assignment[r.rid] for r in wl}) == 2, \
        "placement used only one replica"

    # step until the doomed replica holds in-flight work with streamed
    # tokens, then SIGKILL it — a preempted host, not a graceful exit
    doomed = replicas[0]
    for _ in range(200):
        router.step()
        held = [r for r in wl
                if router._assignment.get(r.rid) == doomed.replica_id]
        if held and any(len(r.tokens) >= 2 for r in held):
            break
    else:
        raise AssertionError("doomed replica never held streaming work")
    kept_at_kill = {r.rid: len(r.tokens) for r in held}
    os.kill(doomed.pid, signal.SIGKILL)
    print(f"[fleet] SIGKILL'd replica r0 (pid {doomed.pid}) holding "
          f"{len(held)} request(s), kept tokens {kept_at_kill}")

    router.run_to_completion()
    assert router.counters.get("replica_dead") == 1, router.counters
    assert router.counters.get("request_rerouted", 0) >= len(held), \
        router.counters
    rerouted_kept = [e for e in router.events
                     if e["event"] == "request_rerouted"
                     and e.get("kept_tokens", 0) > 0]
    assert rerouted_kept, "no re-route preserved streamed tokens"
    assert all(r.state is RequestState.FINISHED for r in wl), \
        [r.state for r in wl]

    audit = router.audit_survivors()
    assert audit["ok"], audit
    assert audit["replicas"]["r1"]["allocated"] == 0, audit
    print(f"[fleet] fleet drained on the survivor "
          f"({router.counters['request_rerouted']} re-routes, "
          f"{len(rerouted_kept)} with kept tokens), audit clean")

    # greedy equivalence: failover must be invisible in the outputs (the
    # parent holds its own jax runtime for the reference engine)
    cfg = G.GPTConfig(**model)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in wl:
        ref = np.asarray(ie.generate(
            np.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
        got = np.asarray(r.tokens[:r.max_new_tokens])
        assert np.array_equal(ref, got), (r.rid, ref, got)
    print("[fleet] greedy outputs identical to InferenceEngine.generate "
          "across the replica kill")

    router.close()
    print("serving_smoke[fleet]: PASS")
    return 0


def disagg_main() -> int:
    """Disaggregated prefill/decode end to end (docs/SERVING.md "Tensor
    parallel & disaggregation"): a prefill-specialist and a decode-specialist
    worker process behind the role-aware router. Every request prefills on
    one replica, hands its int8 KV pages off over the subprocess wire, and
    decodes on the other — generate-identical, both pools drained."""
    from deepspeed_tpu.inference.fleet import (FleetConfig, ReplicaRouter,
                                               SubprocessReplica)
    from deepspeed_tpu.inference.serving import RequestState

    model = dict(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                 max_seq_len=128)
    # int8 KV pages: the handoff wire payload is the quantized pool slices
    # + per-page scales — the cheap-serialization path the design leans on
    serving = dict(page_size=8, max_model_len=64, prefill_chunk=16,
                   dtype="float32", decode_block=4, max_queue=32, kv_bits=8)
    pre = SubprocessReplica("pre", model, dict(serving, num_slots=2,
                                               role="prefill"), seed=0)
    dec = SubprocessReplica("dec", model, dict(serving, num_slots=2,
                                               role="decode"), seed=0)
    router = ReplicaRouter([pre, dec], FleetConfig(reroute_budget=2,
                                                   heartbeat_deadline_s=60.0))
    print(f"[disagg] prefill + decode specialists up "
          f"(pids {pre.pid}, {dec.pid})")

    rng = np.random.default_rng(29)
    wl = [Request(prompt=rng.integers(0, 64, (int(rng.integers(4, 24)),))
                  .astype(np.int32),
                  max_new_tokens=int(rng.integers(6, 14)))
          for _ in range(5)]
    # one prompt spanning several pages: the handoff must transfer a
    # multi-page KV prefix, not just a single page
    wl.append(Request(prompt=(np.arange(30, dtype=np.int32) * 5 + 1) % 64,
                      max_new_tokens=8))
    for r in wl:
        assert router.submit(r), r.rid
    assert all(router._assignment[r.rid] == "pre" for r in wl), \
        "role-aware placement must send fresh requests to the prefill " \
        "specialist"
    router.run_to_completion()

    assert router.counters.get("handoff_forwarded", 0) == len(wl), \
        router.counters
    assert not router.counters.get("handoff_fallback"), router.counters
    assert all(r.state is RequestState.FINISHED for r in wl), \
        [r.state for r in wl]
    print(f"[disagg] {len(wl)} requests prefilled on 'pre', pages handed "
          f"off, decoded on 'dec' "
          f"({router.counters['handoff_forwarded']} handoffs forwarded)")

    audit = router.audit_survivors()
    assert audit["ok"], audit
    assert audit["replicas"]["pre"]["allocated"] == 0, audit
    assert audit["replicas"]["dec"]["allocated"] == 0, audit
    print("[disagg] ownership transfer clean: both pools drained to zero")

    # greedy equivalence: the prefill->wire->decode split must be invisible
    cfg = G.GPTConfig(**model)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in wl:
        ref = np.asarray(ie.generate(
            np.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
        got = np.asarray(r.tokens[:r.max_new_tokens])
        assert np.array_equal(ref, got), (r.rid, ref, got)
    print("[disagg] greedy outputs identical to InferenceEngine.generate "
          "across the handoff")

    router.close()
    print("serving_smoke[disagg]: PASS")
    return 0


def tiers_main() -> int:
    """SLO-tiered multi-tenancy end to end on the real engine
    (docs/SERVING.md "Multi-tenancy & SLO tiers"): a 3-tier mixed stream
    with one injected batch flood (``FaultPlan.tenant_flood_at``). Asserts
    interactive/standard outputs stay generate-identical through the
    flood, the degradation ladder runs >= 1 full brownout cycle (enter AND
    exit), every ladder transition passes the page-conservation audit, and
    the pools drain to zero."""
    import tempfile
    import time

    from deepspeed_tpu.inference.serving import RequestState
    from deepspeed_tpu.resilience import (FaultPlan, RecoveryLog,
                                          install_plan, read_events)

    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    # a tight batch partition (max_queue=2) turns the flood into organic
    # queue_full sheds — the pressure signal that latches the ladder; the
    # short window/dwell lets the exit half of the cycle land in CI time
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        dtype="float32", decode_block=4, max_queue=32,
        tiers={"batch": {"max_queue": 2, "brownout_max_new": 4}},
        tenants={"alice": "interactive", "bob": "standard",
                 "carl": "batch"},
        brownout_window_s=0.8, brownout_enter_shed_rate=0.25,
        brownout_enter_misses=99, brownout_exit_shed_rate=0.05,
        brownout_min_dwell_s=0.05))
    eng.warmup()

    rng = np.random.default_rng(31)
    wl = []
    for tenant in ("alice", "alice", "alice", "bob", "bob", "carl"):
        r = Request(prompt=rng.integers(0, 64,
                                        (int(rng.integers(4, 20)),))
                    .astype(np.int32),
                    max_new_tokens=int(rng.integers(5, 12)),
                    tenant_id=tenant)
        wl.append(r)
    protected = [r for r in wl if r.tenant_id in ("alice", "bob")]

    tmpdir = tempfile.mkdtemp(prefix="serving_tiers_")
    log = RecoveryLog.for_dir(tmpdir, role="serving", prefix="Serving")
    install_plan(FaultPlan(tenant_flood_at=2, tenant_flood_requests=8,
                           tenant_flood_prompt=8, tenant_flood_max_new=8,
                           tenant_flood_vocab=64))
    try:
        sched = eng.make_scheduler(recovery_log=log)
        for r in wl:
            assert sched.submit(r).admitted, r.rid
        sched.run_to_completion()
    finally:
        install_plan(None)
    assert sched.counters.get("tenant_flood") == 1, sched.counters
    # idle ticks let the window drain: the ladder must step fully back
    # down (the reversibility half of the cycle)
    deadline = time.monotonic() + 30.0
    while sched.brownout_stage > 0:
        assert time.monotonic() < deadline, "brownout never exited"
        time.sleep(0.05)
        sched.step()
    events = read_events(tmpdir)
    enters = sum(1 for e in events if e["event"] == "tier_brownout"
                 and e.get("direction") == "enter")
    exits = sum(1 for e in events if e["event"] == "tier_brownout"
                and e.get("direction") == "exit")
    assert enters >= 1 and exits >= 1, (enters, exits)
    print(f"[tiers] brownout cycle complete: {enters} enter / {exits} exit "
          f"transitions, every one page-audited")

    # the flood drew typed verdicts (queue_full / brownout), never silence;
    # the admitted slice of the flood was served, not starved
    flood = [r for r in sched.finished + sched.shed
             if r.tenant_id == "flooder"]
    assert len(flood) == 8, len(flood)
    served = [r for r in flood if r.state is RequestState.FINISHED]
    assert served, "batch-tier flood fully starved"
    assert all(r.reject_reason in ("queue_full", "token_backlog",
                                   "brownout")
               for r in flood if r.state is RequestState.REJECTED)
    print(f"[tiers] flood of 8: {len(served)} served, "
          f"{len(flood) - len(served)} shed with typed verdicts")

    # interactive/standard rode through the flood untouched: every
    # protected request finished, greedy-identical to generate
    assert all(r.state is RequestState.FINISHED for r in protected), \
        [(r.rid, r.state) for r in protected]
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in protected + served:
        ref = np.asarray(ie.generate(
            np.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
        got = np.asarray(r.tokens[:r.max_new_tokens])
        assert np.array_equal(ref, got), (r.rid, ref, got)
    print("[tiers] interactive/standard outputs identical to "
          "InferenceEngine.generate through the flood")

    # per-tenant accounting flowed: every tenant attributable in the ledger
    assert sched.tenants_seen >= {"alice", "bob", "carl", "flooder"}, \
        sched.tenants_seen
    shed_tenants = {e.get("tenant_id") for e in events
                    if e["event"] == "request_shed"}
    assert "flooder" in shed_tenants, shed_tenants
    fin_tiers = {e.get("tier") for e in events
                 if e["event"] == "request_finished"}
    assert {"interactive", "standard"} <= fin_tiers, fin_tiers
    rep = sched.audit()
    assert rep["ok"] and sched.allocator.allocated_pages == 0, rep
    print("[tiers] per-tenant ledger attributable, pool drained, "
          "audit clean")

    print("serving_smoke[tiers]: PASS")
    return 0


if __name__ == "__main__":
    if "--chaos" in sys.argv[1:]:
        sys.exit(chaos_main())
    if "--prefix" in sys.argv[1:]:
        sys.exit(prefix_main())
    if "--spec" in sys.argv[1:]:
        sys.exit(spec_main())
    if "--fleet" in sys.argv[1:]:
        sys.exit(fleet_main())
    if "--disagg" in sys.argv[1:]:
        sys.exit(disagg_main())
    if "--tiers" in sys.argv[1:]:
        sys.exit(tiers_main())
    sys.exit(main())
