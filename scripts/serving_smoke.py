#!/usr/bin/env python
"""CPU-fallback serving smoke for the tier-1 gate (docs/SERVING.md).

Drives the full continuous-batching stack on the simulated-CPU backend with
a tiny GPT: a mixed-length open-loop request stream through admit -> chunked
prefill -> paged decode -> evict, under enough pool pressure to force
preemption, plus an eos-terminated request. Asserts:

1. every request finishes and the slot/allocator state fully drains;
2. greedy serving output is EXACTLY ``InferenceEngine.generate``'s output
   for the same prompts (continuous batching must be invisible to results);
3. the ``serving/unbucketed-decode-shape`` dslint rule stays silent on the
   serving loop's compile log and fires on a synthetic per-step recompile.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deepspeed_tpu.analysis import analyze_compile_log  # noqa: E402
from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,  # noqa: E402
                                     InferenceEngine)
from deepspeed_tpu.inference.engine import for_gpt  # noqa: E402
from deepspeed_tpu.inference.serving import (Request, ServingConfig,  # noqa: E402
                                             ServingEngine,
                                             make_open_loop_workload,
                                             run_continuous)
from deepspeed_tpu.models import gpt as G  # noqa: E402


def main() -> int:
    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    # pool deliberately too small for all slots to max out -> preemption
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        num_pages=12, dtype="float32", decode_block=4))
    eng.warmup()

    wl = make_open_loop_workload(8, rate_rps=500.0, prompt_len=(3, 30),
                                 max_new=(4, 16), vocab_size=64, seed=7)
    # one long prompt exercising the chunked (multi-dispatch) prefill path
    wl.append(Request(prompt=np.arange(40, dtype=np.int32) % 64,
                      max_new_tokens=6, arrival_time=0.01))
    rep = run_continuous(eng, wl)
    assert rep["finished"] == len(wl), rep
    print(f"[smoke] {rep['finished']} finished, "
          f"{rep['preemptions']} preemptions, "
          f"{rep['compiled_programs']} compiled programs, "
          f"tokens/s={rep['tokens_per_sec']}")

    # greedy equivalence vs the static engine
    ie = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in wl:
        ref = np.asarray(ie.generate(
            np.asarray(r.prompt)[None],
            max_new_tokens=r.max_new_tokens))[0, len(r.prompt):]
        got = np.asarray(r.tokens[:r.max_new_tokens])
        assert np.array_equal(ref, got), (r.rid, ref, got)
    print("[smoke] greedy outputs identical to InferenceEngine.generate")

    # eos termination frees the slot early
    sched = eng.make_scheduler()
    probe = Request(prompt=np.zeros(4, np.int32), max_new_tokens=50,
                    eos_token_id=None)
    sched.submit(probe)
    sched.step()
    eos_req = Request(prompt=np.zeros(4, np.int32), max_new_tokens=50,
                      eos_token_id=int(probe.tokens[1]))
    sched2 = eng.make_scheduler()
    sched2.submit(eos_req)
    sched2.run_to_completion()
    assert eos_req.tokens[-1] == eos_req.eos_token_id
    assert len(eos_req.tokens) < 50, "eos did not cut generation short"
    assert sched2.allocator.allocated_pages == 0, "pages leaked after eos"
    print(f"[smoke] eos terminated at {len(eos_req.tokens)} tokens, "
          f"pages drained")

    # dslint: silent on the serving loop, fires on a per-step recompile log
    assert not analyze_compile_log(eng).findings
    broken = [{"kind": "decode", "shape": (1, 5 + i)} for i in range(5)]
    errs = analyze_compile_log(broken).errors()
    assert errs and errs[0].rule_id == "serving/unbucketed-decode-shape"
    print("[smoke] dslint serving rule: silent on loop, fires on regression")

    print("serving_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
