#!/usr/bin/env python
"""Flash-attention tile autotune on the real chip.

Times fwd+bwd (all three grads — both backward kernels) for each
(block_q, block_k) pair at the flagship geometries. N iterations ride ONE
dispatch via lax.fori_loop with a data-dependent carry, so the per-dispatch
tunnel RTT amortizes to noise. Prints one JSON line: per-tile ms + winner.

Usage: python scripts/flash_tile_tune.py ['{"geom": "760m", "iters": 8}']
"""

import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GEOMS = {
    # [B, T, H, D] at the bench train rows' shapes
    "760m": (16, 1024, 16, 96),   # gpt2-760m: d_model 1536, 16 heads
    "350m": (16, 1024, 16, 64),   # gpt2-350m: d_model 1024, 16 heads
    "8k": (2, 8192, 16, 64),      # long-context row
    "tiny": (1, 256, 2, 64),      # CPU interpret-mode smoke only
}

TILES = [(128, 128), (128, 256), (256, 128), (256, 256),
         (256, 512), (512, 256), (512, 512), (1024, 512)]


def main():
    spec = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    geom = spec.get("geom", "760m")
    iters = int(spec.get("iters", 8))
    B, T, H, D = GEOMS[geom]

    import jax

    compile_only = bool(spec.get("compile_only"))
    if spec.get("force_cpu") or compile_only:
        # env alone is too late (sitecustomize imports jax first), and the
        # axon plugin hangs at handshake while another process holds the chip
        os.environ["DS_TPU_ACCELERATOR"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    if compile_only:
        os.environ["DS_TPU_PALLAS_INTERPRET"] = "0"
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt import PRESETS  # noqa: F401 (repo path check)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, T, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, H, D), jnp.bfloat16)

    rows = {}
    best = None
    for bq, bk in TILES:
        if T % bq or T % bk or bq > T or bk > T:
            continue
        fa = functools.partial(flash_attention, causal=True,
                               block_q=bq, block_k=bk)

        def loss(q, k, v, fa=fa):
            return fa(q, k, v).astype(jnp.float32).sum()

        grads = jax.grad(loss, argnums=(0, 1, 2))

        def body(i, carry, grads=grads):
            q, k, v = carry
            dq, dk, dv = grads(q, k, v)
            # data-dependent carry: serializes iterations, defeats DCE
            return (q + 1e-6 * dq.astype(q.dtype),
                    k + 1e-6 * dk.astype(k.dtype),
                    v + 1e-6 * dv.astype(v.dtype))

        f = jax.jit(lambda q, k, v, body=body: jax.lax.fori_loop(
            0, iters, body, (q, k, v)))
        tag = f"{bq}x{bk}"
        if compile_only:
            # Mosaic-compile against the v5e topology (no chips): validates
            # every tile variant BEFORE the tuner spends tunnel time on it
            from jax.experimental import topologies
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            td = topologies.get_topology_desc(platform="tpu",
                                              topology_name="v5e:2x2")
            mesh = Mesh(list(td.devices)[:1], ("d",))
            rep = NamedSharding(mesh, P())
            ab = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
                a.shape, a.dtype, sharding=rep)
            try:
                t0 = time.perf_counter()
                f.lower(ab(q), ab(k), ab(v)).compile()
                rows[tag] = {"compile_ok": True,
                             "compile_s": round(time.perf_counter() - t0, 1)}
            except Exception as e:  # noqa: BLE001
                rows[tag] = {"compile_ok": False, "error": str(e)[:160]}
            print(f"[tile] {geom} {tag}: {rows[tag]}", file=sys.stderr,
                  flush=True)
            continue
        try:
            r = f(q, k, v)
            jax.block_until_ready(r)  # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(f(q, k, v))
            ms = (time.perf_counter() - t0) / iters * 1e3
        except Exception as e:  # noqa: BLE001 — a bad tile must not kill the sweep
            rows[tag] = {"error": str(e)[:160]}
            continue
        rows[tag] = {"ms": round(ms, 2)}
        if best is None or ms < best[1]:
            best = (tag, ms)
        print(f"[tile] {geom} {tag}: {ms:.2f} ms", file=sys.stderr, flush=True)

    out = {"tag": f"flash-tile-{geom}", "geom": list(GEOMS[geom]),
           "iters": iters, "tiles": rows,
           "best": best[0] if best else None,
           "best_ms": round(best[1], 2) if best else None}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
