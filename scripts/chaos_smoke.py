#!/usr/bin/env python
"""Fast fault-injection smoke for CI (scripts/verify_tier1.sh).

Two heal cycles against the real training worker on the CPU mesh:

1. **kill + resume** — one SIGKILL injected mid-checkpoint (pre-commit
   phase, via ``DS_FAULT_PLAN``), then a relaunch that must auto-resume from
   the newest *committed* tag and finish with monotone steps.
2. **NaN → rollback → rejoin** — a ``nan_at_step`` injection poisons one
   batch; the divergence sentinel must roll the run back to the newest
   committed checkpoint, skip the poisoned data cursor, and finish all steps
   with a finite loss IN THE SAME PROCESS (exit 0 = the run self-healed).

With ``--sdc`` it instead runs the silent-data-corruption pair
(docs/RESILIENCE.md "Data integrity") in-process:

3. **host-shard bit flip → rollback, step-exact** — a real bit is flipped
   in a cpu-offloaded optimizer shard mid-run; the integrity scan must
   detect it at the next step boundary, roll back to the newest verified
   anchor, replay the same batches, and land on the SAME final loss as a
   fault-free reference run (the data was never at fault — nothing is
   skipped).
4. **shared KV page bit flip → re-prefill, generate-identical** — a real
   bit is flipped in a prefix-cache-shared page on a live serving engine;
   the background scan must quarantine the page, preempt the borrowers,
   and the re-prefilled requests must emit exactly the fault-free token
   streams with every page audit clean.
"""

import json
import os
import subprocess
import sys
import tempfile


def fail(msg: str) -> int:
    print(f"chaos_smoke: FAIL — {msg}")
    return 1


def nan_rollback_cycle(worker: str) -> int:
    """NaN at data cursor 2 -> auto-rollback -> skip -> finish 4 steps."""
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")
        log = os.path.join(td, "log.jsonl")
        env = dict(os.environ)
        env["DS_FAULT_PLAN"] = json.dumps({"nan_at_step": 2})
        p = subprocess.run(
            [sys.executable, worker, "--ckpt-dir", ckpt, "--steps", "4",
             "--log", log, "--sentinel"], env=env, timeout=240)
        if p.returncode != 0:
            return fail(f"sentinel run did not self-heal (rc={p.returncode})")
        rows = [json.loads(ln) for ln in open(log)]
        if not any(r["rolled_back"] for r in rows):
            return fail("no divergence rollback recorded in the step log")
        events = [json.loads(ln)["event"]
                  for ln in open(os.path.join(ckpt, "recovery_events.jsonl"))]
        for needed in ("divergence_rollback", "poison_skip"):
            if needed not in events:
                return fail(f"recovery event {needed!r} missing ({events})")
        final = rows[-1]
        if final["step"] != 4 or not (final["loss"] == final["loss"]):
            return fail(f"run did not rejoin a healthy trajectory: {final}")
        # the poisoned cursor must be excluded: cursor advances past the
        # step count by exactly the skipped batches
        if final["cursor"] <= final["step"]:
            return fail(f"poisoned cursor was not skipped: {final}")
    print(f"chaos_smoke: PASS — NaN at cursor 2 healed by rollback + skip "
          f"(final step {final['step']}, cursor {final['cursor']}, "
          f"loss {final['loss']:.4f})")
    return 0


def sdc_training_cycle() -> int:
    """Bit flip in a cpu-offloaded optimizer shard: detect -> rollback to
    the verified anchor -> replay -> bitwise-identical final loss."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.resilience.chaos import FaultPlan, install_plan

    steps = 6
    cfg = GPTConfig(vocab_size=128, d_model=32, n_layer=2, n_head=2,
                    max_seq_len=32)

    def make_batch(cursor: int):
        r = np.random.default_rng(1000 + cursor)
        return {"input_ids": r.integers(
            0, cfg.vocab_size, size=(2, 16), dtype=np.int32)}

    def run(td: str, flip_at=None):
        install_plan(FaultPlan(flip_bit_at=flip_at,
                               flip_bit_domain="host_shards")
                     if flip_at is not None else None)
        model, _ = build_gpt(cfg)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 0,
            "zero_optimization": {
                "stage": 2, "offload_optimizer": {"device": "cpu"}},
            "resilience": {
                "enabled": True, "save_dir": td,
                "sentinel": {"enabled": True, "checkpoint_interval": 2,
                             "cursor_checkpointable": True},
                "integrity": {"enabled": True, "scan_interval": 1,
                              "blocks_per_scan": 8, "block_bytes": 4096},
            }})
        rolled = 0
        while engine.global_steps < steps:
            m = engine.train_batch(make_batch(engine.data_cursor))
            if "sdc" in m:
                rolled += 1
        loss = float(m["loss"])
        counters = dict(engine._recovery_log.counters)
        install_plan(None)
        return loss, rolled, counters

    with tempfile.TemporaryDirectory() as td:
        ref_loss, ref_rolled, ref_events = run(os.path.join(td, "ref"))
        if ref_rolled or ref_events.get("sdc_detected"):
            return fail(f"clean run raised SDC alarms ({ref_events})")
        if not ref_events.get("integrity_scan"):
            return fail("integrity scan never ran on the clean run")
        loss, rolled, events = run(os.path.join(td, "flip"), flip_at=4)
        if not rolled:
            return fail("injected host-shard flip was never detected")
        if not events.get("sdc_detected") or not events.get("sdc_rollback"):
            return fail(f"missing sdc events after flip ({events})")
        if loss != ref_loss:
            return fail(f"replay after SDC rollback is not step-exact: "
                        f"final loss {loss!r} vs fault-free {ref_loss!r}")
    print(f"chaos_smoke: PASS — host-shard bit flip detected, rolled back, "
          f"replayed step-exact (final loss {loss:.6f})")
    return 0


def sdc_serving_cycle() -> int:
    """Bit flip in a prefix-shared KV page: quarantine + borrower
    re-prefill -> generate-identical streams, audits clean."""
    import numpy as np

    import jax
    from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine
    from deepspeed_tpu.inference.serving.scheduler import Request
    from deepspeed_tpu.models import gpt as G
    from deepspeed_tpu.resilience.chaos import FaultPlan, install_plan

    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        dtype="float32", decode_block=1, max_queue=64,
        enable_prefix_cache=True, page_fingerprints=True,
        pages_scan_per_step=4))
    prompt = (np.arange(17, dtype=np.int32) % 63) + 1  # 2 shareable pages

    def run(flip_at=None):
        install_plan(FaultPlan(flip_bit_at=flip_at,
                               flip_bit_domain="kv_page")
                     if flip_at is not None else None)
        sched = eng.make_scheduler()
        reqs = [Request(prompt=prompt.copy(), max_new_tokens=6)
                for _ in range(2)]
        sched.submit(reqs[0])
        for _ in range(3):
            sched.step()
        sched.submit(reqs[1])  # borrows the registered prefix pages
        shared_audit = None
        for _ in range(60):
            sched.step()
            if shared_audit is None and sched.page_stats["shared"]:
                shared_audit = sched.audit()  # audit WHILE pages are shared
            if all(r.state.value == "finished" for r in reqs):
                break
        final_audit = sched.audit()
        out = ([list(r.tokens) for r in reqs], dict(sched.counters),
               shared_audit, final_audit)
        sched.close()
        install_plan(None)
        return out

    ref_tokens, ref_counters, ref_shared, ref_final = run(None)
    if ref_counters.get("sdc_detected"):
        return fail(f"clean serving run raised SDC alarms ({ref_counters})")
    if not (ref_shared and ref_shared["ok"] and ref_shared["fingerprinted"]):
        return fail(f"clean shared-page audit swept nothing ({ref_shared})")
    tokens, counters, _, final_audit = run(flip_at=2)
    if not counters.get("chaos_injected"):
        return fail(f"KV-page flip never fired ({counters})")
    if not counters.get("sdc_detected") or not counters.get("sdc_healed"):
        return fail(f"KV-page flip not detected/healed ({counters})")
    if tokens != ref_tokens:
        return fail(f"post-heal streams differ from fault-free: "
                    f"{tokens} vs {ref_tokens}")
    if not final_audit["ok"]:
        return fail(f"page audit dirty after heal: {final_audit['errors']}")
    print(f"chaos_smoke: PASS — shared KV page flip quarantined "
          f"({counters.get('preemption', 0)} borrower preemption(s)), "
          f"re-prefill generate-identical, audits clean")
    return 0


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "--sdc" in sys.argv[1:]:
        sys.path.insert(0, root)  # the SDC cycles run in-process
        rc = sdc_training_cycle()
        return rc if rc else sdc_serving_cycle()
    worker = os.path.join(root, "tests", "resilience_worker.py")
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")
        log = os.path.join(td, "log.jsonl")
        cmd = [sys.executable, worker, "--ckpt-dir", ckpt, "--steps", "3",
               "--log", log]
        env = dict(os.environ)
        # kill during the 2nd save (after step 2), right before COMMIT: the
        # worst spot — all bytes written, durability marker missing
        env["DS_FAULT_PLAN"] = json.dumps(
            {"kill_at_phase": "pre-commit", "kill_at_save": 1})
        p1 = subprocess.run(cmd, env=env, timeout=240)
        if p1.returncode not in (-9, 137):
            return fail(f"injected SIGKILL did not fire (rc={p1.returncode})")

        # the killed tag must exist but carry no COMMIT marker
        killed_tag = os.path.join(ckpt, "global_step2")
        if os.path.exists(os.path.join(killed_tag, "COMMIT")):
            return fail("tag killed pre-commit has a COMMIT marker")
        with open(os.path.join(ckpt, "latest")) as f:
            if f.read().strip() != "global_step1":
                return fail("latest pointer moved past the committed tag")

        env.pop("DS_FAULT_PLAN")
        p2 = subprocess.run(cmd, env=env, timeout=240)
        if p2.returncode != 0:
            return fail(f"auto-resume run exited rc={p2.returncode}")
        steps = [json.loads(ln)["step"] for ln in open(log)]
        if steps != sorted(steps):
            return fail(f"steps reset after resume: {steps}")
        if steps[-1] != 3:
            return fail(f"resume did not reach step 3: {steps}")
        if not os.path.exists(os.path.join(ckpt, "global_step3", "COMMIT")):
            return fail("final checkpoint not committed")
    print(f"chaos_smoke: PASS — SIGKILL pre-commit absorbed, auto-resumed "
          f"(steps {steps})")
    return nan_rollback_cycle(worker)


if __name__ == "__main__":
    sys.exit(main())
