#!/usr/bin/env python
"""Fast fault-injection smoke for CI (scripts/verify_tier1.sh).

Two heal cycles against the real training worker on the CPU mesh:

1. **kill + resume** — one SIGKILL injected mid-checkpoint (pre-commit
   phase, via ``DS_FAULT_PLAN``), then a relaunch that must auto-resume from
   the newest *committed* tag and finish with monotone steps.
2. **NaN → rollback → rejoin** — a ``nan_at_step`` injection poisons one
   batch; the divergence sentinel must roll the run back to the newest
   committed checkpoint, skip the poisoned data cursor, and finish all steps
   with a finite loss IN THE SAME PROCESS (exit 0 = the run self-healed).

This is the cheap end of the resilience test pyramid — the full phase matrix
with bitwise state comparison lives in
``tests/test_resilience.py::test_sigkill_at_every_phase_resumes_bitwise``,
and the in-run health acceptance suite in ``tests/test_watchdog.py``.
"""

import json
import os
import subprocess
import sys
import tempfile


def fail(msg: str) -> int:
    print(f"chaos_smoke: FAIL — {msg}")
    return 1


def nan_rollback_cycle(worker: str) -> int:
    """NaN at data cursor 2 -> auto-rollback -> skip -> finish 4 steps."""
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")
        log = os.path.join(td, "log.jsonl")
        env = dict(os.environ)
        env["DS_FAULT_PLAN"] = json.dumps({"nan_at_step": 2})
        p = subprocess.run(
            [sys.executable, worker, "--ckpt-dir", ckpt, "--steps", "4",
             "--log", log, "--sentinel"], env=env, timeout=240)
        if p.returncode != 0:
            return fail(f"sentinel run did not self-heal (rc={p.returncode})")
        rows = [json.loads(ln) for ln in open(log)]
        if not any(r["rolled_back"] for r in rows):
            return fail("no divergence rollback recorded in the step log")
        events = [json.loads(ln)["event"]
                  for ln in open(os.path.join(ckpt, "recovery_events.jsonl"))]
        for needed in ("divergence_rollback", "poison_skip"):
            if needed not in events:
                return fail(f"recovery event {needed!r} missing ({events})")
        final = rows[-1]
        if final["step"] != 4 or not (final["loss"] == final["loss"]):
            return fail(f"run did not rejoin a healthy trajectory: {final}")
        # the poisoned cursor must be excluded: cursor advances past the
        # step count by exactly the skipped batches
        if final["cursor"] <= final["step"]:
            return fail(f"poisoned cursor was not skipped: {final}")
    print(f"chaos_smoke: PASS — NaN at cursor 2 healed by rollback + skip "
          f"(final step {final['step']}, cursor {final['cursor']}, "
          f"loss {final['loss']:.4f})")
    return 0


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "resilience_worker.py")
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")
        log = os.path.join(td, "log.jsonl")
        cmd = [sys.executable, worker, "--ckpt-dir", ckpt, "--steps", "3",
               "--log", log]
        env = dict(os.environ)
        # kill during the 2nd save (after step 2), right before COMMIT: the
        # worst spot — all bytes written, durability marker missing
        env["DS_FAULT_PLAN"] = json.dumps(
            {"kill_at_phase": "pre-commit", "kill_at_save": 1})
        p1 = subprocess.run(cmd, env=env, timeout=240)
        if p1.returncode not in (-9, 137):
            return fail(f"injected SIGKILL did not fire (rc={p1.returncode})")

        # the killed tag must exist but carry no COMMIT marker
        killed_tag = os.path.join(ckpt, "global_step2")
        if os.path.exists(os.path.join(killed_tag, "COMMIT")):
            return fail("tag killed pre-commit has a COMMIT marker")
        with open(os.path.join(ckpt, "latest")) as f:
            if f.read().strip() != "global_step1":
                return fail("latest pointer moved past the committed tag")

        env.pop("DS_FAULT_PLAN")
        p2 = subprocess.run(cmd, env=env, timeout=240)
        if p2.returncode != 0:
            return fail(f"auto-resume run exited rc={p2.returncode}")
        steps = [json.loads(ln)["step"] for ln in open(log)]
        if steps != sorted(steps):
            return fail(f"steps reset after resume: {steps}")
        if steps[-1] != 3:
            return fail(f"resume did not reach step 3: {steps}")
        if not os.path.exists(os.path.join(ckpt, "global_step3", "COMMIT")):
            return fail("final checkpoint not committed")
    print(f"chaos_smoke: PASS — SIGKILL pre-commit absorbed, auto-resumed "
          f"(steps {steps})")
    return nan_rollback_cycle(worker)


if __name__ == "__main__":
    sys.exit(main())
