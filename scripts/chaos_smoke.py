#!/usr/bin/env python
"""Fast fault-injection smoke for CI (scripts/verify_tier1.sh).

One SIGKILL injected mid-checkpoint (pre-commit phase, via ``DS_FAULT_PLAN``)
against the real training worker on the CPU mesh, then a relaunch that must
auto-resume from the newest *committed* tag and finish with monotone steps.
This is the cheap end of the resilience test pyramid — the full phase matrix
with bitwise state comparison lives in
``tests/test_resilience.py::test_sigkill_at_every_phase_resumes_bitwise``.
"""

import json
import os
import subprocess
import sys
import tempfile


def fail(msg: str) -> int:
    print(f"chaos_smoke: FAIL — {msg}")
    return 1


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "resilience_worker.py")
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")
        log = os.path.join(td, "log.jsonl")
        cmd = [sys.executable, worker, "--ckpt-dir", ckpt, "--steps", "3",
               "--log", log]
        env = dict(os.environ)
        # kill during the 2nd save (after step 2), right before COMMIT: the
        # worst spot — all bytes written, durability marker missing
        env["DS_FAULT_PLAN"] = json.dumps(
            {"kill_at_phase": "pre-commit", "kill_at_save": 1})
        p1 = subprocess.run(cmd, env=env, timeout=240)
        if p1.returncode not in (-9, 137):
            return fail(f"injected SIGKILL did not fire (rc={p1.returncode})")

        # the killed tag must exist but carry no COMMIT marker
        killed_tag = os.path.join(ckpt, "global_step2")
        if os.path.exists(os.path.join(killed_tag, "COMMIT")):
            return fail("tag killed pre-commit has a COMMIT marker")
        with open(os.path.join(ckpt, "latest")) as f:
            if f.read().strip() != "global_step1":
                return fail("latest pointer moved past the committed tag")

        env.pop("DS_FAULT_PLAN")
        p2 = subprocess.run(cmd, env=env, timeout=240)
        if p2.returncode != 0:
            return fail(f"auto-resume run exited rc={p2.returncode}")
        steps = [json.loads(ln)["step"] for ln in open(log)]
        if steps != sorted(steps):
            return fail(f"steps reset after resume: {steps}")
        if steps[-1] != 3:
            return fail(f"resume did not reach step 3: {steps}")
        if not os.path.exists(os.path.join(ckpt, "global_step3", "COMMIT")):
            return fail("final checkpoint not committed")
    print(f"chaos_smoke: PASS — SIGKILL pre-commit absorbed, auto-resumed "
          f"(steps {steps})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
