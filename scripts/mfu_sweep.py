#!/usr/bin/env python
"""MFU attribution sweep on the real chip (VERDICT r3 'next' #2).

Runs the bench train config across remat policies / block sizes / batch
geometry, recording step time, MFU, and peak HBM from device memory_stats.
Usage: python scripts/mfu_sweep.py [configs...]  (default: the standard grid)
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# persistent XLA compile cache: near-identical grid rows each paid a full
# multi-minute compile (chunk-loss scans pushed rows past their timeouts)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))


def run_one(spec: dict) -> dict:
    import numpy as np

    import jax

    # explicit: sitecustomize imports jax before the module-top env edit
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # env alone is not enough once sitecustomize has imported jax: with
        # the tunnel down, axon plugin discovery hangs the first device op
        jax.config.update("jax_platforms", "cpu")

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    mcfg = gpt_mod.PRESETS[spec["model"]]
    mcfg = dataclasses.replace(
        mcfg, remat=spec["remat"], remat_policy=spec.get("policy", "nothing_saveable"),
        max_seq_len=max(mcfg.max_seq_len, spec["seq"]),
        loss_chunk=int(spec.get("loss_chunk", 0)))
    model, mcfg = build_gpt(mcfg)
    micro_bs, seq, steps = spec["micro_bs"], spec["seq"], spec.get("steps", 10)
    # gas>1 folds all micro-steps into ONE compiled program (the engine's
    # fused accumulation scan) — amortizes per-dispatch tunnel latency, which
    # the r4 chip session measured at ~350ms/step constant across models
    gas = int(spec.get("gas", 1))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": spec.get("stage", 1)},
            "gradient_clipping": 1.0,
            "steps_per_print": 0,
        })
    rng = np.random.default_rng(0)
    # k_steps: K complete optimizer steps per dispatch (train_batches scan) —
    # amortizes tunnel RTT with NO extra HBM (unlike gas, whose fp32
    # accumulator AOT-OOMs the lead 760M rows)
    k_steps = int(spec.get("k_steps", 1))
    shape = (gas, micro_bs, seq) if gas > 1 else (micro_bs, seq)
    if k_steps > 1:
        shape = (k_steps,) + shape

    def make_batch():
        return {"input_ids": rng.integers(0, mcfg.vocab_size,
                                          size=shape, dtype=np.int32)}

    step_fn = engine.train_batches if k_steps > 1 else engine.train_batch
    m = step_fn(make_batch())
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = step_fn(make_batch())
    float(m["loss"])
    dt = time.perf_counter() - t0

    stats = jax.local_devices()[0].memory_stats() or {}
    peak_gb = stats.get("peak_bytes_in_use", 0) / 2**30
    tok = steps * k_steps * gas * micro_bs * (seq - 1) / dt
    n_params = mcfg.num_params()
    fpt = 6 * n_params + 12 * mcfg.n_layer * mcfg.d_model * seq
    mfu = tok * fpt / (197e12 * jax.device_count())  # v5e bf16 peak per chip
    # platform lets evidence consumers (bench._load_chip_evidence) reject a
    # CPU-run row as chip evidence
    return {**spec, "platform": jax.devices()[0].platform,
            "step_ms": round(dt / (steps * k_steps) * 1e3, 1),
            "tok_s": round(tok, 1), "mfu": round(mfu, 4),
            "peak_hbm_gb": round(peak_gb, 2)}


def main():
    grid = [
        # remat policy attribution at the bench geometry
        {"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "tag": "r2-baseline"},
        {"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "dots_with_no_batch_dims_saveable", "tag": "save-dots"},
        {"model": "gpt2-350m", "micro_bs": 32, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "tag": "350m-bs32"},
        {"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "tag": "350m-save-sublayer"},
        # bigger model: fatter matmuls -> better MXU utilization
        {"model": "gpt2-760m", "micro_bs": 24, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "tag": "760m-bs24"},
        {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "tag": "760m-save-sublayer"},
        {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "dots_with_no_batch_dims_saveable", "tag": "760m-save-dots"},
        {"model": "gpt2-760m", "micro_bs": 16, "seq": 2048, "remat": True,
         "policy": "nothing_saveable", "tag": "760m-seq2048"},
        {"model": "gpt2-760m", "micro_bs": 8, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "tag": "760m-bs8"},
    ]
    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        print(json.dumps(run_one(json.loads(sys.argv[2]))))
        return
    results = []
    for spec in grid:
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one",
                 json.dumps(spec)],
                capture_output=True, text=True, timeout=1200, cwd=REPO)
            line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                         if ln.startswith("{")), None)
            r = json.loads(line) if line else {"tag": spec["tag"],
                                               "error": p.stderr[-300:]}
        except subprocess.TimeoutExpired:
            r = {"tag": spec["tag"], "error": "timed out after 1200s"}
        results.append(r)
        print(json.dumps(r), flush=True)
    with open(os.path.join(REPO, "mfu_sweep_results.json"), "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
