#!/usr/bin/env python
"""Elastic device-loss smoke for CI (scripts/verify_tier1.sh; docs/RESILIENCE.md
"Elastic membership").

One full resize-and-resume cycle against the real training worker:

1. The elastic agent launches a dp=4 worker (quantized-gradient error
   feedback armed, so the run carries world-size-coupled ``qgrad_residual``
   state). A ``lose_worker_at_step`` fault plan SIGKILLs the worker mid-run
   at data cursor 3 — a dp worker dying with its lost device. The device
   probe sees 3 devices from then on.
2. The agent must absorb the death budget-free (``membership_change``, not a
   counted restart), re-resolve the elastic ladder at world=3 (same
   effective batch 12), and relaunch. The worker auto-resumes from the
   newest committed tag, resharding on load (``reshard_applied`` +
   ``reshard_residual_reset`` events).
3. The resharded run must be *exactly* the run a fresh dp=3 worker resumed
   from the same anchor produces: per-step losses identical, final engine
   state bitwise identical, and the consumed data-cursor sequence
   contiguous across the resize (no sample dropped or replayed).
4. Library check on the real anchor: for every master/optimizer leaf,
   repartitioning its 4-way flat shards to 3-way equals freshly
   partitioning the merged leaf 3 ways, bitwise
   (``runtime/zero/reshard.py``).

The full property matrix lives in ``tests/test_reshard.py``; this is the
end-to-end contract in one script.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

TOTAL_STEPS = 6
LOSE_AT = 3  # data cursor of the injected device loss (steps 1..3 committed)

ELASTIC = {
    "enabled": True,
    "max_train_batch_size": 12,
    "micro_batch_sizes": [1, 2, 3, 4],
    "min_world_size": 1,
    "max_world_size": 6,
    "prefer_larger_batch": True,
    "version": 0.2,
}


def fail(msg: str) -> int:
    print(f"elastic_smoke: FAIL — {msg}")
    return 1


def read_log(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f]


def pid_alive(pid_file: str) -> bool:
    try:
        with open(pid_file) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return True  # not written yet: the worker is starting up
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def anchor_partition_check(tag_dir: str) -> str:
    """Repartitioning the anchor's 4-way flat shards to 3-way must equal
    freshly partitioning the merged state 3 ways — bitwise, on the REAL
    committed anchor's master/optimizer leaves."""
    import msgpack
    import numpy as np

    from deepspeed_tpu.runtime.zero.reshard import (
        partition_flat,
        repartition_flat,
    )

    state_dir = os.path.join(tag_dir, "state")
    with open(os.path.join(state_dir, "state.msgpack"), "rb") as f:
        meta = msgpack.unpackb(f.read())
    checked = 0
    for leaf in meta["leaves"]:
        key = leaf["key"]
        if not (key.startswith("master/") or key.startswith("opt/")
                or key.startswith("params/")):
            continue
        arr = np.load(os.path.join(state_dir, "arrays",
                                   f"{leaf['index']}.npy")).reshape(-1)
        if arr.size < 2:
            continue
        four = partition_flat(arr, 4)
        via_reshard = repartition_flat(four, 3, arr.size)
        fresh = partition_flat(arr, 3)
        if via_reshard.tobytes() != fresh.tobytes():
            return f"leaf {key!r}: 4->3 reshard != fresh 3-way partition"
        back = repartition_flat(via_reshard, 4, arr.size)
        if back.tobytes() != four.tobytes():
            return f"leaf {key!r}: 4->3->4 round-trip not bitwise"
        checked += 1
    if checked < 3:
        return f"anchor partition check covered only {checked} leaves"
    print(f"elastic_smoke: anchor partition property held on {checked} "
          f"master/opt/param leaves (4->3 bitwise == fresh 3-way)")
    return ""


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    worker = os.path.join(root, "tests", "elastic_worker.py")

    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.resilience import is_committed, read_events

    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")
        log = os.path.join(td, "log.jsonl")
        pid_file = os.path.join(td, "worker1.pid")
        out_state = os.path.join(td, "resharded_state.npz")
        os.makedirs(ckpt, exist_ok=True)
        launches = []

        def device_count():
            # 4 devices while the first worker lives; the SIGKILL takes one
            # with it (a lost host kills its worker), so every later probe
            # reports 3
            if len(launches) >= 2 or (launches and not pid_alive(pid_file)):
                return 3
            return 4

        def make_cmd(spec):
            launches.append(spec)
            cmd = [sys.executable, worker, "--ckpt-dir", ckpt, "--log", log,
                   "--steps", str(TOTAL_STEPS),
                   "--elastic-world", str(spec.world_size),
                   "--elastic-micro", str(spec.micro_batch),
                   "--elastic-gas", str(spec.gas),
                   "--resilience", "--cursor-data", "--qgrad",
                   "--elastic-config", json.dumps(ELASTIC)]
            if len(launches) == 1:
                cmd += ["--lose-at", str(LOSE_AT), "--pid-file", pid_file]
            else:
                cmd += ["--out-state", out_state]
            return cmd

        agent = DSElasticAgent(
            make_cmd, {"elasticity": ELASTIC}, device_count_fn=device_count,
            max_restarts=2, poll_interval=0.3, checkpoint_dir=ckpt,
            backoff_base=0.05, backoff_max=0.2)
        result = agent.run()

        if result.state != "SUCCEEDED":
            return fail(f"agent did not succeed: {result}")
        if [s.world_size for s in launches] != [4, 3]:
            return fail(f"expected launches at dp4 then dp3, got "
                        f"{[s.world_size for s in launches]}")
        if result.membership_changes != 1:
            return fail(f"expected 1 membership change, got "
                        f"{result.membership_changes}")
        if result.restarts != 0:
            return fail(f"device loss spent restart budget: "
                        f"{result.restarts} restarts counted")
        anchor = os.path.join(ckpt, f"global_step{LOSE_AT}")
        if not is_committed(anchor):
            return fail(f"anchor tag global_step{LOSE_AT} not committed")

        events = {e["event"] for e in read_events(ckpt)}
        for needed in ("membership_change", "reshard_applied",
                       "reshard_residual_reset"):
            if needed not in events:
                return fail(f"recovery event {needed!r} missing ({sorted(events)})")

        rows = read_log(log)
        run1 = [r for r in rows if r["world"] == 4]
        run2 = [r for r in rows if r["world"] == 3]
        if [r["step"] for r in run1] != list(range(1, LOSE_AT + 1)):
            return fail(f"dp4 run steps wrong: {[r['step'] for r in run1]}")
        if [r["step"] for r in run2] != list(range(LOSE_AT + 1, TOTAL_STEPS + 1)):
            return fail(f"dp3 run steps wrong: {[r['step'] for r in run2]}")
        # cursor exactness: the consumed data indexes must be one contiguous
        # range across the resize — nothing dropped, nothing replayed
        consumed = [r["index"] for r in run1] + [r["index"] for r in run2]
        if consumed != list(range(TOTAL_STEPS)):
            return fail(f"data indexes not contiguous across the resize: "
                        f"{consumed}")
        if {r["effective"] for r in rows} != {12}:
            return fail(f"effective batch changed across the resize: "
                        f"{sorted({r['effective'] for r in rows})}")
        if not all(r["loss"] == r["loss"] for r in rows):
            return fail("non-finite loss in the healed run")

        # library property on the real anchor bytes
        err = anchor_partition_check(anchor)
        if err:
            return fail(err)

        # control: a fresh dp3 worker resumed from the SAME anchor must
        # produce the identical trajectory and final state
        control = os.path.join(td, "control")
        control_log = os.path.join(td, "control_log.jsonl")
        control_state = os.path.join(td, "control_state.npz")
        os.makedirs(control, exist_ok=True)
        shutil.copytree(anchor, os.path.join(control,
                                             f"global_step{LOSE_AT}"))
        with open(os.path.join(control, "latest"), "w") as f:
            f.write(f"global_step{LOSE_AT}")
        spec3 = launches[1]
        p = subprocess.run(
            [sys.executable, worker, "--ckpt-dir", control,
             "--log", control_log, "--steps", str(TOTAL_STEPS),
             "--elastic-world", str(spec3.world_size),
             "--elastic-micro", str(spec3.micro_batch),
             "--elastic-gas", str(spec3.gas),
             "--resilience", "--cursor-data", "--qgrad",
             "--elastic-config", json.dumps(ELASTIC),
             "--out-state", control_state],
            timeout=300)
        if p.returncode != 0:
            return fail(f"control dp3 run exited rc={p.returncode}")
        control_rows = read_log(control_log)
        got = [(r["step"], r["loss"]) for r in run2]
        want = [(r["step"], r["loss"]) for r in control_rows]
        if got != want:
            return fail(f"resharded trajectory diverged from the dp3-from-"
                        f"anchor control: {got} vs {want}")

        import numpy as np

        with np.load(out_state) as a, np.load(control_state) as b:
            if sorted(a.files) != sorted(b.files):
                return fail(f"state key sets differ: {sorted(a.files)} vs "
                            f"{sorted(b.files)}")
            for k in a.files:
                if a[k].tobytes() != b[k].tobytes():
                    return fail(f"final state leaf {k!r} not bitwise equal "
                                f"to the dp3-from-anchor control")

    print(f"elastic_smoke: PASS — SIGKILL one of 4 dp workers at cursor "
          f"{LOSE_AT} -> budget-free relaunch at dp3 from global_step"
          f"{LOSE_AT}, resharded run bitwise-identical to the dp3-from-"
          f"anchor control, cursors contiguous {consumed}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
