#!/usr/bin/env python
"""Tunnel-window orchestrator: strict priority order, every row a subprocess.

Learned from the 03:45-06:50Z window (r4): new XLA programs compile 10-25+
min through this path, rows die on compile not execution, and the window can
vanish at any minute. So: cheapest diagnostics first, then the MFU headline
(k8 grid), then decode/SD (never yet measured on chip), then the long rows.
The persistent compile cache (.jax_cache) makes any repeat instant.

Results append to window_run_results.json after every row.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "window_run_results.json")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))

# append across windows: a second tunnel window later in the session must
# add rows, not erase the first window's evidence
RESULTS = []
try:
    with open(OUT) as _f:
        _prev = json.load(_f)
    if isinstance(_prev, list):
        RESULTS = _prev
    else:
        # valid-but-wrong-shape JSON is still evidence — set it aside
        # rather than letting the first save() erase it
        os.replace(OUT, OUT + ".corrupt")
except ValueError:
    # a truncated/corrupt ledger is still evidence — keep it aside rather
    # than overwriting it with a fresh file
    os.replace(OUT, OUT + ".corrupt")
except OSError:
    pass


def save():
    # atomic: a kill mid-write must never truncate the banked rows
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(RESULTS, f, indent=1)
    os.replace(tmp, OUT)


def run(tag, argv, timeout):
    print(f"[window] {tag}...", flush=True)
    t0 = time.time()
    # ts: the ledger now spans windows (and possibly sessions) — rows must
    # carry their own provenance for consumers to tell fresh from stale
    try:
        p = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO)
        line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                     if ln.strip().startswith("{")), None)
        rec = {"tag": tag, "ts": round(t0), "rc": p.returncode,
               "wall_s": round(time.time() - t0),
               "result": json.loads(line) if line else None}
        if p.returncode != 0:
            rec["stderr"] = p.stderr[-400:]
    except subprocess.TimeoutExpired:
        rec = {"tag": tag, "ts": round(t0), "rc": -1,
               "wall_s": round(time.time() - t0),
               "error": f"timeout {timeout}s"}
    except Exception as e:  # noqa: BLE001
        rec = {"tag": tag, "ts": round(t0), "rc": -1, "error": str(e)[:200]}
    RESULTS.append(rec)
    save()
    print(f"[window] {tag}: {json.dumps(rec)[:300]}", flush=True)
    return rec


def mfu(spec, timeout=2400):
    return run(f"mfu:{spec['tag']}",
               [sys.executable, os.path.join(REPO, "scripts", "mfu_sweep.py"),
                "--one", json.dumps(spec)], timeout)


def bench(spec, timeout=2700):
    return run(f"{spec['kind']}:{spec['name']}",
               [sys.executable, os.path.join(REPO, "bench.py"), "--worker",
                json.dumps(spec)], timeout)


def main():
    # 1. diagnostics: RTT + does the cache bridge from AOT compiles work?
    run("rtt-probe", [sys.executable,
                      os.path.join(REPO, "scripts", "chip_session2.py"),
                      "--rtt"], 600)
    run("cache-bridge-axon", [sys.executable,
                              os.path.join(REPO, "scripts",
                                           "cache_bridge_test.py"),
                              "--axon"], 1200)

    # 2. MFU headline: k8 no-chunk rows first (fast compiles, known-runnable)
    mfu({"model": "gpt2-760m", "micro_bs": 12, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "k_steps": 8, "steps": 4,
         "tag": "760m-selrm12-k8"})
    mfu({"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "k_steps": 8, "steps": 4,
         "tag": "350m-save-sublayer-k8"})

    # 3. first-ever on-chip decode + SD (decode compiles TWO generate
    # programs through the tunnel — budget accordingly)
    bench({"kind": "inference", "name": "gpt2-350m-decode", "model": "gpt2-350m",
           "batch": 1, "prompt": 128, "gen": 64}, timeout=3600)
    bench({"kind": "diffusion", "name": "sd-ddim20", "latent": 32,
           "ddim_steps": 20}, timeout=3000)

    # 4. tile autotune (informs flash_block_q/k defaults)
    run("tile:760m", [sys.executable,
                      os.path.join(REPO, "scripts", "flash_tile_tune.py"),
                      json.dumps({"geom": "760m", "iters": 8})], 2400)

    # 5. more k8 rows: full-remat bs16, then the chunk-loss ladder
    mfu({"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "k_steps": 8, "steps": 4,
         "tag": "760m-full-bs16-k8"})
    mfu({"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "loss_chunk": 512, "k_steps": 8,
         "steps": 4, "tag": "760m-selrm16-chunk512-k8"}, timeout=2700)
    mfu({"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "loss_chunk": 128, "k_steps": 8,
         "steps": 4, "tag": "760m-selrm16-chunkloss-k8"}, timeout=2700)

    # 6. batched decode, measured MoE (VERDICT r4 next #5), int8 HBM
    # evidence, MPMD dispatch microbench
    bench({"kind": "inference", "name": "gpt2-350m-decode-b8",
           "model": "gpt2-350m", "batch": 8, "prompt": 128, "gen": 64})
    bench({"kind": "moe_train", "name": "moe-125m-8e-train",
           "model": "moe-125m-8e", "micro_bs": 8, "seq": 1024, "steps": 5},
          timeout=2700)
    # quantized decode: the weight-bandwidth lever measured on chip (int8
    # halves, packed int4 quarters the bytes/token)
    bench({"kind": "inference", "name": "gpt2-350m-decode-b8-int8",
           "model": "gpt2-350m", "batch": 8, "prompt": 128, "gen": 64,
           "quantize_bits": 8})
    bench({"kind": "inference", "name": "gpt2-350m-decode-b8-int4",
           "model": "gpt2-350m", "batch": 8, "prompt": 128, "gen": 64,
           "quantize_bits": 4})
    run("int8-hbm", [sys.executable,
                     os.path.join(REPO, "scripts", "int8_hbm.py")], 2400)
    bench({"kind": "pipeline_mpmd", "name": "pipeline-mpmd-dispatch"})

    # 7. long rows: offload + infinity (big models, host streaming)
    sys.path.insert(0, REPO)
    from bench import INFINITY_CONFIGS

    for spec in INFINITY_CONFIGS:
        bench(spec, timeout=spec.get("timeout", 3600))

    # 7b. the big-decode gamble: 20B int4 chip-RESIDENT decode, host-streamed
    # init (AOT says 13.8 GB peak, 1.95 GB headroom — outside the margin)
    bench({"kind": "inference", "name": "neox20b-decode-b1-int4",
           "model": "gpt-neox-20b", "batch": 1, "prompt": 128, "gen": 32,
           "quantize_bits": 4, "stream_init": True, "reps": 3},
          timeout=3600)

    # 8. long-context k8 row last (compile gamble)
    mfu({"model": "gpt2-350m", "micro_bs": 2, "seq": 8192, "remat": True,
         "policy": "nothing_saveable", "loss_chunk": 512, "k_steps": 8,
         "steps": 4, "tag": "350m-seq8k-chunk512-k8"}, timeout=2700)

    # 9. a full bench.py core sweep: its train rows are the SAME engine
    # programs as the mfu rows above (now cache-warm), so this is cheap and
    # leaves a driver-grade artifact + partial ledger from inside the window
    run("bench-core-sweep",
        [sys.executable, os.path.join(REPO, "bench.py")], 7200)
    print(f"[window] done -> {OUT}")


if __name__ == "__main__":
    main()
