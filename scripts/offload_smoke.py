#!/usr/bin/env python
"""Streamed-offload smoke (docs/OFFLOAD.md): the host<->HBM streaming
contract end to end on the forced-CPU backend, against the REAL engine.

Gates (any failing assertion exits non-zero):

1. streamed == inline: the depth-2 prefetch pipeline reproduces the
   fetch-on-demand trajectory BITWISE over 3 steps (same units, same
   consume order — only the DMA issue points move), and the host-DMA
   column reports the pipeline's depth.
2. quantized fetch: block-int8 host pushes are ledger-recorded
   (``qpush[host-dma]``, ratio > 3x vs fp32) and tolerance-close.
3. chaos DMA stall flagged: an injected ``stall_offload_at`` hang trips the
   ``offload_fetch`` watchdog deadline (stall event recorded, phase named).
4. drain clean + SIGKILL mid-flush: a worker SIGKILL'd inside the per-unit
   host-shard flush leaves the previous committed tag loadable; auto-resume
   from it finishes the run with losses BITWISE equal to an uninterrupted
   reference run.

Wired into scripts/verify_tier1.sh as the offload gate.
"""

import json
import os
import signal
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DS_TPU_ACCELERATOR", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "offload_worker.py")


def _engine(extra):
    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=4, n_head=2, max_seq_len=32))
    config = {"train_micro_batch_size_per_gpu": 2,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "steps_per_print": 0}
    config.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, cfg


def _batch(cfg, seed=0):
    r = np.random.default_rng(seed)
    n = max(2, __import__("jax").device_count())
    return {"input_ids": r.integers(0, cfg.vocab_size, size=(2 * n, 16),
                                    dtype=np.int32)}


def _stream_cfg(**op):
    return {"zero_optimization": {"offload_param": {
        "device": "cpu", "buffer_count": 1, **op}}}


def check_streamed_equals_inline():
    e_str, cfg = _engine(_stream_cfg(prefetch_depth=2))
    e_inl, _ = _engine(_stream_cfg(stream=False))
    for i in range(3):
        b = _batch(cfg, seed=i)
        m1, m2 = e_str.train_batch(b), e_inl.train_batch(b)
        assert float(m1["loss"]) == float(m2["loss"]), \
            f"streamed loss diverged at step {i}"
        assert float(m1["grad_norm"]) == float(m2["grad_norm"])
    dma = e_str._param_stream.last_stats["host_dma"]
    assert dma["prefetch_depth"] == 2 and dma["pushes"] > 0
    print(f"[offload_smoke] streamed == inline bitwise over 3 steps; "
          f"host DMA: {dma['pushes']} pushes, "
          f"{dma['overlapped_frac']:.0%} of waits overlapped, "
          f"exposed {dma['exposed_wait_s'] * 1e3:.1f}ms")


def check_quantized_fetch():
    from deepspeed_tpu.comm.runtime_accounting import wire_ledger

    wire_ledger.reset()
    e_q, cfg = _engine(_stream_cfg(quantized_fetch=True))
    e_x, _ = _engine(_stream_cfg())
    mq = e_q.train_batch(_batch(cfg))
    mx = e_x.train_batch(_batch(cfg))
    rel = abs(float(mq["loss"]) - float(mx["loss"])) / abs(float(mx["loss"]))
    assert rel < 0.05, f"quantized-fetch loss off by {rel:.3f}"
    ratio = wire_ledger.ratio("qpush")
    assert "qpush[host-dma]" in wire_ledger.records and ratio > 3.0, ratio
    wire_ledger.reset()
    print(f"[offload_smoke] quantized host fetch: ledger ratio {ratio:.2f}x, "
          f"loss within {rel:.4f} of exact")


def check_chaos_stall_flagged(tmp):
    from deepspeed_tpu.resilience.chaos import FaultPlan, install_plan
    from deepspeed_tpu.resilience.events import read_events

    save_dir = os.path.join(tmp, "wd")
    e, cfg = _engine({
        **_stream_cfg(prefetch_depth=1),
        "resilience": {"enabled": True, "save_dir": save_dir,
                       "watchdog": {"enabled": True,
                                    "poll_interval_s": 0.05,
                                    "offload_fetch_deadline_s": 0.3,
                                    "escalate": False}}})
    try:
        install_plan(FaultPlan(stall_offload_at=0,
                               stall_offload_seconds=1.2))
        e.train_batch(_batch(cfg))
        deadline = time.monotonic() + 3.0
        while e._watchdog.stall_count == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert e._watchdog.stall_count >= 1, "injected DMA hang not flagged"
        assert e._watchdog.last_stall[0] == "offload_fetch"
        stalls = [ev for ev in read_events(
            os.path.join(save_dir, "recovery_events.jsonl"))
            if ev.get("event") == "watchdog_stall"]
        assert stalls and stalls[-1]["phase"] == "offload_fetch"
    finally:
        install_plan(None)
        if e._watchdog is not None:
            e._watchdog.stop()
    print("[offload_smoke] injected DMA hang flagged as offload_fetch stall "
          f"({e._watchdog.last_stall[1]:.1f}s elapsed at detection)")


def _run_worker(ckpt_dir, steps, log, plan=""):
    env = {**os.environ, "DS_FAULT_PLAN": plan}
    return subprocess.run(
        [sys.executable, WORKER, "--ckpt-dir", ckpt_dir,
         "--steps", str(steps), "--log", log],
        env=env, capture_output=True, text=True, timeout=240)


def check_kill_mid_flush(tmp):
    ckpt = os.path.join(tmp, "ckpt")
    plan = json.dumps({"kill_at_phase": "host-shard:1", "kill_at_save": 2})
    r = _run_worker(ckpt, 4, os.path.join(tmp, "killed.jsonl"), plan)
    assert r.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), \
        f"worker rc {r.returncode}: {r.stderr[-500:]}"
    assert os.path.exists(os.path.join(ckpt, "global_step2", "COMMIT"))
    assert not os.path.exists(os.path.join(ckpt, "global_step3", "COMMIT"))
    r2 = _run_worker(ckpt, 4, os.path.join(tmp, "resumed.jsonl"))
    assert r2.returncode == 0, r2.stderr[-500:]
    r3 = _run_worker(os.path.join(tmp, "clean"), 4,
                     os.path.join(tmp, "clean.jsonl"))
    assert r3.returncode == 0, r3.stderr[-500:]

    def log_rows(p):
        with open(p) as f:
            return {row["step"]: row for row in map(json.loads, f)}

    resumed = log_rows(os.path.join(tmp, "resumed.jsonl"))
    clean = log_rows(os.path.join(tmp, "clean.jsonl"))
    for step in (3, 4):
        assert resumed[step]["loss"] == clean[step]["loss"], \
            f"step {step}: resumed {resumed[step]} != clean {clean[step]}"
    print("[offload_smoke] SIGKILL mid host-shard flush -> torn tag "
          "uncommitted, resume from step-2 tag bitwise-identical to the "
          "uninterrupted run")


def main() -> int:
    import tempfile

    tmp = tempfile.mkdtemp(prefix="ds_offload_smoke_")
    check_streamed_equals_inline()
    check_quantized_fetch()
    check_chaos_stall_flagged(tmp)
    check_kill_mid_flush(tmp)
    print("offload_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
