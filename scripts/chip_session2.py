#!/usr/bin/env python
"""Second-pass chip session: dispatch-overhead attribution + gas-amortized MFU.

The r4 first-pass grid measured a ~constant +350ms/step vs the r3 numbers at
identical configs (350M: 952 vs ~612ms; 760M: 1329 vs ~950ms) — the signature
of per-dispatch tunnel round-trip latency, not device-side regression. This
session (run AFTER chip_session.py finishes):

  1. measures the raw dispatch RTT directly (tiny jitted op, per-call sync);
  2. re-runs the leading MFU configs with k_steps=8 (engine.train_batches:
     8 COMPLETE optimizer steps scanned in one program): one dispatch per
     8 steps, RTT amortizes 8x, and peak HBM equals the k=1 program
     (the gas=8 fp32 accumulator AOT-OOMs the lead geometries).

Results append to chip_session2_results.json after every row.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "chip_session2_results.json")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))


def _rtt_probe_inner() -> dict:
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.ones((8, 128), jnp.bfloat16)
    f(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    n = 20
    for _ in range(n):
        f(x).block_until_ready()
    sync_ms = (time.perf_counter() - t0) / n * 1e3
    # async chain: if dispatch is truly async these 20 overlap
    t0 = time.perf_counter()
    y = x
    for _ in range(n):
        y = f(y)
    y.block_until_ready()
    chain_ms = (time.perf_counter() - t0) / n * 1e3
    return {"tag": "rtt-probe", "per_call_sync_ms": round(sync_ms, 1),
            "per_call_chained_ms": round(chain_ms, 1)}


def rtt_probe() -> dict:
    """Subprocess wrapper: a TPU client is process-exclusive, so the probe
    must not leave this (long-lived) process holding the device while the
    per-row subprocesses try to open it."""
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--rtt"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    return (json.loads(line) if line else
            {"tag": "rtt-probe", "rc": p.returncode, "stderr": p.stderr[-300:]})


def run_row(spec, timeout=1500):
    tag = f"mfu-k8:{spec['tag']}"
    print(f"[chip2] {tag}...", flush=True)
    try:
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "mfu_sweep.py"),
             "--one", json.dumps(spec)],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
        line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        rec = {"tag": tag, "rc": p.returncode,
               "result": json.loads(line) if line else None}
        if p.returncode != 0:
            rec["stderr"] = p.stderr[-400:]
    except subprocess.TimeoutExpired:
        rec = {"tag": tag, "rc": -1, "error": f"timeout {timeout}s"}
    print(f"[chip2] {tag}: {json.dumps(rec)[:300]}", flush=True)
    return rec


GRID = [
    # NO-CHUNK rows first: session-1 showed chunk-loss programs compile
    # >25min (3 of 4 rows died on compile timeout) while plain rows finish in
    # ~10-15min — bank the completable measurements before gambling on long
    # compiles. bs12 selrm measured 33.4% WITH per-dispatch RTT; k8 shows the
    # device-only number.
    {"model": "gpt2-760m", "micro_bs": 12, "seq": 1024, "remat": True,
     "policy": "save_attn_mlp_out", "k_steps": 8, "steps": 4,
     "tag": "760m-selrm12-k8"},
    # save-dots policies OOM on chip (session 1: 350m rc1 OOM, 760m timeout)
    # — selective-remat is the live 350m candidate
    {"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
     "policy": "save_attn_mlp_out", "k_steps": 8, "steps": 4,
     "tag": "350m-save-sublayer-k8"},
    {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
     "policy": "nothing_saveable", "k_steps": 8, "steps": 4,
     "tag": "760m-full-bs16-k8"},
    # chunk 512 = 4x fewer loss-scan iterations at identical AOT peak
    # (14.74 GB): isolates the chunk-serialization cost; maybe also compiles
    # faster than chunk-128
    {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
     "policy": "save_attn_mlp_out", "loss_chunk": 512, "k_steps": 8, "steps": 4,
     "tag": "760m-selrm16-chunk512-k8"},
    {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
     "policy": "save_attn_mlp_out", "loss_chunk": 128, "k_steps": 8, "steps": 4,
     "tag": "760m-selrm16-chunkloss-k8"},
    {"model": "gpt2-350m", "micro_bs": 2, "seq": 8192, "remat": True,
     "policy": "nothing_saveable", "loss_chunk": 512, "k_steps": 8, "steps": 4,
     "tag": "350m-seq8k-chunkloss-k8"},
]


def main():
    results = []

    def save():
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    print("[chip2] rtt probe...", flush=True)
    try:
        results.append(rtt_probe())
    except Exception as e:  # noqa: BLE001
        results.append({"tag": "rtt-probe", "error": str(e)[:200]})
    print(f"[chip2] {json.dumps(results[-1])}", flush=True)
    save()
    # flash tile autotune first: its winner informs which flash_block_q/k to
    # promote as defaults (dispatch-amortized in-program, ~2min per geom)
    for geom in ("760m", "350m"):
        tag = f"tile:{geom}"
        print(f"[chip2] {tag}...", flush=True)
        try:
            p = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "scripts", "flash_tile_tune.py"),
                 json.dumps({"geom": geom, "iters": 8})],
                capture_output=True, text=True, timeout=1800, cwd=REPO)
            line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                         if ln.startswith("{")), None)
            results.append(json.loads(line) if line else
                           {"tag": tag, "rc": p.returncode,
                            "stderr": p.stderr[-300:]})
        except subprocess.TimeoutExpired:
            results.append({"tag": tag, "error": "timeout 1800s"})
        print(f"[chip2] {tag}: {json.dumps(results[-1])[:300]}", flush=True)
        save()
    for spec in GRID:
        # chunk-loss programs compile long (scanned loss); without a warm
        # cache the 1500s default ate two first-pass rows
        results.append(run_row(spec, timeout=2400))
        save()
    print(f"[chip2] done -> {OUT}")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--rtt":
        print(json.dumps(_rtt_probe_inner()))
    else:
        main()
