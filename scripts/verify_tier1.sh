#!/usr/bin/env bash
# Tier-1 verification gate: runs the ROADMAP.md tier-1 command VERBATIM and
# additionally fails on any pytest collection error — regressions like the
# `from jax import shard_map` import break (which silently dropped 2 test
# files from collection at seed) must be caught pre-merge, not by the next
# round's driver.
#
# Usage: scripts/verify_tier1.sh   (from anywhere; cd's to the repo root)
set -u
cd "$(dirname "$0")/.."

# --- ROADMAP.md "Tier-1 verify" command, verbatim -------------------------
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# --------------------------------------------------------------------------

# Collection errors render as "ERROR tests/<file>.py" in the short summary
# and "N errors" in the tail line; either one fails the gate even when the
# exit code is masked by --continue-on-collection-errors + timeout.
if grep -aqE '^ERROR[[:space:]]+tests/' /tmp/_t1.log; then
    echo "verify_tier1: FAIL — collection errors:" >&2
    grep -aE '^ERROR[[:space:]]+tests/' /tmp/_t1.log >&2
    exit 1
fi
if grep -aqE 'errors? during collection' /tmp/_t1.log; then
    echo "verify_tier1: FAIL — errors during collection" >&2
    exit 1
fi

# A timeout kill (rc 124) is a budget condition, not a collection regression;
# surface it distinctly so the caller can tell the two apart.
if [ "$rc" -eq 124 ]; then
    echo "verify_tier1: suite hit the 870s tier-1 budget (rc=124); no" \
         "collection errors detected in the portion that ran" >&2
fi

# --- static analysis gate (docs/STATIC_ANALYSIS.md) -----------------------
# dslint over the default bench config: traces the engine's fused train
# program (no execution) and exits 2 on ERROR-severity findings — the
# sharding/precision/collective/config regressions that would otherwise
# surface as burned TPU-hours.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m deepspeed_tpu.analysis > /tmp/_t1_dslint.log 2>&1; then
    echo "verify_tier1: FAIL — dslint reported ERROR findings (or crashed):" >&2
    tail -40 /tmp/_t1_dslint.log >&2
    exit 1
fi

# --- pipeline-schedule gate (docs/STATIC_ANALYSIS.md "Pipeline schedules")
# the schedule prover itself: pairing/deadlock/liveness/weight-version
# proofs over the three generators, the four mutation counterexamples
# (each rejected with the exact stage + instruction named), the engine's
# refuse-before-build check, and the AOT pricing join.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_schedule_prover.py -q -m 'not slow' \
        -p no:cacheprovider -p no:randomly > /tmp/_t1_schedule.log 2>&1; then
    echo "verify_tier1: FAIL — schedule prover tests" \
         "(tests/test_schedule_prover.py):" >&2
    tail -30 /tmp/_t1_schedule.log >&2
    exit 1
fi
grep -aE '^[0-9]+ passed' /tmp/_t1_schedule.log || true

# the dslint pipe/* gate: prove the shipped 1F1B/interleaved/zero-bubble
# generators over the schedule matrix and report static bubble % — exits 2
# if any generated schedule is rejected by its own prover.
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
        python -m deepspeed_tpu.analysis --schedules \
        > /tmp/_t1_schedules_cli.log 2>&1; then
    echo "verify_tier1: FAIL — pipeline-schedule prover gate" \
         "(python -m deepspeed_tpu.analysis --schedules):" >&2
    tail -30 /tmp/_t1_schedules_cli.log >&2
    exit 1
fi

# --- overlap gate (docs/COMM_COMPRESSION.md "Overlap & fusion") -----------
# the pipelined quantized-gather scan, bucketed gradient exchange, overlap
# ledger arithmetic, and the collective/unoverlapped-quantized-collective
# rule's fire/stay-silent behavior must stay green even when the full suite
# hits its budget mid-run (the dslint gate above already proves the default
# bench row is clean under the rule).
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_overlap.py -q -m 'not slow' \
        -p no:cacheprovider -p no:randomly > /tmp/_t1_overlap.log 2>&1; then
    echo "verify_tier1: FAIL — overlap tests (tests/test_overlap.py):" >&2
    tail -30 /tmp/_t1_overlap.log >&2
    exit 1
fi
grep -aE '^[0-9]+ passed' /tmp/_t1_overlap.log || true

# --- serving gate (docs/SERVING.md) ---------------------------------------
# the continuous-batching stack must stay green even when the full suite
# hits its budget mid-run: decode-kernel batch regression (the b16 BlockSpec
# crash class), paged allocator/equivalence, scheduler mechanics, and the
# serving dslint rule.
if ! timeout -k 10 480 env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_serving.py tests/test_serving_chaos.py \
        tests/test_paged_kv.py tests/test_fleet.py tests/test_speculation.py \
        tests/test_decode_attention.py tests/test_tp_serving.py \
        tests/test_tenancy.py \
        -q -m 'not slow' \
        -p no:cacheprovider -p no:randomly > /tmp/_t1_serving.log 2>&1; then
    echo "verify_tier1: FAIL — serving/paged-KV tests:" >&2
    tail -30 /tmp/_t1_serving.log >&2
    exit 1
fi
grep -aE '^[0-9]+ passed' /tmp/_t1_serving.log || true

# the CPU-fallback scheduler smoke: admit/evict/finish a mixed-length
# request stream end to end (paged prefill/decode, preemption, eos,
# greedy-equivalence vs generate) — the serving contract in one script.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py > /tmp/_t1_serving_smoke.log 2>&1; then
    echo "verify_tier1: FAIL — serving smoke (scripts/serving_smoke.py):" >&2
    tail -30 /tmp/_t1_serving_smoke.log >&2
    exit 1
fi
grep -a "serving_smoke: PASS" /tmp/_t1_serving_smoke.log || true

# the prefix-caching smoke (docs/SERVING.md "KV quantization & prefix
# caching"): a shared-system-prompt stream through the copy-on-write
# prefix cache — physical pages < sum of logical pages, greedy outputs
# generate-identical, refcount audit clean after the drain.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py --prefix \
        > /tmp/_t1_serving_prefix.log 2>&1; then
    echo "verify_tier1: FAIL — serving prefix-cache smoke" \
         "(scripts/serving_smoke.py --prefix):" >&2
    tail -30 /tmp/_t1_serving_prefix.log >&2
    exit 1
fi
grep -a "serving_smoke\[prefix\]: PASS" /tmp/_t1_serving_prefix.log || true

# the speculative-decoding smoke (docs/SERVING.md "Speculative decoding"):
# both drafters against the real engine — >= 1 full-reject window (n-gram
# on random history) and >= 1 full-accept window (draft == target), greedy
# outputs generate-IDENTICAL under both, page audit clean.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py --spec \
        > /tmp/_t1_serving_spec.log 2>&1; then
    echo "verify_tier1: FAIL — speculative-decoding smoke" \
         "(scripts/serving_smoke.py --spec):" >&2
    tail -30 /tmp/_t1_serving_spec.log >&2
    exit 1
fi
grep -a "serving_smoke\[spec\]: PASS" /tmp/_t1_serving_spec.log || true

# the serving chaos smoke (docs/SERVING.md "Overload & failure"): one
# injected dispatch-failure episode (preempt-and-requeue heal) and one
# deadline expiry against the REAL engine, asserting generate-identical
# outputs and a clean page-conservation audit after each recovery.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py --chaos \
        > /tmp/_t1_serving_chaos.log 2>&1; then
    echo "verify_tier1: FAIL — serving chaos smoke" \
         "(scripts/serving_smoke.py --chaos):" >&2
    tail -30 /tmp/_t1_serving_chaos.log >&2
    exit 1
fi
grep -a "serving_smoke\[chaos\]: PASS" /tmp/_t1_serving_chaos.log || true

# the fleet failover smoke (docs/SERVING.md "Fleet"): two real-engine
# replica PROCESSES behind the router, one SIGKILL'd mid-stream — the
# dead replica's requests must re-route to the survivor with kept tokens,
# finish generate-identical, and leave the survivor's page audit clean.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py --fleet \
        > /tmp/_t1_serving_fleet.log 2>&1; then
    echo "verify_tier1: FAIL — serving fleet smoke" \
         "(scripts/serving_smoke.py --fleet):" >&2
    tail -30 /tmp/_t1_serving_fleet.log >&2
    exit 1
fi
grep -a "serving_smoke\[fleet\]: PASS" /tmp/_t1_serving_fleet.log || true

# the disaggregated prefill/decode smoke (docs/SERVING.md "Tensor parallel
# & disaggregation"): a prefill-specialist and a decode-specialist worker
# process behind the role-aware router — every request prefills on one,
# hands its int8 KV pages off over the wire (ownership transfer), decodes
# on the other, generate-identical, with BOTH pools drained to zero.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py --disagg \
        > /tmp/_t1_serving_disagg.log 2>&1; then
    echo "verify_tier1: FAIL — serving disagg smoke" \
         "(scripts/serving_smoke.py --disagg):" >&2
    tail -30 /tmp/_t1_serving_disagg.log >&2
    exit 1
fi
grep -a "serving_smoke\[disagg\]: PASS" /tmp/_t1_serving_disagg.log || true

# the multi-tenancy smoke (docs/SERVING.md "Multi-tenancy & SLO tiers"):
# a 3-tier mixed-tenant stream with an injected noisy-neighbor batch
# flood — interactive/standard outputs generate-identical, >= 1 full
# brownout enter/exit cycle with every transition page-audited, the flood
# shed with typed verdicts but never fully starved, pools drained.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/serving_smoke.py --tiers \
        > /tmp/_t1_serving_tiers.log 2>&1; then
    echo "verify_tier1: FAIL — serving multi-tenancy smoke" \
         "(scripts/serving_smoke.py --tiers):" >&2
    tail -30 /tmp/_t1_serving_tiers.log >&2
    exit 1
fi
grep -a "serving_smoke\[tiers\]: PASS" /tmp/_t1_serving_tiers.log || true

# --- offload gate (docs/OFFLOAD.md) ---------------------------------------
# the streamed host<->HBM DMA pipeline: streamed-vs-inline bitwise
# equivalence (depths 1/2), quantized-fetch ledger ratio, the
# offload/unstreamed-host-fetch rule, and the nested watchdog phase stack.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_infinity_stream.py -q -m 'not slow' \
        -p no:cacheprovider -p no:randomly > /tmp/_t1_offload.log 2>&1; then
    echo "verify_tier1: FAIL — offload stream tests" \
         "(tests/test_infinity_stream.py):" >&2
    tail -30 /tmp/_t1_offload.log >&2
    exit 1
fi
grep -aE '^[0-9]+ passed' /tmp/_t1_offload.log || true

# the offload smoke: streamed step == inline step bitwise, quantized-fetch
# ledger ratio, an injected DMA hang flagged as an offload_fetch stall, and
# SIGKILL mid host-shard flush -> committed-tag resume, bitwise step-exact.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python scripts/offload_smoke.py > /tmp/_t1_offload_smoke.log 2>&1; then
    echo "verify_tier1: FAIL — offload smoke (scripts/offload_smoke.py):" >&2
    tail -30 /tmp/_t1_offload_smoke.log >&2
    exit 1
fi
grep -a "offload_smoke: PASS" /tmp/_t1_offload_smoke.log || true

# --- elastic gate (docs/RESILIENCE.md "Elastic membership") ---------------
# the deterministic ZeRO reshard: flat-shard repartition properties, cursor
# remap exactness, reshard-on-load through the real engine, the validated
# elasticity block, budget-free membership restarts, and the
# config/elastic-without-reshard-anchor rule.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python -m pytest tests/test_reshard.py -q -m 'not slow' \
        -p no:cacheprovider -p no:randomly > /tmp/_t1_reshard.log 2>&1; then
    echo "verify_tier1: FAIL — reshard tests (tests/test_reshard.py):" >&2
    tail -30 /tmp/_t1_reshard.log >&2
    exit 1
fi
grep -aE '^[0-9]+ passed' /tmp/_t1_reshard.log || true

# the elastic device-loss smoke: SIGKILL one of four dp workers mid-run ->
# the agent relaunches at dp3 from the newest committed tag (budget-free
# membership change), the resharded run is bitwise-identical to a dp3 run
# resumed from the same anchor, and no data sample is dropped or replayed.
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
        python scripts/elastic_smoke.py > /tmp/_t1_elastic.log 2>&1; then
    echo "verify_tier1: FAIL — elastic smoke (scripts/elastic_smoke.py):" >&2
    tail -40 /tmp/_t1_elastic.log >&2
    exit 1
fi
grep -a "elastic_smoke: PASS" /tmp/_t1_elastic.log || true

# --- fault-injection smoke (docs/RESILIENCE.md) ---------------------------
# two heal cycles on the CPU mesh: SIGKILL mid-checkpoint + auto-resume
# (crash consistency), and injected NaN -> divergence rollback -> poisoned
# data-cursor skip -> rejoin (in-run health). Either contract regressing
# must fail the gate, not the next incident in production.
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
        python scripts/chaos_smoke.py > /tmp/_t1_chaos.log 2>&1; then
    echo "verify_tier1: FAIL — fault-injection smoke (kill/NaN heal cycles):" >&2
    tail -40 /tmp/_t1_chaos.log >&2
    exit 1
fi
grep -a "chaos_smoke: PASS" /tmp/_t1_chaos.log || true

# --- silent-data-corruption smoke (docs/RESILIENCE.md "Data integrity") ---
# a REAL bit flip in a cpu-offloaded optimizer shard must be detected and
# healed step-exact (rollback + replay, same final loss), and a flip in a
# prefix-shared KV page must be quarantined with borrowers re-prefilled to
# identical token streams — both on real engines, with clean runs raising
# zero sdc_detected events.
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
        python scripts/chaos_smoke.py --sdc > /tmp/_t1_sdc.log 2>&1; then
    echo "verify_tier1: FAIL — SDC smoke (scripts/chaos_smoke.py --sdc):" >&2
    tail -40 /tmp/_t1_sdc.log >&2
    exit 1
fi
grep -a "chaos_smoke: PASS" /tmp/_t1_sdc.log || true

# --- lint gate (ruff.toml: analysis subsystem + its tests) ----------------
# advisory where the interpreter lacks ruff (this image does not bundle it);
# CI lanes that have it get the real check.
if python -c "import ruff" 2>/dev/null || command -v ruff >/dev/null 2>&1; then
    if ! python -m ruff check deepspeed_tpu/analysis tests/test_analysis.py \
            2>/dev/null && ! ruff check deepspeed_tpu/analysis \
            tests/test_analysis.py; then
        echo "verify_tier1: FAIL — ruff findings in the analysis subsystem" >&2
        exit 1
    fi
else
    echo "verify_tier1: ruff not installed; lint gate skipped" >&2
fi

exit "$rc"
