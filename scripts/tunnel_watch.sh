#!/bin/bash
# Watch for the axon TPU tunnel to come up; run the chip session the moment it does.
# Probes every 240s with a 60s timeout (tunnel-down hangs forever, never errors).
LOG=/root/repo/tunnel_watch.log
DEADLINE=$(( $(date +%s) + 39600 ))   # give up after 11h
echo "[watch] start $(date -u +%FT%TZ)" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 90 python -c "
import sys
import jax, jax.numpy as jnp
d = jax.devices()
# a CPU fallback must NOT count as the tunnel being up (bench.py's probe
# makes the same platform check): chip_session on CPU would burn the window
if d[0].platform == 'cpu':
    print('probe found only CPU devices'); sys.exit(1)
x = jnp.ones((256,256), jnp.bfloat16)
(x@x).block_until_ready()
print('up:', d[0])
" >> "$LOG" 2>&1; then
    echo "[watch] tunnel UP $(date -u +%FT%TZ); running chip_session" >> "$LOG"
    python /root/repo/scripts/chip_session.py >> "$LOG" 2>&1
    echo "[watch] chip_session done rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    exit 0
  fi
  echo "[watch] down $(date -u +%FT%TZ)" >> "$LOG"
  sleep 240
done
echo "[watch] deadline reached, tunnel never recovered" >> "$LOG"
exit 1
