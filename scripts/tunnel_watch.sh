#!/bin/bash
# Watch for the axon TPU tunnel to come up; run the window orchestrator the
# moment it does. Probes every 240s with a 90s timeout (tunnel-down hangs
# forever, never errors).
LOG=/root/repo/tunnel_watch.log
DEADLINE=$(( $(date +%s) + ${WATCH_SECS:-30000} ))
WINDOWS_RUN=0
echo "[watch] start $(date -u +%FT%TZ)" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 90 python -c "
import sys
import jax, jax.numpy as jnp
d = jax.devices()
# a CPU fallback must NOT count as the tunnel being up
if d[0].platform == 'cpu':
    print('probe found only CPU devices'); sys.exit(1)
x = jnp.ones((256,256), jnp.bfloat16)
(x@x).block_until_ready()
print('up:', d[0])
" >> "$LOG" 2>&1; then
    echo "[watch] tunnel UP $(date -u +%FT%TZ); running window_run" >> "$LOG"
    python /root/repo/scripts/window_run.py >> "$LOG" 2>&1
    RC=$?
    echo "[watch] window_run done rc=$RC $(date -u +%FT%TZ)" >> "$LOG"
    # only a SUCCESSFUL run counts toward the exit-0 verdict
    [ "$RC" -eq 0 ] && WINDOWS_RUN=$(( WINDOWS_RUN + 1 ))
    # bank whatever rows exist EVEN on a partial window (the ledger is
    # append-per-row; bench's evidence loader filters per-row rc/platform).
    # Pathspec'd commit: never sweep unrelated staged work, never leave the
    # artifact staged on failure.
    if cp /root/repo/window_run_results.json \
          /root/repo/docs/CHIP_SESSION_r05.json 2>/dev/null; then
      # add is needed for the first (untracked) copy; the pathspec'd commit
      # still only ever commits this one file. A no-change repeat window is
      # an expected no-op, not a failure.
      if (cd /root/repo && git status --porcelain \
            -- docs/CHIP_SESSION_r05.json | grep -q .); then
        if ! (cd /root/repo && git add -- docs/CHIP_SESSION_r05.json \
              && git commit -q \
                 -m "chip session r5: tunnel-window results (auto-committed by watcher)" \
                 -- docs/CHIP_SESSION_r05.json) >> "$LOG" 2>&1; then
          echo "[watch] evidence commit failed (see above)" >> "$LOG"
          (cd /root/repo \
           && git restore --staged docs/CHIP_SESSION_r05.json) >> "$LOG" 2>&1
        fi
      fi
    fi
    # keep watching: a SECOND window later in the session should bank more
    # rows (window_run appends; repeat runs are cache-warm re-measurements)
    sleep 600
    continue
  fi
  echo "[watch] down $(date -u +%FT%TZ)" >> "$LOG"
  sleep 240
done
if [ "$WINDOWS_RUN" -gt 0 ]; then
  echo "[watch] deadline reached after $WINDOWS_RUN window run(s)" >> "$LOG"
  exit 0
fi
echo "[watch] deadline reached, tunnel never recovered" >> "$LOG"
exit 1
