#!/usr/bin/env python
"""Measure peak HBM of bf16 vs int8 generate on the real chip (VERDICT r3 #5:
'measured int8 generate peak HBM < bf16 generate peak HBM').

Each mode runs in a fresh subprocess so memory_stats peaks don't bleed across.
Usage: python scripts/int8_hbm.py [model] (default gpt2-350m)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_one(model: str, quant: bool) -> None:
    import numpy as np

    import jax

    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt

    cfg = gpt.PRESETS[model]
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(
        for_gpt(cfg, params),
        DeepSpeedInferenceConfig(
            dtype="bfloat16", max_out_tokens=256,
            quant={"enabled": quant, "bits": 8, "group_size": 64}))
    # drop every reference to the fp32 init tree (the adapter keeps one) so the
    # generate-phase peak is not dominated by init-phase residency
    eng.model.params = None
    del params
    ids = np.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 128)), np.int32)
    out = eng.generate(ids, max_new_tokens=64)
    assert out.shape == (1, 192)
    stats = jax.local_devices()[0].memory_stats() or {}
    print(json.dumps({
        "model": model, "int8": quant,
        "peak_hbm_gb": round(stats.get("peak_bytes_in_use", 0) / 2**30, 3),
        "in_use_gb": round(stats.get("bytes_in_use", 0) / 2**30, 3),
    }))


def main() -> None:
    model = sys.argv[1] if len(sys.argv) > 1 else "gpt2-350m"
    results = []
    for quant in (False, True):
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", model,
             str(int(quant))],
            capture_output=True, text=True, timeout=1200, cwd=REPO)
        line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        r = json.loads(line) if line else {"int8": quant,
                                           "error": p.stderr[-300:]}
        results.append(r)
        print(json.dumps(r), flush=True)
    if all("peak_hbm_gb" in r for r in results):
        bf16, int8 = results
        print(json.dumps({
            "int8_saves_hbm": int8["peak_hbm_gb"] < bf16["peak_hbm_gb"],
            "bf16_peak_gb": bf16["peak_hbm_gb"],
            "int8_peak_gb": int8["peak_hbm_gb"],
        }))


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--one":
        run_one(sys.argv[2], bool(int(sys.argv[3])))
    else:
        main()
