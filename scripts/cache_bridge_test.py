#!/usr/bin/env python
"""Does a local v5e-topology AOT compile warm the cache for the axon backend?

Compiles the same jitted fn twice with JAX_COMPILATION_CACHE_DIR set:
  --aot   : against topologies.get_topology_desc("tpu", "v5e:2x2") (local, no chip)
  --axon  : against the live axon device, timing the compile

If the second is near-instant after the first, every chip program can be
pre-compiled host-side and tunnel windows become pure measurement.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
# cache even "fast" compiles and log hits/misses
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")


def build(mode: str):
    import jax

    # sitecustomize imports jax before our env vars exist — set explicitly
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    import jax.numpy as jnp

    if mode == "aot":
        os.environ["DS_TPU_ACCELERATOR"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    def f(x, w1, w2):
        def body(carry, ws):
            a, b = ws
            h = jnp.tanh(carry @ a)
            return h @ b, ()

        y, _ = jax.lax.scan(body, x, (w1, w2))
        return (y * jnp.float32(1.000123)).sum()

    g = jax.grad(f, argnums=(1, 2))
    import numpy as np
    x = jnp.zeros((256, 512), jnp.bfloat16)
    w1 = jnp.zeros((6, 512, 512), jnp.bfloat16)
    w2 = jnp.zeros((6, 512, 512), jnp.bfloat16)
    return jax.jit(g), (x, w1, w2)


def main():
    mode = sys.argv[1].lstrip("-")
    import jax

    jit, args = build(mode)
    t0 = time.perf_counter()
    if mode == "aot":
        from jax.experimental import topologies

        td = topologies.get_topology_desc(platform="tpu",
                                          topology_name="v5e:2x2")
        dev = list(td.devices)[:1]
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.sharding import Mesh

        mesh = Mesh(dev, ("d",))
        sh = NamedSharding(mesh, P())
        abstract = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
                    for a in args]
        jit.lower(*abstract).compile()
        print(json.dumps({"mode": mode,
                          "compile_s": round(time.perf_counter() - t0, 2)}))
    else:
        c = jit.lower(*args).compile()
        dt = time.perf_counter() - t0
        print(json.dumps({"mode": mode, "compile_s": round(dt, 2),
                          "platform": jax.devices()[0].platform}))


if __name__ == "__main__":
    main()
