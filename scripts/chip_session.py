#!/usr/bin/env python
"""One-shot on-chip measurement session (run when the axon tunnel is up).

Runs, in order, each in its own subprocess with a timeout so a tunnel drop
costs one config and the partial results survive in chip_session_results.json:
  1. pallas kernel smoke (Mosaic-compiles all 5 kernels)
  2. MFU sweep grid (scripts/mfu_sweep.py, incl. selective-remat policies)
  3. decode p50/p90
  4. Stable-Diffusion DDIM latency
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "chip_session_results.json")


def run(tag, argv, timeout, env=None):
    print(f"[chip_session] {tag}...", flush=True)
    try:
        p = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO, env=env)
        line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                     if ln.strip().startswith("{")), None)
        rec = {"tag": tag, "rc": p.returncode,
               "result": json.loads(line) if line else None}
        if p.returncode != 0:
            rec["stderr"] = p.stderr[-400:]
    except subprocess.TimeoutExpired:
        rec = {"tag": tag, "rc": -1, "error": f"timeout {timeout}s"}
    except Exception as e:  # noqa: BLE001
        rec = {"tag": tag, "rc": -1, "error": str(e)[:200]}
    print(f"[chip_session] {tag}: {json.dumps(rec)[:300]}", flush=True)
    return rec


def main():
    results = []

    def save():
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    results.append(run("kernel-smoke", [
        sys.executable, os.path.join(REPO, "bench.py"), "--worker",
        json.dumps({"kind": "kernels", "name": "pallas-kernel-smoke"})], 900))
    save()
    if results[-1]["rc"] != 0:
        print("[chip_session] chip unusable; stopping")
        return

    # AOT fit-checked against the v5e compiler (bench.py train_aot rows,
    # 2026-07-30): selrm bs16 needs 16.85G and full-remat bs20/24 >17G — both
    # OOM the 15.75G chip and were cut; selrm bs8/bs12 and full bs16 fit.
    sweep_grid = [
        {"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "dots_with_no_batch_dims_saveable", "tag": "350m-save-dots"},
        {"model": "gpt2-350m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "tag": "350m-save-sublayer"},
        {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "tag": "760m-bs16"},
        {"model": "gpt2-760m", "micro_bs": 12, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "tag": "760m-save-sublayer-bs12"},
        # chunked loss (GPTConfig.loss_chunk) removes the fp32 logits buffer:
        # AOT-verified to fit where the unchunked variants OOM — the two
        # strongest 45%-MFU candidates
        {"model": "gpt2-760m", "micro_bs": 16, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "loss_chunk": 128,
         "tag": "760m-selrm16-chunkloss"},
        {"model": "gpt2-760m", "micro_bs": 24, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "loss_chunk": 128,
         "tag": "760m-bs24-chunkloss"},
        # mid-ladder hedges: the two rows above compile at 15.7 of 15.75 GB —
        # if runtime fragmentation OOMs them on device, these (14.5 / 14.1 GB
        # AOT) are the fallback measurements
        {"model": "gpt2-760m", "micro_bs": 14, "seq": 1024, "remat": True,
         "policy": "save_attn_mlp_out", "loss_chunk": 128,
         "tag": "760m-selrm14-chunkloss"},
        {"model": "gpt2-760m", "micro_bs": 20, "seq": 1024, "remat": True,
         "policy": "nothing_saveable", "loss_chunk": 128,
         "tag": "760m-bs20-chunkloss"},
        {"model": "gpt2-760m", "micro_bs": 8, "seq": 1024, "remat": True,
         "policy": "dots_with_no_batch_dims_saveable", "tag": "760m-bs8-save-dots"},
        # long context on ONE chip: streamed flash kernels + chunked loss
        # (AOT: 7.40 GB peak at seq 8192)
        {"model": "gpt2-350m", "micro_bs": 2, "seq": 8192, "remat": True,
         "policy": "nothing_saveable", "loss_chunk": 512,
         "tag": "350m-seq8k-chunkloss"},
    ]
    for spec in sweep_grid:
        results.append(run(f"mfu:{spec['tag']}", [
            sys.executable, os.path.join(REPO, "scripts", "mfu_sweep.py"),
            "--one", json.dumps(spec)], 1500))
        save()

    results.append(run("decode", [
        sys.executable, os.path.join(REPO, "bench.py"), "--worker",
        json.dumps({"kind": "inference", "name": "gpt2-350m-decode",
                    "model": "gpt2-350m", "batch": 1, "prompt": 128,
                    "gen": 64})], 1500))
    save()
    results.append(run("sd-ddim20", [
        sys.executable, os.path.join(REPO, "bench.py"), "--worker",
        json.dumps({"kind": "diffusion", "name": "sd-ddim20", "latent": 32,
                    "ddim_steps": 20})], 1500))
    save()
    results.append(run("int8-hbm", [
        sys.executable, os.path.join(REPO, "scripts", "int8_hbm.py")], 1500))
    save()
    # ZeRO-Infinity param-stream rows last: longest, and must never cost the
    # decode/SD/MFU evidence if the tunnel drops mid-run. Config dicts come
    # from bench.py (single source of truth).
    sys.path.insert(0, REPO)
    from bench import INFINITY_CONFIGS, PIPELINE_CONFIGS

    for spec in PIPELINE_CONFIGS + INFINITY_CONFIGS:
        if spec.get("force_cpu"):
            # AOT compile-only rows need no chip and are already committed
            # evidence (docs/BENCH_fallback_builderrun_r04.json) — a tunnel
            # window is too precious to spend on them
            continue
        results.append(run(f"{spec['kind']}:{spec['name']}", [
            sys.executable, os.path.join(REPO, "bench.py"), "--worker",
            json.dumps(spec)], spec.get("timeout", 3600)))
        save()
    print(f"[chip_session] done -> {OUT}")


if __name__ == "__main__":
    main()
