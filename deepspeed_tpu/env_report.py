"""Environment/compatibility report (the ``ds_report`` CLI).

Parity: reference ``deepspeed/env_report.py`` (``ds_report`` entry in ``bin/``):
versions, device inventory, and per-native-op compatibility probing.
"""

from __future__ import annotations

import importlib
import platform
import sys
from typing import List, Tuple

GREEN_OK = "[OKAY]"
RED_FAIL = "[FAIL]"
YELLOW_NO = "[NO]"


def op_report() -> List[Tuple[str, bool]]:
    from .ops.op_builder import get_builder

    out = []
    for name in ("ds_cpu_ops", "ds_aio"):
        try:
            out.append((name, get_builder(name).is_compatible()))
        except Exception:
            out.append((name, False))
    return out


def main(argv=None) -> int:
    lines = ["-" * 70, "DeepSpeed-TPU C++/native op report", "-" * 70]
    for name, ok in op_report():
        lines.append(f"{name:<24} {GREEN_OK if ok else YELLOW_NO}")
    lines += ["-" * 70, "General environment:", "-" * 70]
    lines.append(f"python                   {sys.version.split()[0]} ({platform.platform()})")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy", "transformers", "torch"):
        try:
            m = importlib.import_module(mod)
            lines.append(f"{mod:<24} {getattr(m, '__version__', '?')}")
        except ImportError:
            lines.append(f"{mod:<24} {YELLOW_NO}")
    from . import __version__

    lines.append(f"deepspeed_tpu            {__version__}")
    try:
        import jax

        devs = jax.devices()
        lines.append(f"devices                  {len(devs)} x {devs[0].device_kind}"
                     if devs else "devices                  none")
        lines.append(f"default backend          {jax.default_backend()}")
        lines.append(f"process count            {jax.process_count()}")
    except Exception as e:
        lines.append(f"devices                  {RED_FAIL} ({e})")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
