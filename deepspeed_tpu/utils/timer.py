"""Wall-clock and throughput timers.

Parity: reference ``utils/timer.py:33`` (``SynchronizedWallClockTimer``) and ``:137``
(``ThroughputTimer``). CUDA events become ``jax.block_until_ready`` fences: on TPU the
only way to get honest wall-clock numbers through async dispatch is to synchronize at
the timer boundary, so ``stop()`` optionally blocks on a supplied array.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax

from .logging import log_dist


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0
        self.count = 0

    def start(self) -> None:
        assert not self.started_, f"timer {self.name} already started"
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, sync_on=None) -> None:
        assert self.started_, f"timer {self.name} not started"
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        self.elapsed_ += time.perf_counter() - self.start_time
        self.count += 1
        self.started_ = False

    def reset(self) -> None:
        self.elapsed_ = 0.0
        self.count = 0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        e = self.elapsed_
        if reset:
            self.reset()
        return e

    def mean(self) -> float:
        return self.elapsed_ / max(self.count, 1)


class SynchronizedWallClockTimer:
    """Named-timer registry. Parity: ``utils/timer.py:33``."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: Optional[List[str]] = None, reset: bool = True, memory_breakdown=False) -> str:
        names = names or list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0
                parts.append(f"{name}: {ms:.2f}ms")
        msg = "time (ms) | " + " | ".join(parts)
        log_dist(msg)
        return msg


class ThroughputTimer:
    """Samples/sec + tokens/sec accounting across steps. Parity: ``utils/timer.py:137``."""

    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: int = 50,
                 monitor_memory: bool = False, logging_fn=None):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.steps_per_output = steps_per_output if steps_per_output else 0
        self.logging = logging_fn or log_dist
        self.epoch_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.started = False
        self.start_time = 0.0

    def update_epoch_count(self) -> None:
        self.epoch_count += 1

    def start(self) -> None:
        self.started = True
        self.start_time = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True, sync_on=None) -> None:
        if not self.started:
            return
        self.started = False
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        duration = time.perf_counter() - self.start_time
        if global_step:
            self.global_step_count += 1
            if self.global_step_count >= self.start_step:
                self.total_elapsed_time += duration
                self.step_elapsed_time += duration
                if (report_speed and self.steps_per_output
                        and self.global_step_count % self.steps_per_output == 0):
                    self.logging(
                        f"epoch={self.epoch_count}/micro_step={self.global_step_count} "
                        f"samples/sec={self.avg_samples_per_sec():.2f}")
                    self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        counted = max(self.global_step_count - self.start_step + 1, 1)
        if self.total_elapsed_time == 0:
            return 0.0
        return self.batch_size / (self.total_elapsed_time / counted)
