"""Rank-filtered logging.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (``log_dist``,
``logger``): a process-wide logger plus helpers that only emit on selected ranks so
multi-host TPU jobs don't produce world_size copies of every line.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

_LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if not lg.handlers:
        lg.setLevel(level)
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(_LOG_FORMAT))
        lg.addHandler(handler)
        lg.propagate = False
    return lg


logger = _create_logger()


def _process_index() -> int:
    # Lazy: jax.process_index() requires jax to be initialised; fall back to env.
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0 only).

    ``ranks=[-1]`` logs on every process.
    """
    ranks = list(ranks) if ranks is not None else [0]
    me = _process_index()
    if -1 in ranks or me in ranks:
        logger.log(level, message)


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
