"""Version-tolerant imports for JAX APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a top-level
``jax.shard_map`` export (and renamed its ``check_rep`` kwarg to
``check_vma``). The repo targets both eras: import ``shard_map`` from here,
never from ``jax`` directly — a bare ``from jax import shard_map`` kills
module import (and pytest collection) on the older runtime this image ships.
"""

from __future__ import annotations

try:  # newer jax: top-level export, kwarg named check_vma
    from jax import shard_map as shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental module, kwarg named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        if f is None:  # decorator-style usage
            return lambda g: _shard_map_legacy(
                g, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
        return _shard_map_legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


try:  # probe resolved once: manual_axis_names runs on the hot tracing path
    from jax._src.core import unsafe_get_axis_names as _get_axis_names
except Exception:  # newer jax dropped the API (and no-ops the constraint)
    _get_axis_names = None

_EMPTY = frozenset()


def manual_axis_names() -> frozenset:
    """Mesh axis names currently bound manually (i.e. we are tracing inside a
    ``shard_map``/``pmap`` body). Older jax rejects ``with_sharding_constraint``
    over such axes at lowering time — callers use this to skip the constraint.
    Newer jax treats those constraints as no-ops and also dropped the probe API,
    so an empty set is the correct degradation."""
    if _get_axis_names is None:
        return _EMPTY
    try:
        names = _get_axis_names()
        return frozenset(names) if names else _EMPTY
    except Exception:
        return _EMPTY


__all__ = ["shard_map", "manual_axis_names"]
