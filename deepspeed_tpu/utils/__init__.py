from .logging import log_dist, logger, warning_once
from .timer import SynchronizedWallClockTimer, ThroughputTimer

__all__ = ["logger", "log_dist", "warning_once",
           "SynchronizedWallClockTimer", "ThroughputTimer"]
