"""Consolidate a checkpoint into a single fp32 state dict.

Capability parity with the reference's ``utils/zero_to_fp32.py`` (auto-copied
next to every checkpoint, ``runtime/engine.py:3388``): recover full fp32 weights
from a training checkpoint without constructing the model or the training
topology. The reference must merge per-rank ZeRO shards; this framework's
checkpoint format already stores every leaf as its full logical array
(SURVEY §5 "universal checkpoint" is the native format), so consolidation is
extraction: prefer the fp32 master copy when present, else cast params.

CLI:  python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output.npz>
where <checkpoint_dir> is either the run directory (uses ``latest``) or a tag
directory.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

import msgpack
import numpy as np


def _load_leaves(state_dir: str) -> Dict[str, np.ndarray]:
    with open(os.path.join(state_dir, "state.msgpack"), "rb") as f:
        meta = msgpack.unpackb(f.read())
    out = {}
    for m in meta["leaves"]:
        arr = np.load(os.path.join(state_dir, "arrays", f"{m['index']}.npy"))
        if m.get("raw_view"):
            import ml_dtypes

            arr = arr.view(np.dtype(getattr(ml_dtypes, m["dtype"], m["dtype"])))
        out[m["key"]] = arr
    return out


def _resolve_tag_dir(path: str) -> str:
    if os.path.exists(os.path.join(path, "state")):
        return path
    latest = os.path.join(path, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            return os.path.join(path, f.read().strip())
    raise FileNotFoundError(f"{path} is neither a tag dir nor has a 'latest' file")


def _load_host_masters(ckpt: str):
    """Flat ``master_{i}`` dict + shard meta from the host-offload state:
    the sharded ``host_state/`` layout (docs/OFFLOAD.md — per-unit atomic
    ``shard_<k>.npz`` + ``host_meta.json``) or the legacy/NVMe consolidated
    ``host_optimizer.npz``. Standalone: numpy + json only."""
    host_dir = os.path.join(ckpt, "host_state")
    meta_path = os.path.join(host_dir, "host_meta.json")
    if os.path.isdir(host_dir) and os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        out: Dict[str, np.ndarray] = {}
        for shard in meta.get("shards", []):
            with np.load(os.path.join(host_dir, shard["file"])) as d:
                for key in d.files:
                    out[key] = d[key]
        return out, meta
    host_path = os.path.join(ckpt, "host_optimizer.npz")
    if os.path.exists(host_path):
        with np.load(host_path) as d:
            return {k: d[k] for k in d.files}, {}
    return {}, {}


def get_fp32_state_dict_from_zero_checkpoint(
        checkpoint_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Parity: the reference function of the same name (``zero_to_fp32.py``)."""
    if tag is not None:
        ckpt = os.path.join(checkpoint_dir, tag)
    else:
        ckpt = _resolve_tag_dir(checkpoint_dir)
    leaves = _load_leaves(os.path.join(ckpt, "state"))
    masters = {k[len("master/"):]: v for k, v in leaves.items()
               if k.startswith("master/")}
    params = {k[len("params/"):]: v for k, v in leaves.items()
              if k.startswith("params/")}
    # ZeRO-Offload/Infinity: fp32 masters live in the host state (host_state/
    # shards or legacy host_optimizer.npz), positionally keyed master_{i} in
    # the params tree's flatten order (_load_leaves preserves it)
    host, host_meta = ({}, {}) if masters else _load_host_masters(ckpt)
    if not masters and host:
        if not params and host_meta.get("leaves"):
            # ZeRO-Infinity param stream: the device tree is EMPTY — the
            # weights exist ONLY as host masters. The shard meta names every
            # leaf (unit, name), so recovery keys them `unit/name`.
            return {f"{lf['unit']}/{lf['name']}":
                    np.asarray(host[f"master_{lf['i']}"], np.float32)
                    for lf in host_meta["leaves"]}
        for i, key in enumerate(params):
            mkey = f"master_{i}"
            if mkey in host:
                masters[key] = host[mkey].reshape(params[key].shape)
    out = {}
    for key, arr in params.items():
        src = masters.get(key, arr)
        out[key] = np.asarray(src, np.float32) if src.dtype != np.float32 else src
    if not out:
        raise ValueError(f"no params found in {ckpt}")
    return out


def convert_zero_checkpoint_to_fp32_state_dict(
        checkpoint_dir: str, output_file: str, tag: Optional[str] = None) -> None:
    """Parity: the reference CLI behavior — writes a consolidated fp32 file."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(int(v.size) for v in sd.values())
    print(f"saved {len(sd)} tensors ({total / 1e6:.1f}M params, fp32) "
          f"to {output_file}")


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print(__doc__)
        return 1
    convert_zero_checkpoint_to_fp32_state_dict(argv[0], argv[1])
    return 0


if __name__ == "__main__":
    sys.exit(main())
