"""Attention ops.

Capability parity with the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu``, ``csrc/transformer/inference/csrc/softmax.cu``):
on TPU the fused path is (a) XLA's automatic fusion of the QK^T -> masked softmax -> V
chain for moderate sequence lengths, and (b) a Pallas flash-attention kernel
(:mod:`deepspeed_tpu.ops.pallas.flash_attention`) for long sequences where
materializing the [T, T] score matrix would blow HBM. This module is the dispatch
point; models call :func:`multihead_attention` and never pick a kernel themselves.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def causal_mask(q_len: int, kv_len: int, dtype=jnp.float32) -> jnp.ndarray:
    i = jnp.arange(q_len)[:, None] + (kv_len - q_len)
    j = jnp.arange(kv_len)[None, :]
    return (j <= i)  # [q, kv] bool


def dot_product_attention(
    q: jnp.ndarray,  # [B, T, H, Dh]
    k: jnp.ndarray,  # [B, S, H, Dh]
    v: jnp.ndarray,  # [B, S, H, Dh]
    causal: bool = True,
    bias: Optional[jnp.ndarray] = None,
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Reference (XLA-fused) attention. fp32 softmax accumulation regardless of the
    input dtype — same numerics stance as the reference's fused softmax kernels."""
    *_, q_len, _, head_dim = q.shape
    kv_len = k.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        mask = causal_mask(q_len, kv_len)
        logits = jnp.where(mask[None, None, :, :], logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)
    return out


def multihead_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    bias: Optional[jnp.ndarray] = None,
    use_flash: Optional[bool] = None,
    softmax_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    stochastic_mode: bool = False,
) -> jnp.ndarray:
    """Kernel dispatch: Pallas flash attention on TPU when eligible, XLA
    otherwise. ``block_q``/``block_k`` tune the flash tiling (autotunable);
    ``stochastic_mode`` is the speed-over-bit-exactness kernel flag (bf16
    MXU operands, fp32 accumulation — see ops/pallas/flash_attention.py)."""
    if use_flash is None:
        use_flash = _flash_eligible(q, k, bias)
    elif use_flash and bias is not None:
        # the flash kernel has no bias input (same reason the decode path
        # guards ALiBi); computing without it would be silently wrong
        from ..utils.logging import warning_once

        warning_once("flash attention forced on but an attention bias is "
                     "present (ALiBi?); falling back to XLA attention")
        use_flash = False
    if use_flash:
        try:
            from .pallas.flash_attention import flash_attention
        except ImportError:
            from ..utils.logging import warning_once

            warning_once("pallas flash attention unavailable; using XLA attention")
        else:
            fa = functools.partial(
                flash_attention, causal=causal, softmax_scale=softmax_scale,
                block_q=block_q, block_k=block_k,
                stochastic_mode=stochastic_mode)
            return _shard_mapped_kernel(fa, q, k, v)
    return dot_product_attention(q, k, v, causal=causal, bias=bias,
                                 softmax_scale=softmax_scale)


def _bound_mesh():
    """The mesh governing the current trace (None outside any mesh context)."""
    from ..runtime.topology import bound_mesh

    return bound_mesh()


def _shard_mapped_kernel(fa, q, k, v):
    """Run a Pallas attention kernel under multi-device SPMD.

    Mosaic custom calls cannot be auto-partitioned by GSPMD (XLA raises
    "wrap the call in a shard_map") — a plain call inside a jit over a >1
    device mesh would crash on real hardware. Attention is embarrassingly
    parallel over batch and heads, so when a mesh is bound we shard_map over
    the data-parallel batch axes and the tp head axis; each shard runs the
    kernel on its local [B/dp, T, H/tp, D] block. Sequence stays unsharded
    here — sp>1 routes to ring/Ulysses before kernel dispatch
    (models/gpt._attention_delta)."""
    mesh = _bound_mesh()
    if mesh is None:
        return fa(q, k, v)
    names = set(mesh.axis_names)
    batch_axes = tuple(a for a in ("dp", "ep") if a in names
                       and mesh.shape[a] > 1)
    head_axis = "tp" if "tp" in names and mesh.shape["tp"] > 1 else None
    if not batch_axes and head_axis is None:
        return fa(q, k, v)
    bsz = int(np.prod([mesh.shape[a] for a in batch_axes])) if batch_axes else 1
    hsz = mesh.shape[head_axis] if head_axis else 1
    B, H = q.shape[0], q.shape[2]
    if B % bsz or H % hsz:
        raise ValueError(
            f"flash attention under SPMD needs batch {B} divisible by "
            f"{batch_axes}={bsz} and heads {H} by tp={hsz}")
    from ..utils.jax_compat import shard_map

    spec = jax.sharding.PartitionSpec(
        batch_axes or None, None, head_axis, None)
    return shard_map(fa, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def _flash_eligible(q, k, bias) -> bool:
    if bias is not None:
        return False
    if jax.default_backend() not in ("tpu",):
        return False
    # block tiling needs 128-divisible sequence lengths; any head_dim works
    # (lanes are padded), but tiny dims aren't worth the kernel
    return q.shape[-1] >= 64 and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
