"""Group-wise quantization ops.

Capability parity with the reference's quantization kernels
(``csrc/quantization/fake_quantizer.cu`` QAT fake-quant used by MoQ,
``quantize.cu``/``pt_binding.cpp`` groupwise int8 quant/dequant used by int8
inference, wrapped by ``ops/quantizer/``): symmetric group-wise quantization to
``bits`` with fp32 scales, plus a straight-through-estimator fake-quant for
quantization-aware training.

TPU-native: these are pure XLA element-wise ops (reduce-max per group, scale,
round, clamp) — they fuse into the surrounding program; no custom kernel is
needed for the quality path. Storage quantization (int8 weights at rest for
inference) uses the same math with the int8 array actually materialized.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _group(x: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    n = x.size
    if n % num_groups != 0:
        raise ValueError(f"size {n} not divisible into {num_groups} groups")
    return x.reshape(num_groups, n // num_groups)


def quantize(x: jnp.ndarray, bits: int = 8, num_groups: int = 1
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric group-wise quantization.

    Returns ``(q, scales)`` where ``q`` is int8 (any bits <= 8 stored as int8)
    of ``x.shape`` and ``scales`` is ``[num_groups]`` fp32.
    """
    g = _group(x.astype(jnp.float32), num_groups)
    qmax = 2.0 ** (bits - 1) - 1.0
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
    scales = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(g / scales), -qmax - 1, qmax).astype(jnp.int8)
    return q.reshape(x.shape), scales[:, 0]


def dequantize(q: jnp.ndarray, scales: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    num_groups = scales.shape[0]
    g = _group(q.astype(jnp.float32), num_groups)
    return (g * scales[:, None]).reshape(q.shape).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fake_quant_dynamic(x: jnp.ndarray, bits: jnp.ndarray,
                       num_groups: int = 1) -> jnp.ndarray:
    """Fake-quant with a TRACED bit count (scalar, or ``[L]`` matching ``x``'s
    leading dim for per-layer schedules). Powers MoQ's progressive bit
    annealing (parity: ``runtime/quantize.py:76`` ``quantize_highbit`` with
    the step-scheduled ``start_bits`` countdown): because the bit width is
    ordinary arithmetic on the scale/clip bounds, the entire anneal runs
    inside ONE compiled program — no recompile per precision change.
    Straight-through gradient to ``x``."""
    xf = x.astype(jnp.float32)
    per_layer = getattr(bits, "ndim", 0) == 1
    if per_layer:
        L = x.shape[0]
        g = xf.reshape(L, num_groups, -1)
        b = bits.reshape(L, 1, 1).astype(jnp.float32)
    else:
        g = _group(xf, num_groups)
        b = jnp.asarray(bits, jnp.float32)
    qmax = 2.0 ** (b - 1.0) - 1.0
    absmax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scales = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(g / scales), -qmax - 1.0, qmax)
    return (q * scales).reshape(x.shape).astype(x.dtype)


def _fqd_fwd(x, bits, num_groups):
    return fake_quant_dynamic(x, bits, num_groups), None


def _fqd_bwd(num_groups, _, g):
    return g, None  # straight-through to x; bits get no gradient


fake_quant_dynamic.defvjp(_fqd_fwd, _fqd_bwd)


def annealed_bits(step, start_bits: int, target_bits: int, period: int,
                  factor=1.0):
    """Scheduled bit width at ``step`` (steps since quantization onset).

    Drop k (1-based) fires at ``period * (2*factor)**(k-1)`` — each drop
    doubles the period, stretched by the eigenvalue ``factor`` (parity:
    ``runtime/quantize.py:138-143``: ``q_period <<= 1; q_period *= factor;
    start_bits -= 1``). ``step`` and ``factor`` may be traced (factor ``[L]``
    for per-layer schedules); the result broadcasts accordingly."""
    if target_bits >= start_bits:
        return jnp.asarray(float(start_bits))
    t = jnp.asarray(step, jnp.float32)
    f = jnp.asarray(factor, jnp.float32)
    safe_t = jnp.maximum(t, 1.0)
    drops = jnp.where(
        t >= period,
        1.0 + jnp.floor(jnp.log(safe_t / period) / jnp.log(2.0 * f)),
        0.0)
    return jnp.maximum(float(target_bits), start_bits - drops)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def fake_quant(x: jnp.ndarray, bits: int = 8, num_groups: int = 1) -> jnp.ndarray:
    """Quantize-dequantize with a straight-through gradient (QAT).

    Parity: ``fake_quantizer.cu`` (MoQ's in-training quantizer).
    """
    q, scales = quantize(x, bits=bits, num_groups=num_groups)
    return dequantize(q, scales, dtype=x.dtype)


def _fq_fwd(x, bits, num_groups):
    return fake_quant(x, bits, num_groups), None


def _fq_bwd(bits, num_groups, _, g):
    return (g,)  # straight-through


fake_quant.defvjp(_fq_fwd, _fq_bwd)
