"""Group-wise quantization ops.

Capability parity with the reference's quantization kernels
(``csrc/quantization/fake_quantizer.cu`` QAT fake-quant used by MoQ,
``quantize.cu``/``pt_binding.cpp`` groupwise int8 quant/dequant used by int8
inference, wrapped by ``ops/quantizer/``): symmetric group-wise quantization to
``bits`` with fp32 scales, plus a straight-through-estimator fake-quant for
quantization-aware training.

TPU-native: these are pure XLA element-wise ops (reduce-max per group, scale,
round, clamp) — they fuse into the surrounding program; no custom kernel is
needed for the quality path. Storage quantization (int8 weights at rest for
inference) uses the same math with the int8 array actually materialized.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _group(x: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    n = x.size
    if n % num_groups != 0:
        raise ValueError(f"size {n} not divisible into {num_groups} groups")
    return x.reshape(num_groups, n // num_groups)


def quantize(x: jnp.ndarray, bits: int = 8, num_groups: int = 1
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric group-wise quantization.

    Returns ``(q, scales)`` where ``q`` is int8 (any bits <= 8 stored as int8)
    of ``x.shape`` and ``scales`` is ``[num_groups]`` fp32.
    """
    g = _group(x.astype(jnp.float32), num_groups)
    qmax = 2.0 ** (bits - 1) - 1.0
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
    scales = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(g / scales), -qmax - 1, qmax).astype(jnp.int8)
    return q.reshape(x.shape), scales[:, 0]


def dequantize(q: jnp.ndarray, scales: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    num_groups = scales.shape[0]
    g = _group(q.astype(jnp.float32), num_groups)
    return (g * scales[:, None]).reshape(q.shape).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def fake_quant(x: jnp.ndarray, bits: int = 8, num_groups: int = 1) -> jnp.ndarray:
    """Quantize-dequantize with a straight-through gradient (QAT).

    Parity: ``fake_quantizer.cu`` (MoQ's in-training quantizer).
    """
    q, scales = quantize(x, bits=bits, num_groups=num_groups)
    return dequantize(q, scales, dtype=x.dtype)


def _fq_fwd(x, bits, num_groups):
    return fake_quant(x, bits, num_groups), None


def _fq_bwd(bits, num_groups, _, g):
    return (g,)  # straight-through


fake_quant.defvjp(_fq_fwd, _fq_bwd)
