from .quantize import (  # noqa: F401
    annealed_bits,
    dequantize,
    fake_quant,
    fake_quant_dynamic,
    quantize,
)
