from .quantize import (  # noqa: F401
    dequantize,
    fake_quant,
    quantize,
)
