"""Single-token decode attention over a KV cache, in Pallas.

Capability parity with the reference's fused decode kernels — the
``softmax_context`` KV-cache attention (``csrc/transformer/inference/csrc/
softmax.cu`` + ``pt_binding.cpp`` attention bindings, workspace layout
``inference_context.h``): one new query token attends over the cache with a
validity mask, in one kernel, without materializing [B, H, S] probabilities in
HBM.

Layout is [B, H, S, Dh] — sequence in the sublane dimension, head_dim in the
lane dimension — so every block the kernel touches is Mosaic-tileable: K/V
stream as (block_k, Dh) tiles (block_k a multiple of the sublane tile, Dh the
full lane extent) and the q/out blocks are full-dim (1, Dh) slices. The head
and batch axes are size-1 leading block dims selected by the grid index map.

Grid = (B, H, S/block_k): the cache's sequence dimension is a GRID axis, so each
program instance holds only one [block_k, Dh] K/V tile in VMEM — long contexts
stream tile by tile (TPU iterates the innermost grid dimension sequentially on
one core, so the online-softmax state lives in VMEM scratch across tiles). The
current cache length arrives as a scalar array input (the analog of the
reference's ``current_tokens`` workspace field) — one compiled kernel serves
every decode step of a generation; tiles entirely past the valid length
contribute nothing (their rows mask to -inf).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   *, sm_scale: float, block_k: int, num_blocks: int):
    ki = pl.program_id(2)
    cur = len_ref[0, 0]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(ki * block_k < cur)  # tiles wholly past the valid length: no work
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [1, Dh]
        k = k_ref[0, 0].astype(jnp.float32)  # [Bk, Dh]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [1, Bk]
        s_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        s = jnp.where(s_pos < cur, s, NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(p, v)

    @pl.when(ki == num_blocks - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, 1, H, Dh] — the new token's query
    k_cache: jnp.ndarray,  # [B, H, S, Dh]
    v_cache: jnp.ndarray,
    cur_len: jnp.ndarray,  # scalar int32: valid cache entries INCLUDING the new token
    softmax_scale: Optional[float] = None,
    block_k: int = 512,
) -> jnp.ndarray:
    """Returns [B, 1, H, Dh]. The new token's k/v must already be in the cache."""
    B, one, H, Dh = q.shape
    assert one == 1
    S = k_cache.shape[2]
    # largest power-of-two tile that divides S (engines should pad the cache to
    # a 128-multiple so tiles stay sublane-aligned)
    block_k = min(block_k, S)
    while block_k > 1 and S % block_k:
        block_k //= 2
    num_blocks = S // block_k
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(Dh)
    lens = jnp.broadcast_to(jnp.asarray(cur_len, jnp.int32), (1, 1))
    qh = q.transpose(0, 2, 1, 3)  # [B, H, 1, Dh] — heads lead, like the cache

    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=scale, block_k=block_k,
                          num_blocks=num_blocks),
        grid=(B, H, num_blocks),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, h, ki: (0, 0)),
            pl.BlockSpec((1, 1, 1, Dh), lambda b, h, ki: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, Dh), lambda b, h, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, Dh), lambda b, h, ki: (b, h, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, Dh), lambda b, h, ki: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, 1, Dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, Dh), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(lens, qh, k_cache, v_cache)
    return out.transpose(0, 2, 1, 3)  # back to [B, 1, H, Dh]
