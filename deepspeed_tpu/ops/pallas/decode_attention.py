"""Single-token decode attention over a KV cache, in Pallas.

Capability parity with the reference's fused decode kernels — the
``softmax_context`` KV-cache attention (``csrc/transformer/inference/csrc/
softmax.cu`` + ``pt_binding.cpp`` attention bindings, workspace layout
``inference_context.h``): one new query token attends over the cache with a
validity mask, in one kernel, without materializing [B, H, S] probabilities in
HBM.

Grid = (B, H): each program streams its head's cache [S, Dh] through VMEM in
blocks with an online softmax. The current cache length arrives as a scalar
array input (the analog of the reference's ``current_tokens`` workspace field) —
the compiled kernel serves every decode step of a generation, whatever the
length.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import NEG_INF, _interpret


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                   block_k: int):
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [1, Dh] row-block
    cur = len_ref[0, 0]

    Dh = q.shape[-1]
    acc = jnp.zeros((1, Dh), jnp.float32)
    m_i = jnp.full((1, 1), NEG_INF, jnp.float32)
    l_i = jnp.zeros((1, 1), jnp.float32)
    num_blocks = (cur + block_k - 1) // block_k

    def body(ki, carry):
        acc, m_i, l_i = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), 0, :].astype(jnp.float32)  # [Bk, Dh]
        v = v_ref[0, pl.ds(ki * block_k, block_k), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [1, Bk]
        s_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        s = jnp.where(s_pos < cur, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot(p, v)
        return acc, m_new, l_new

    acc, m_i, l_i = jax.lax.fori_loop(0, num_blocks, body, (acc, m_i, l_i))
    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, 1, H, Dh] — the new token's query
    k_cache: jnp.ndarray,  # [B, S, H, Dh]
    v_cache: jnp.ndarray,
    cur_len: jnp.ndarray,  # scalar int32: valid cache entries INCLUDING the new token
    softmax_scale: Optional[float] = None,
    block_k: int = 256,
) -> jnp.ndarray:
    """Returns [B, 1, H, Dh]. The new token's k/v must already be in the cache."""
    B, one, H, Dh = q.shape
    assert one == 1
    S = k_cache.shape[1]
    # largest power-of-two block that divides S (any S works; engines should pad
    # the cache to a 128-multiple so the loop runs on full-lane blocks)
    block_k = min(block_k, S)
    while block_k > 1 and S % block_k:
        block_k //= 2
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(Dh)
    lens = jnp.broadcast_to(jnp.asarray(cur_len, jnp.int32), (1, 1))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=scale, block_k=block_k),
        grid=(B, H),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, h: (0, 0)),
            pl.BlockSpec((1, 1, 1, Dh), lambda b, h: (b, 0, h, 0)),
            pl.BlockSpec((1, S, 1, Dh), lambda b, h: (b, 0, h, 0)),
            pl.BlockSpec((1, S, 1, Dh), lambda b, h: (b, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, Dh), lambda b, h: (b, 0, h, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1, H, Dh), q.dtype),
        interpret=_interpret(),
    )(lens, q, k_cache, v_cache)
    return out
