"""Single-token decode attention over a KV cache, in Pallas.

Capability parity with the reference's fused decode kernels — the
``softmax_context`` KV-cache attention (``csrc/transformer/inference/csrc/
softmax.cu`` + ``pt_binding.cpp`` attention bindings, workspace layout
``inference_context.h``): one new query token attends over the cache with a
validity mask, in one kernel, without materializing [B, H, S] probabilities in
HBM.

Two cache layouts share the kernel body:

- **Contiguous** (:func:`decode_attention`): K/V are [B, H, S, Dh] — sequence
  in the sublane dimension, head_dim in the lane dimension — so every block
  the kernel touches is Mosaic-tileable: K/V stream as (block_k, Dh) tiles
  and the q/out blocks are full-dim (1, Dh) slices.
- **Paged** (:func:`paged_decode_attention`): K/V live in a shared page pool
  [H, P, page_size, Dh]; each request owns a *block table* row naming its
  pages in order. The grid's innermost axis walks the table and the K/V
  ``index_map`` reads the page id from the scalar-prefetched table — the
  gather happens in the BlockSpec, so the kernel body is identical to the
  contiguous case with ``block_k = page_size``.

Grid = (B, H, S/block_k): the cache's sequence dimension is a GRID axis, so
each program instance holds only one [block_k, Dh] K/V tile in VMEM — long
contexts stream tile by tile (TPU iterates the innermost grid dimension
sequentially on one core, so the online-softmax state lives in VMEM scratch
across tiles).

Per-request valid lengths ride scalar prefetch
(``pltpu.PrefetchScalarGridSpec``), NOT a VMEM operand. The previous revision
fed the length as a (1, 1) float-tiled VMEM array with no memory space and a
q ``index_map`` that disagreed with the transposed [B, H, 1, Dh] layout —
Mosaic rejected the block-shape/array-shape/index_map triple once the batch
grid axis was wide enough to matter (b16 decode, ``BENCH_r02.json``:
"Blocked(1), Blocked(1), Blocked(1), Blocked(64) ... in memory space None").
Scalar prefetch puts lengths (and the paged block tables) in SMEM where the
index maps and ``@pl.when`` guards can consume them, which is also exactly
what continuous batching needs: every batch row decodes at its OWN length.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   *, sm_scale: float, block_k: int, num_blocks: int):
    """One (batch row, head, K/V tile) step of the online softmax.

    ``len_ref`` is the scalar-prefetched [B] lengths vector in SMEM; the
    paged and contiguous callers share this body (they differ only in how
    the k/v BlockSpecs address the tile)."""
    b = pl.program_id(0)
    ki = pl.program_id(2)
    cur = len_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(ki * block_k < cur)  # tiles wholly past the valid length: no work
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [1, Dh]
        k = k_ref[0, 0].astype(jnp.float32)  # [Bk, Dh]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [1, Bk]
        s_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        s = jnp.where(s_pos < cur, s, NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(p, v)

    @pl.when(ki == num_blocks - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _as_lengths(cur_len, batch: int) -> jnp.ndarray:
    """Accept the legacy scalar (one length for the whole batch) or a [B]
    per-request vector (continuous batching: every slot at its own length)."""
    lens = jnp.asarray(cur_len, jnp.int32)
    if lens.ndim == 0:
        return jnp.broadcast_to(lens, (batch,))
    if lens.shape != (batch,):
        raise ValueError(f"cur_len must be a scalar or [batch]={batch} vector, "
                         f"got shape {lens.shape}")
    return lens


def decode_attention(
    q: jnp.ndarray,  # [B, 1, H, Dh] — the new token's query
    k_cache: jnp.ndarray,  # [B, H, S, Dh]
    v_cache: jnp.ndarray,
    cur_len: jnp.ndarray,  # int32 scalar or [B]: valid entries INCLUDING the new token
    softmax_scale: Optional[float] = None,
    block_k: int = 512,
) -> jnp.ndarray:
    """Returns [B, 1, H, Dh]. The new token's k/v must already be in the cache."""
    B, one, H, Dh = q.shape
    assert one == 1
    S = k_cache.shape[2]
    # largest power-of-two tile that divides S (engines should pad the cache to
    # a 128-multiple so tiles stay sublane-aligned)
    block_k = min(block_k, S)
    while block_k > 1 and S % block_k:
        block_k //= 2
    num_blocks = S // block_k
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(Dh)
    lens = _as_lengths(cur_len, B)
    qh = q.transpose(0, 2, 1, 3)  # [B, H, 1, Dh] — heads lead, like the cache

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # lens -> SMEM, readable by index maps + body
        grid=(B, H, num_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, 1, Dh), lambda b, h, ki, lens: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, Dh),
                         lambda b, h, ki, lens: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, Dh),
                         lambda b, h, ki, lens: (b, h, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, Dh), lambda b, h, ki, lens: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, Dh), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=scale, block_k=block_k,
                          num_blocks=num_blocks),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, Dh), q.dtype),
        interpret=_interpret(),
    )(lens, qh, k_cache, v_cache)
    return out.transpose(0, 2, 1, 3)  # back to [B, 1, H, Dh]


# ------------------------------------------------------------------ paged path
def unpack_kv_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Two int4 values per int8 byte, half-split along the last dim — THE
    ``int8_matmul.pack_int4`` layout (one canonical nibble format for
    weights and KV; delegating keeps them from ever desynchronizing).
    Float output, shared by the kernel body and the XLA fallback so both
    dequantize bit-identically."""
    from .int8_matmul import unpack_int4

    return unpack_int4(packed).astype(jnp.float32)


def paged_decode_attention(
    q: jnp.ndarray,           # [B, 1, H, Dh]
    k_pages: jnp.ndarray,     # [H, P, page_size, Dh] — shared page pool
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,     # [B] int32: valid tokens INCLUDING the new one
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32 page ids (pad: 0)
    softmax_scale: Optional[float] = None,
    impl: Optional[str] = None,  # None=auto | "kernel" | "gather"
    k_scales: Optional[jnp.ndarray] = None,  # [H, P] f32: per-page scales
    v_scales: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Decode attention reading K/V through a block table.

    Each request's cache is a list of fixed-size pages scattered through the
    pool; the kernel's innermost grid axis walks ``block_tables[b]`` and the
    K/V ``index_map`` resolves the page id from SMEM — HBM traffic is exactly
    the pages the request owns, regardless of pool fragmentation. Table slots
    past a request's length must hold a VALID page id (the allocator reserves
    page 0 as that sink); their tiles are masked, never read into the sum.

    **Quantized pools**: pass ``k_scales``/``v_scales`` ([H, P] fp32, one
    symmetric scale per head x page) and int8 pools — either plain int8
    ([..., Dh]) or nibble-packed int4 ([..., Dh // 2], the
    :func:`unpack_kv_int4` layout). Scales ride scalar prefetch next to the
    block tables, and each K/V tile dequantizes inside the online-softmax
    body on its way out of VMEM — HBM moves 2x (int8) or 4x (int4) fewer
    cache bytes than bf16 and no dequantized copy of the pool ever exists.

    ``impl``: "kernel" forces the Pallas path (Mosaic on TPU, interpret
    elsewhere), "gather" the XLA fallback; auto follows the backend like the
    other Pallas ops. The fallback dequantizes the same payload with the
    same arithmetic, so kernel vs fallback agree to fp tolerance.
    """
    B, one, H, Dh = q.shape
    assert one == 1
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    quantized = k_scales is not None
    packed = quantized and k_pages.shape[-1] * 2 == Dh
    if quantized and not packed and k_pages.shape[-1] != Dh:
        raise ValueError(
            f"quantized pool last dim {k_pages.shape[-1]} matches neither "
            f"int8 ({Dh}) nor packed int4 ({Dh // 2})")
    page_size = k_pages.shape[2]
    pages_per_seq = block_tables.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(Dh)
    lens = _as_lengths(lengths, B)
    tables = jnp.asarray(block_tables, jnp.int32)
    if impl is None:
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "gather":
        return _paged_gather_attention(q, k_pages, v_pages, lens, tables,
                                       scale, k_scales, v_scales)
    if impl != "kernel":
        raise ValueError(f"impl must be None, 'kernel' or 'gather': {impl!r}")

    qh = q.transpose(0, 2, 1, 3)  # [B, H, 1, Dh]
    Dp = k_pages.shape[-1]  # Dh, or Dh//2 nibble-packed
    n_prefetch = 4 if quantized else 2
    kv_spec = pl.BlockSpec(
        (1, 1, page_size, Dp),
        # the paged gather IS this index_map: tile i of row b lives in
        # pool slot tbl[b, i] (args: grid ids, then every prefetch ref)
        lambda b, h, i, lens, tbl, *_s: (h, tbl[b, i], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # (lens, tables[, k/v scales]) -> SMEM
        grid=(B, H, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, 1, Dh),
                         lambda b, h, i, lens, tbl, *_s: (b, h, 0, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, 1, Dh),
                               lambda b, h, i, lens, tbl, *_s: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, Dh), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_q_kernel if quantized else _paged_kernel, sm_scale=scale,
        page_size=page_size, num_pages=pages_per_seq,
        **({"packed": packed} if quantized else {}))
    operands = ((lens, tables, k_scales.astype(jnp.float32),
                 v_scales.astype(jnp.float32), qh, k_pages, v_pages)
                if quantized else (lens, tables, qh, k_pages, v_pages))
    # k/v page pools enter with a leading dummy batch-of-heads axis folded
    # away by the (1, 1, ps, Dp) blocks over [H, P, ps, Dp]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, Dh), q.dtype),
        interpret=_interpret(),
    )(*operands)
    return out.transpose(0, 2, 1, 3)


def _paged_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, sm_scale: float, page_size: int,
                  num_pages: int):
    del tbl_ref  # consumed by the index maps; the body only needs lengths
    _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   sm_scale=sm_scale, block_k=page_size, num_blocks=num_pages)


def _paged_q_kernel(len_ref, tbl_ref, ks_ref, vs_ref, q_ref, k_ref, v_ref,
                    o_ref, acc_ref, m_ref, l_ref, *, sm_scale: float,
                    page_size: int, num_pages: int, packed: bool):
    """Quantized-pool variant of :func:`_paged_kernel`: the K/V tile is int8
    (or nibble-packed int4) and dequantizes against its per-(head, page)
    scale — read from SMEM next to the block table — inside the
    online-softmax body. Same state machine as the dense kernel."""
    b = pl.program_id(0)
    h = pl.program_id(1)
    ki = pl.program_id(2)
    cur = len_ref[b]
    page = tbl_ref[b, ki]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(ki * page_size < cur)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [1, Dh]
        kq = k_ref[0, 0]  # [ps, Dp] int8
        vq = v_ref[0, 0]
        if packed:
            k = unpack_kv_int4(kq)
            v = unpack_kv_int4(vq)
        else:
            k = kq.astype(jnp.float32)
            v = vq.astype(jnp.float32)
        k = k * ks_ref[h, page]  # per-(head, page) symmetric dequant
        v = v * vs_ref[h, page]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [1, ps]
        s_pos = (ki * page_size
                 + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1))
        s = jnp.where(s_pos < cur, s, NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(p, v)

    @pl.when(ki == num_pages - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


# ---------------------------------------------------------- multi-token verify
def paged_verify_attention(
    q: jnp.ndarray,           # [B, W, H, Dh] — the speculation window's queries
    k_pages: jnp.ndarray,     # [H, P, page_size, Dh] (or int8/int4 quantized)
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,     # [B] int32: tokens already in the POOL (the
    #                           window is NOT in the pool — it rides win_k/v)
    block_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    win_k: jnp.ndarray,       # [B, W, H, Dh] dense post-rope window keys
    win_v: jnp.ndarray,
    softmax_scale: Optional[float] = None,
    impl: Optional[str] = None,
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Speculative-decoding verification attention: score a ``W``-token
    window (the verified last token + k drafted tokens) in ONE pass.

    Window position ``i`` sits at absolute position ``lengths[b] + i`` and
    attends to the pool history (positions ``< lengths[b]``, read through the
    block table and dequantized exactly like :func:`paged_decode_attention`)
    plus window positions ``0..i`` (causal within the window). The window's
    K/V never touch the pool here — they arrive DENSE as ``win_k``/``win_v``
    and are committed separately, only up to the accepted prefix
    (``models/gpt.commit_window_kv``), which is what makes rejected-suffix
    rollback a no-op instead of an undo.

    The XLA ``gather`` fallback scatters the window K/V into the gathered
    pool copy at their true absolute positions and then runs EXACTLY the
    single-token fallback's masked softmax per window position — for dense
    pools the position-``i`` value stream is structurally identical to what
    ``i`` sequential :func:`paged_decode_attention` fallback calls would
    compute: the same values at the same positions reduced over the same
    axis, differing only by how XLA tiles the reduction for a different
    ``W`` (observed <=1e-7 on fp32 — argmax-stable, which is what the
    spec-on == spec-off greedy-equivalence gate measures at 1.0). The
    Pallas kernel streams pool pages like the single-token kernel and
    handles the window as one extra (causal) tile on the same online-softmax
    state; kernel vs fallback agree to fp tolerance (tested).
    """
    B, W, H, Dh = q.shape
    if win_k.shape != (B, W, H, Dh) or win_v.shape != (B, W, H, Dh):
        raise ValueError(
            f"win_k/win_v must be [B, W, H, Dh]={(B, W, H, Dh)}, got "
            f"{win_k.shape} / {win_v.shape}")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    quantized = k_scales is not None
    packed = quantized and k_pages.shape[-1] * 2 == Dh
    if quantized and not packed and k_pages.shape[-1] != Dh:
        raise ValueError(
            f"quantized pool last dim {k_pages.shape[-1]} matches neither "
            f"int8 ({Dh}) nor packed int4 ({Dh // 2})")
    page_size = k_pages.shape[2]
    pages_per_seq = block_tables.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(Dh)
    lens = _as_lengths(lengths, B)
    tables = jnp.asarray(block_tables, jnp.int32)
    if not quantized:
        # mirror the sequential append's pool cast, so the fallback reads
        # the same bits a committed-then-read window token would have
        win_k = win_k.astype(k_pages.dtype)
        win_v = win_v.astype(v_pages.dtype)
    if impl is None:
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "gather":
        return _paged_verify_gather(q, k_pages, v_pages, lens, tables,
                                    win_k, win_v, scale, k_scales, v_scales)
    if impl != "kernel":
        raise ValueError(f"impl must be None, 'kernel' or 'gather': {impl!r}")

    qh = q.transpose(0, 2, 1, 3)        # [B, H, W, Dh]
    wkh = win_k.transpose(0, 2, 1, 3)   # [B, H, W, Dh]
    wvh = win_v.transpose(0, 2, 1, 3)
    Dp = k_pages.shape[-1]
    n_prefetch = 4 if quantized else 2
    # grid walks the table's pages, then ONE extra step for the window tile;
    # the pool index_map clamps at the last table slot for that step (its
    # fetch is unused — the body only reads the window operands there)
    kv_spec = pl.BlockSpec(
        (1, 1, page_size, Dp),
        lambda b, h, i, lens, tbl, *_s: (
            h, tbl[b, jnp.minimum(i, pages_per_seq - 1)], 0, 0))
    win_spec = pl.BlockSpec((1, 1, W, Dh),
                            lambda b, h, i, lens, tbl, *_s: (b, h, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(B, H, pages_per_seq + 1),
        in_specs=[win_spec, kv_spec, kv_spec, win_spec, win_spec],
        out_specs=pl.BlockSpec((1, 1, W, Dh),
                               lambda b, h, i, lens, tbl, *_s: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((W, Dh), jnp.float32),
            pltpu.VMEM((W, 1), jnp.float32),
            pltpu.VMEM((W, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _verify_kernel, sm_scale=scale, page_size=page_size,
        num_pages=pages_per_seq, window=W, quantized=quantized,
        packed=packed)
    operands = ((lens, tables, k_scales.astype(jnp.float32),
                 v_scales.astype(jnp.float32), qh, k_pages, v_pages, wkh, wvh)
                if quantized else (lens, tables, qh, k_pages, v_pages,
                                   wkh, wvh))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, W, Dh), q.dtype),
        interpret=_interpret(),
    )(*operands)
    return out.transpose(0, 2, 1, 3)    # back to [B, W, H, Dh]


def _verify_kernel(len_ref, tbl_ref, *refs, sm_scale: float, page_size: int,
                   num_pages: int, window: int, quantized: bool,
                   packed: bool):
    """Online softmax over (pool pages ++ the causal window tile), with a
    [W, ·] state row per window position. Pool tiles mask at the POOL length
    (every window query sees the whole history); the final grid step scores
    the window against itself with the in-window causal mask and
    finalizes."""
    if quantized:
        (ks_ref, vs_ref, q_ref, k_ref, v_ref, wk_ref, wv_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
    else:
        ks_ref = vs_ref = None
        (q_ref, k_ref, v_ref, wk_ref, wv_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
    b = pl.program_id(0)
    h = pl.program_id(1)
    ki = pl.program_id(2)
    cur = len_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _online_update(s, v):
        """s: [W, bk] masked scores; v: [bk, Dh] values."""
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(p, v)

    @pl.when(jnp.logical_and(ki < num_pages, ki * page_size < cur))
    def _pool_tile():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale   # [W, Dh]
        kq = k_ref[0, 0]
        vq = v_ref[0, 0]
        if quantized:
            if packed:
                k = unpack_kv_int4(kq)
                v = unpack_kv_int4(vq)
            else:
                k = kq.astype(jnp.float32)
                v = vq.astype(jnp.float32)
            page = tbl_ref[b, ki]
            k = k * ks_ref[h, page]
            v = v * vs_ref[h, page]
        else:
            k = kq.astype(jnp.float32)
            v = vq.astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [W, ps]
        s_pos = (ki * page_size
                 + jax.lax.broadcasted_iota(jnp.int32, (window, page_size), 1))
        # pool history is valid for EVERY window query: the window itself
        # never lives in the pool during verification
        s = jnp.where(s_pos < cur, s, NEG_INF)
        _online_update(s, v)

    @pl.when(ki == num_pages)
    def _window_tile():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale   # [W, Dh]
        wk = wk_ref[0, 0].astype(jnp.float32)            # [W, Dh]
        wv = wv_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, wk, (((1,), (1,)), ((), ())))  # [W, W]
        row = jax.lax.broadcasted_iota(jnp.int32, (window, window), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (window, window), 1)
        s = jnp.where(col <= row, s, NEG_INF)  # causal within the window
        _online_update(s, wv)
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _paged_verify_gather(q, k_pages, v_pages, lens, tables, win_k, win_v,
                         scale, k_scales=None, v_scales=None):
    """XLA fallback for :func:`paged_verify_attention`: gather the pool like
    the single-token fallback, scatter the dense window K/V at their true
    absolute positions (``lengths[b] + i`` maps to gathered index
    ``lengths[b] + i`` because gathered order IS table order), then run the
    identical masked softmax once per window position via one einsum. For a
    dense pool the per-position arithmetic is bit-identical to ``W``
    sequential single-token fallback calls over a pool holding the same
    committed tokens."""
    B, W, H, Dh = q.shape

    def gather(pages, scales):
        g = pages[:, tables]          # [H, B, n, ps, Dp]
        if scales is not None:
            g = (unpack_kv_int4(g) if g.shape[-1] * 2 == Dh
                 else g.astype(jnp.float32))
            g = g * scales[:, tables][..., None, None]
        g = g.transpose(1, 0, 2, 3, 4)
        return g.reshape(B, g.shape[1], -1, g.shape[-1])  # [B, H, S, Dh]

    k = gather(k_pages, k_scales)
    v = gather(v_pages, v_scales)
    S = k.shape[2]
    # window position i lives at absolute (= gathered) position lens + i;
    # positions past the table capacity DROP (never clip: clipping would
    # overwrite an earlier window token's K/V at S-1 for a request whose
    # final window touches the capacity edge — a committable query would
    # then attend a rejected draft's K/V at its own position). Dropped
    # positions can never be committed: budget caps n at max_new, and
    # admission bounds prompt+max_new to the table.
    pos = lens[:, None] + jnp.arange(W)[None, :]              # [B, W]
    bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, W))
    k = k.at[bidx, :, pos, :].set(win_k.astype(k.dtype), mode="drop")
    v = v.at[bidx, :, pos, :].set(win_v.astype(v.dtype), mode="drop")
    s = jnp.einsum("bwhd,bhsd->bhws", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    # query i sees positions < lens + i + 1 (history + window prefix + self)
    limit = lens[:, None] + jnp.arange(1, W + 1)[None, :]      # [B, W]
    mask = jnp.arange(S)[None, None, :] < limit[:, :, None]    # [B, W, S]
    s = jnp.where(mask[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhws,bhsd->bwhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_gather_attention(q, k_pages, v_pages, lens, tables, scale,
                            k_scales=None, v_scales=None):
    """XLA fallback: materialize each request's pages contiguously (one
    gather), then the same masked softmax the dense reference computes — the
    value stream is arithmetically identical to attending over a contiguous
    cache holding the same tokens, so tests check it BITWISE against the
    dense path (dense pools) and against dequantize-then-dense (quantized
    pools: the fallback consumes the identical int payload, so the only
    difference from a dense cache is the quantization itself)."""
    B = q.shape[0]
    Dh = q.shape[-1]

    # [H, B, pages, ps, Dp] -> [B, H, pages*ps, Dh]
    def gather(pages, scales):
        g = pages[:, tables]          # [H, B, n, ps, Dp]
        if scales is not None:
            g = (unpack_kv_int4(g) if g.shape[-1] * 2 == Dh
                 else g.astype(jnp.float32))
            g = g * scales[:, tables][..., None, None]
        g = g.transpose(1, 0, 2, 3, 4)
        return g.reshape(B, g.shape[1], -1, g.shape[-1])

    k = gather(k_pages, k_scales)
    v = gather(v_pages, v_scales)
    s = jnp.einsum("bthd,bhsd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    S = k.shape[2]
    mask = jnp.arange(S)[None, None, None, :] < lens[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhts,bhsd->bthd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
