"""Dequant-fused matmul for the quantized-collective wire format.

``comm/quantized.py`` moves ZeRO traffic as per-block *affine* payloads
(uint8 ``q`` + fp32 ``scale``/``zero_point`` per trailing-dim block, possibly
edge-padded to a block multiple). The straightforward consumption path
materializes the dequantized fp copy (``dequantize_blockwise`` then matmul) —
an extra HBM-resident buffer per gathered window, and an extra HBM round trip
on the weight bytes. This kernel consumes the payload directly:

    out = x @ (q * scale + zero_point)        # dequantized per VMEM tile

so the int payload is the only resident wire artifact; dequantization happens
in the matmul's prologue on a ``(block_d, block_f)`` tile already in VMEM.
Same idea as :mod:`.int8_matmul` (the inference-side symmetric groupwise
format) but for the comm wire layout: affine (zero-point) blocks along the
trailing dimension, uint8 payload, possible edge padding trimmed at the end.

Off-TPU (or for ineligible shapes) the dispatcher falls back to XLA
``x @ dequantize_blockwise(...)`` — the payload is consumed by a reshape +
elementwise affine that XLA fuses into the matmul operand read, and the uint8
buffer is dead (donatable) after that single use, so no *persistent* fp copy
exists there either.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret

_LANE = 128
_SUBLANE = 8


def _kernel(x_ref, q_ref, s_ref, z_ref, o_ref, acc_ref, *, n_d: int,
            block: int):
    di = pl.program_id(2)

    @pl.when(di == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = q_ref[...].astype(jnp.float32)   # [bd, bf] u8 -> f32
    s = s_ref[0]                         # [bd, bf // block] f32
    z = z_ref[0]                         # [bd, bf // block] f32
    bd, bf = w.shape
    w = (w.reshape(bd, bf // block, block) * s[:, :, None]
         + z[:, :, None]).reshape(bd, bf)
    x = x_ref[...].astype(jnp.float32)   # [bm, bd]
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(di == n_d - 1)
    def _out():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _eligible(M: int, D: int, Fp: int, block: int, block_m: int,
              block_d: int, block_f: int) -> bool:
    return (block % _LANE == 0
            and Fp % block == 0
            and M % block_m == 0 and D % block_d == 0 and Fp % block_f == 0
            and block_f % block == 0)


@functools.partial(jax.jit, static_argnames=("block", "block_m", "block_d",
                                             "block_f", "orig_size",
                                             "out_dtype"))
def _dequant_matmul_kernel_call(x, q, s2d, z2d, block, block_m, block_d,
                                block_f, orig_size, out_dtype):
    M, D = x.shape
    Fp = q.shape[1]
    nbf = block_f // block
    # scales/zero-points pre-tiled [Fp/block_f, D, nbf]: Mosaic requires a
    # block's trailing dim to be lane-divisible OR the full array dim — the
    # per-f-block tile (nbf columns) is only legal as a full trailing dim
    s3 = s2d.reshape(D, Fp // block_f, nbf).transpose(1, 0, 2)
    z3 = z2d.reshape(D, Fp // block_f, nbf).transpose(1, 0, 2)
    out = pl.pallas_call(
        functools.partial(_kernel, n_d=D // block_d, block=block),
        grid=(M // block_m, Fp // block_f, D // block_d),
        in_specs=[
            pl.BlockSpec((block_m, block_d), lambda mi, fi, di: (mi, di)),
            pl.BlockSpec((block_d, block_f), lambda mi, fi, di: (di, fi)),
            pl.BlockSpec((1, block_d, nbf), lambda mi, fi, di: (fi, di, 0)),
            pl.BlockSpec((1, block_d, nbf), lambda mi, fi, di: (fi, di, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, block_f), lambda mi, fi, di: (mi, fi)),
        out_shape=jax.ShapeDtypeStruct((M, Fp), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_f), jnp.float32)],
        interpret=_interpret(),
    )(x, q, s3, z3)
    return out[:, :orig_size]


def _kernel_enabled() -> bool:
    """Kernel path on a real TPU backend, or when interpret/Mosaic lowering is
    explicitly requested (tests / AOT flows). Unlike the tiny decode GEMMs in
    :mod:`.int8_matmul`, these are training-scale matmuls — interpret-mode
    execution on the CPU backend would be pathologically slow, so plain CPU
    runs take the XLA fallback unless DS_TPU_PALLAS_INTERPRET opts in."""
    return (jax.default_backend() == "tpu"
            or os.environ.get("DS_TPU_PALLAS_INTERPRET") is not None)


def dequant_matmul(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray,
                   zero_point: jnp.ndarray, orig_size: int, bits: int = 8,
                   block_m: int = 256, block_d: int = 256,
                   block_f: int = 512) -> jnp.ndarray:
    """``x @ dequantize_blockwise(q, scale, zero_point)[:, :orig_size]``
    without materializing the dequantized weight in HBM.

    ``x``: [M, D] float. ``q``: [D, Fp] uint8 payload from
    :func:`~deepspeed_tpu.comm.quantized.quantize_blockwise` (8-bit; the
    packed int4 wire goes through the fallback). ``scale``/``zero_point``:
    [D, nb] fp32 per-block affine params; the block extent is ``Fp // nb``.
    ``orig_size``: the unpadded trailing dim of the weight.
    """
    from ...comm.quantized import dequantize_blockwise

    M, D = x.shape
    Dq, Fp = q.shape
    assert D == Dq, (x.shape, q.shape)
    if bits == 8:
        nb = scale.shape[-1]
        block = Fp // nb
        block_m = min(block_m, M)
        block_d = min(block_d, D)
        block_f = min(block_f, Fp)
        if (q.dtype == jnp.uint8 and _kernel_enabled()
                and _eligible(M, D, Fp, block, block_m, block_d, block_f)):
            return _dequant_matmul_kernel_call(
                x.astype(jnp.float32), q, scale.astype(jnp.float32),
                zero_point.astype(jnp.float32), block, block_m, block_d,
                block_f, orig_size, x.dtype)
    w = dequantize_blockwise(q, scale, zero_point, bits=bits,
                             orig_size=orig_size).astype(x.dtype)
    return x @ w
