"""Blocksparse flash attention in Pallas.

Capability parity with the reference's Triton blocksparse attention core
(``ops/sparse_attention/matmul.py`` SDD/DSD blocksparse matmuls +
``softmax.py`` blocksparse softmax, composed by
``sparse_self_attention.py:11``): attention restricted to the active blocks of a
static block layout, with flash-style online softmax so neither the dense [T, T]
scores nor the sparse score blocks are ever materialized in HBM — one fused
kernel instead of the reference's three (SDD matmul, softmax, DSD matmul).

Structure (extends :mod:`.flash_attention`):
- the layout ``[H, nQ, nK]`` is static (numpy). Per (head, q-block) the active
  k-block indices are precomputed into a padded index table ``kidx [H, nQ, A]``
  with counts ``kcnt [H, nQ]``; the kernel's inner ``fori_loop`` runs only
  ``kcnt`` iterations and dynamically slices the k/v blocks it needs — compute
  and HBM traffic scale with layout density, not T².
- the index/count tables ride **scalar prefetch** (SMEM via
  ``pltpu.PrefetchScalarGridSpec``) — int32 control data is not tileable as a
  VMEM block, and Mosaic rejects (1, 1, A) blocks; SMEM residency is the TPU
  idiom for blocksparse index tables.
- backward mirrors it with the transposed table (active q-blocks per k-block)
  for dk/dv.
- causal masking is elementwise inside diagonal blocks; block-level causality is
  already encoded in the layout (configs mask the upper triangle).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LANES, NEG_INF, _interpret


def layout_tables(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Static index tables from a [H, nQ, nK] 0/1 layout.

    Returns (kidx [H,nQ,A], kcnt [H,nQ], qidx [H,nK,Aq], qcnt [H,nK]) padded
    with 0 (padding entries are never read: the loop bound is the count).
    """
    H, nQ, nK = layout.shape
    max_k = max(1, int(layout.sum(axis=2).max()))
    max_q = max(1, int(layout.sum(axis=1).max()))
    kidx = np.zeros((H, nQ, max_k), np.int32)
    kcnt = np.zeros((H, nQ), np.int32)
    qidx = np.zeros((H, nK, max_q), np.int32)
    qcnt = np.zeros((H, nK), np.int32)
    for h in range(H):
        for i in range(nQ):
            cols = np.nonzero(layout[h, i])[0]
            kidx[h, i, : len(cols)] = cols
            kcnt[h, i] = len(cols)
        for j in range(nK):
            rows = np.nonzero(layout[h, :, j])[0]
            qidx[h, j, : len(rows)] = rows
            qcnt[h, j] = len(rows)
    return kidx, kcnt, qidx, qcnt


# --------------------------------------------------------------------------- fwd
def _fwd_kernel(kidx_ref, kcnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                H: int, sm_scale: float, causal: bool, block: int):
    h = jax.lax.rem(pl.program_id(0), H)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [B, D]
    bq = q.shape[0]
    acc = jnp.zeros((bq, v_ref.shape[-1]), jnp.float32)
    m_i = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l_i = jnp.zeros((bq, 1), jnp.float32)
    q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)

    def body(a, carry):
        acc, m_i, l_i = carry
        ki = kidx_ref[h, qi, a]
        k = k_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [B, B]
        if causal:
            k_pos = ki * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot(p, v)
        return acc, m_new, l_new

    acc, m_i, l_i = jax.lax.fori_loop(0, kcnt_ref[h, qi], body, (acc, m_i, l_i))
    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m_i + jnp.log(l_safe), (bq, LANES))


# --------------------------------------------------------------------------- bwd
def _bwd_dq_kernel(kidx_ref, kcnt_ref, q_ref, k_ref, v_ref, o_ref, do_ref,
                   lse_ref, dq_ref, *, H: int, sm_scale: float, causal: bool,
                   block: int):
    h = jax.lax.rem(pl.program_id(0), H)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    o = o_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]
    delta = jnp.sum(do * o, axis=-1, keepdims=True)
    bq = q.shape[0]
    q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)

    def body(a, dq):
        ki = kidx_ref[h, qi, a]
        k = k_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * sm_scale
        if causal:
            k_pos = ki * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot(ds, k)

    dq = jax.lax.fori_loop(0, kcnt_ref[h, qi], body,
                           jnp.zeros((bq, q.shape[-1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(qidx_ref, qcnt_ref, q_ref, k_ref, v_ref, o_ref, do_ref,
                    lse_ref, dk_ref, dv_ref, *, H: int, sm_scale: float,
                    causal: bool, block: int):
    h = jax.lax.rem(pl.program_id(0), H)
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk = k.shape[0]
    k_pos = ki * block + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 1)

    def body(a, carry):
        dk, dv = carry
        qi = qidx_ref[h, ki, a]
        q = q_ref[0, pl.ds(qi * block, block), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block, block), :].astype(jnp.float32)
        o = o_ref[0, pl.ds(qi * block, block), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block, block), :1]
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * sm_scale
        if causal:
            q_pos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 0)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())))
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        0, qcnt_ref[h, ki], body,
        (jnp.zeros((bk, k.shape[-1]), jnp.float32),
         jnp.zeros((bk, v.shape[-1]), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------------- glue
def _fwd(q, k, v, kidx, kcnt, H, sm_scale, causal, block):
    BH, T, D = q.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # kidx, kcnt in SMEM
        grid=(BH, T // block),
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, *_: (bh, i, 0)),
            pl.BlockSpec((1, T, D), lambda bh, i, *_: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, i, *_: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, *_: (bh, i, 0)),
            pl.BlockSpec((1, block, LANES), lambda bh, i, *_: (bh, i, 0)),
        ],
    )
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, H=H, sm_scale=sm_scale, causal=causal,
                          block=block),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(kidx, kcnt, q, k, v)
    return o, lse


def _bwd(kidx, kcnt, qidx, qcnt, H, sm_scale, causal, block, res, do):
    q, k, v, o, lse = res
    BH, T, D = q.shape
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, T // block),
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, *_: (bh, i, 0)),
            pl.BlockSpec((1, T, D), lambda bh, i, *_: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, i, *_: (bh, 0, 0)),
            pl.BlockSpec((1, block, D), lambda bh, i, *_: (bh, i, 0)),
            pl.BlockSpec((1, block, D), lambda bh, i, *_: (bh, i, 0)),
            pl.BlockSpec((1, block, LANES), lambda bh, i, *_: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, D), lambda bh, i, *_: (bh, i, 0)),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, H=H, sm_scale=sm_scale, causal=causal,
                          block=block),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret(),
    )(kidx, kcnt, q, k, v, o, do, lse)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, T // block),
        in_specs=[
            pl.BlockSpec((1, T, D), lambda bh, j, *_: (bh, 0, 0)),
            pl.BlockSpec((1, block, D), lambda bh, j, *_: (bh, j, 0)),
            pl.BlockSpec((1, block, D), lambda bh, j, *_: (bh, j, 0)),
            pl.BlockSpec((1, T, D), lambda bh, j, *_: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, j, *_: (bh, 0, 0)),
            pl.BlockSpec((1, T, LANES), lambda bh, j, *_: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda bh, j, *_: (bh, j, 0)),
            pl.BlockSpec((1, block, D), lambda bh, j, *_: (bh, j, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, H=H, sm_scale=sm_scale, causal=causal,
                          block=block),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qidx, qcnt, q, k, v, o, do, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _bs_attn(q, k, v, kidx, kcnt, qidx, qcnt, H, sm_scale, causal, block):
    o, _ = _fwd(q, k, v, kidx, kcnt, H, sm_scale, causal, block)
    return o


def _bs_fwd(q, k, v, kidx, kcnt, qidx, qcnt, H, sm_scale, causal, block):
    o, lse = _fwd(q, k, v, kidx, kcnt, H, sm_scale, causal, block)
    return o, (q, k, v, o, lse, kidx, kcnt, qidx, qcnt)


def _bs_bwd(H, sm_scale, causal, block, res, do):
    q, k, v, o, lse, kidx, kcnt, qidx, qcnt = res
    dq, dk, dv = _bwd(kidx, kcnt, qidx, qcnt, H, sm_scale, causal, block,
                      (q, k, v, o, lse), do)
    return dq, dk, dv, None, None, None, None


_bs_attn.defvjp(_bs_fwd, _bs_bwd)


def blocksparse_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    layout: np.ndarray,  # [H, T/block, T/block] static 0/1
    block: int,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    tables: Optional[Tuple] = None,  # precomputed layout_tables (caching)
) -> jnp.ndarray:
    """Attention restricted to the active blocks of ``layout``; differentiable."""
    B, T, H, D = q.shape
    if layout.shape != (H, T // block, T // block):
        raise ValueError(
            f"layout {layout.shape} != (H={H}, {T // block}, {T // block})")
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(D)
    if tables is None:
        tables = tuple(jnp.asarray(t) for t in layout_tables(layout))
    kidx, kcnt, qidx, qcnt = tables
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    o = _bs_attn(qt, kt, vt, kidx, kcnt, qidx, qcnt, H, scale, causal, block)
    return o.reshape(B, H, T, D).transpose(0, 2, 1, 3)
