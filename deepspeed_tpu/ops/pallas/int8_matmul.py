"""int8-weight matmul: dequantize per VMEM tile, never in HBM.

Capability parity with the reference's int8 inference GEMMs, which consume
quantized weights directly and dequantize inside the kernel
(``csrc/transformer/inference/csrc/dequantize.cu`` + the GEMM bindings in
``pt_binding.cpp``). On TPU this matters twice over for decode:

1. HBM CAPACITY — XLA-level dequantize-then-matmul materializes bf16 weight
   buffers (and, measured at 13B, layout-transposed copies of the s8 stacks);
   the kernel reads s8 straight from HBM and widens only a (block_d, block_f)
   tile in VMEM.
2. HBM BANDWIDTH — single-token decode is weight-bandwidth-bound, so moving
   s8 instead of bf16 halves the bytes per step: the same lever the
   reference's dequant-fused GEMMs pull on V100.

Quantization layout matches ``ops/quantizer/quantize`` as used by
``models/gpt.quantize_for_inference``: a weight [D, F] is flattened row-major
and split into contiguous ``group_size`` runs, so with ``F % group_size == 0``
the scales reshape to [D, F // group_size] — each scale covers a run along F
within one row.

Grid = (F / block_f, D / block_d): the contraction (D) axis is innermost, so
the f32 accumulator lives in VMEM scratch across its steps; x stays whole
(decode M = B*T is tiny) with rows padded to the 8-sublane tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret

_LANE = 128
_SUBLANE = 8


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_d: int, group: int):
    di = pl.program_id(1)

    @pl.when(di == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = q_ref[...].astype(jnp.float32)  # [bd, bf] s8 -> f32
    s = s_ref[0]  # [bd, bf // group] f32 (scales pre-tiled per f-block)
    bd, bf = w.shape
    w = (w.reshape(bd, bf // group, group) * s[:, :, None]).reshape(bd, bf)
    x = x_ref[...].astype(jnp.float32)  # [M, bd]
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(di == n_d - 1)
    def _out():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


_MAX_M = 256  # beyond this (large prefill) x + the f32 accumulator overflow
# VMEM — the XLA fallback is compute-bound there anyway


def _on_tpu() -> bool:
    """Kernel path when: on a TPU backend, in interpret mode (tests), OR
    when real Mosaic lowering is forced (DS_TPU_PALLAS_INTERPRET=0 — the
    AOT compile-only flow targets a TPU topology from a CPU host, where
    default_backend() says "cpu" but the program IS for TPU). Shared by the
    int8 and int4 dispatchers so the policy cannot diverge."""
    import os

    return (jax.default_backend() == "tpu" or _interpret()
            or os.environ.get("DS_TPU_PALLAS_INTERPRET") == "0")


def _eligible(M: int, D: int, F: int, group: int, block_d: int,
              block_f: int) -> bool:
    return (M <= _MAX_M
            and F % group == 0 and group % _LANE == 0
            and D % block_d == 0 and F % block_f == 0
            and block_f % group == 0)


@functools.partial(jax.jit, static_argnames=("group", "block_d", "block_f",
                                             "out_dtype"))
def _int8_matmul_kernel_call(x, q, s2d, group, block_d, block_f, out_dtype):
    M, D = x.shape
    F = q.shape[1]
    Mp = max(_SUBLANE, ((M + _SUBLANE - 1) // _SUBLANE) * _SUBLANE)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    # scales pre-tiled [F/block_f, D, block_f/group]: Mosaic requires a
    # block's last dim to be lane-divisible OR the full array dim — the
    # per-f-block scale tile (block_f/group columns) is only legal as a
    # full trailing dim
    nf = block_f // group
    s3 = s2d.reshape(D, F // block_f, nf).transpose(1, 0, 2)
    out = pl.pallas_call(
        functools.partial(_kernel, n_d=D // block_d, group=group),
        grid=(F // block_f, D // block_d),
        in_specs=[
            pl.BlockSpec((Mp, block_d), lambda fi, di: (0, di)),
            pl.BlockSpec((block_d, block_f), lambda fi, di: (di, fi)),
            pl.BlockSpec((1, block_d, nf), lambda fi, di: (fi, di, 0)),
        ],
        out_specs=pl.BlockSpec((Mp, block_f), lambda fi, di: (0, fi)),
        out_shape=jax.ShapeDtypeStruct((Mp, F), out_dtype),
        scratch_shapes=[pltpu.VMEM((Mp, block_f), jnp.float32)],
        interpret=_interpret(),
    )(x, q, s3)
    return out[:M]


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int4 values (stored sign-extended in int8, range [-8, 7]) two per
    byte along the LAST axis, half-split: byte j holds ``w[..., j]`` in its
    low nibble and ``w[..., j + F/2]`` in its high nibble. Half-split (vs
    pairwise interleave) keeps the kernel's unpack a lane-aligned
    whole-tile op — each output f-block reads one nibble of one packed tile.
    """
    F = q.shape[-1]
    assert F % 2 == 0, f"int4 packing needs an even last dim, got {F}"
    lo = q[..., : F // 2].astype(jnp.int32) & 0xF
    hi = q[..., F // 2:].astype(jnp.int32)
    return (lo | (hi << 4)).astype(jnp.int8)


def _unpack_nibble(t: jnp.ndarray, high: bool) -> jnp.ndarray:
    """Sign-extended int4 from a packed int32 tile (xor-sub trick)."""
    nib = ((t >> 4) if high else t) & 0xF
    return (nib ^ 8) - 8


def unpack_int4(q4: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: [., F/2] packed -> [., F] int8."""
    t = q4.astype(jnp.int32)
    return jnp.concatenate(
        [_unpack_nibble(t, False), _unpack_nibble(t, True)],
        axis=-1).astype(jnp.int8)


def _kernel4(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_d: int, group: int):
    """One grid step consumes ONE packed tile and emits BOTH output halves
    (lo nibble -> output block fi, hi nibble -> block fi + n_f/2, stacked on
    the output's leading axis) — each packed byte is read from HBM exactly
    once per matmul, so decode weight traffic is a true QUARTER of bf16."""
    di = pl.program_id(1)

    @pl.when(di == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    t = q_ref[...].astype(jnp.int32)  # [bd, bf] packed bytes
    bd, bf = t.shape
    # [bd, 2*bf]: lo-half columns then hi-half columns
    w = jnp.concatenate(
        [_unpack_nibble(t, False), _unpack_nibble(t, True)],
        axis=1).astype(jnp.float32)
    s = s_ref[0]  # [bd, 2 * bf // group] f32 (lo-block + hi-block scales)
    w = (w.reshape(bd, 2 * bf // group, group)
         * s[:, :, None]).reshape(bd, 2 * bf)
    x = x_ref[...].astype(jnp.float32)  # [M, bd]
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(di == n_d - 1)
    def _out():
        o_ref[0] = acc_ref[:, :bf].astype(o_ref.dtype)
        o_ref[1] = acc_ref[:, bf:].astype(o_ref.dtype)


def _eligible4(M: int, D: int, F: int, group: int, block_d: int,
               block_f: int) -> bool:
    n_f = F // block_f if F % block_f == 0 else 0
    return (M <= _MAX_M
            and F % group == 0 and group % _LANE == 0
            and D % block_d == 0 and F % block_f == 0
            and n_f % 2 == 0  # halves must tile into whole f-blocks
            and block_f % group == 0)


@functools.partial(jax.jit, static_argnames=("group", "block_d", "block_f",
                                             "out_dtype"))
def _int4_matmul_kernel_call(x, q4, s2d, group, block_d, block_f, out_dtype):
    M, D = x.shape
    F = q4.shape[1] * 2
    n_f = F // block_f
    nh = n_f // 2  # packed f-blocks (each serves two output blocks)
    Mp = max(_SUBLANE, ((M + _SUBLANE - 1) // _SUBLANE) * _SUBLANE)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    nf = block_f // group
    # scales for packed block k = output blocks k (lo) and k + n_f/2 (hi),
    # paired along the trailing dim: [nh, D, 2*nf]
    s3 = s2d.reshape(D, n_f, nf).transpose(1, 0, 2)
    s3 = jnp.concatenate([s3[:nh], s3[nh:]], axis=-1)
    out = pl.pallas_call(
        functools.partial(_kernel4, n_d=D // block_d, group=group),
        grid=(nh, D // block_d),
        in_specs=[
            pl.BlockSpec((Mp, block_d), lambda fi, di: (0, di)),
            pl.BlockSpec((block_d, block_f), lambda fi, di: (di, fi)),
            pl.BlockSpec((1, block_d, 2 * nf), lambda fi, di: (fi, di, 0)),
        ],
        # halves stacked on a leading axis: one grid step writes both
        out_specs=pl.BlockSpec((2, Mp, block_f), lambda fi, di: (0, 0, fi)),
        out_shape=jax.ShapeDtypeStruct((2, Mp, F // 2), out_dtype),
        scratch_shapes=[pltpu.VMEM((Mp, 2 * block_f), jnp.float32)],
        interpret=_interpret(),
    )(x, q4, s3)
    return jnp.concatenate([out[0], out[1]], axis=-1)[:M]


def int4_matmul(x: jnp.ndarray, q4: jnp.ndarray, s: jnp.ndarray,
                group_size: int = 128, block_d: int = 256,
                block_f: int = 512) -> jnp.ndarray:
    """``x @ dequantize(unpack_int4(q4), s)`` without materializing the bf16
    (or even the unpacked s8) weight: nibbles widen per VMEM tile.

    x: [M, D]; q4: [D, F//2] packed int8 (:func:`pack_int4` half-split
    layout); s: flat scales for row-major ``group_size`` runs of the
    UNPACKED [D, F] weight. Decode moves a QUARTER of the bf16 weight
    bytes — GPT-NeoX-20B decode becomes chip-resident on one 16 GB v5e.
    Parity: the reference's 4-bit groupwise quantized inference GEMMs
    (``csrc/transformer/inference/csrc/dequantize.cu`` dequant-fused path).
    """
    M, D = x.shape
    Dq, F2 = q4.shape
    F = F2 * 2
    assert D == Dq, (x.shape, q4.shape)
    block_d = min(block_d, D)
    block_f = min(block_f, F)
    if not (_on_tpu() and _eligible4(M, D, F, group_size, block_d, block_f)):
        w = (unpack_int4(q4).astype(jnp.float32).reshape(-1, group_size)
             * s.astype(jnp.float32)[:, None]).reshape(D, F).astype(x.dtype)
        return x @ w
    s2d = s.reshape(D, F // group_size).astype(jnp.float32)
    return _int4_matmul_kernel_call(x, q4, s2d, group_size, block_d, block_f,
                                    x.dtype)


def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
                group_size: int = 64, block_d: int = 256,
                block_f: int = 512) -> jnp.ndarray:
    """``x @ dequantize(q, s)`` without materializing the bf16 weight.

    x: [M, D] (float); q: [D, F] int8; s: flat scales for row-major
    ``group_size`` runs (``models/gpt.quantize_for_inference`` layout).
    Falls back to XLA dequantize-then-matmul off-TPU or for ineligible
    shapes/groupings.
    """
    M, D = x.shape
    Dq, F = q.shape
    assert D == Dq, (x.shape, q.shape)
    block_d = min(block_d, D)
    block_f = min(block_f, F)
    if not (_on_tpu() and _eligible(M, D, F, group_size, block_d, block_f)):
        # flat-group dequant (handles F % group != 0 — groups are runs of the
        # row-major flatten, the quantizer's only real invariant)
        w = (q.astype(jnp.float32).reshape(-1, group_size)
             * s.astype(jnp.float32)[:, None]).reshape(D, F).astype(x.dtype)
        return x @ w
    s2d = s.reshape(D, F // group_size).astype(jnp.float32)
    return _int8_matmul_kernel_call(x, q, s2d, group_size, block_d, block_f,
                                    x.dtype)
