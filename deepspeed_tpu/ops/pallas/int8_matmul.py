"""int8-weight matmul: dequantize per VMEM tile, never in HBM.

Capability parity with the reference's int8 inference GEMMs, which consume
quantized weights directly and dequantize inside the kernel
(``csrc/transformer/inference/csrc/dequantize.cu`` + the GEMM bindings in
``pt_binding.cpp``). On TPU this matters twice over for decode:

1. HBM CAPACITY — XLA-level dequantize-then-matmul materializes bf16 weight
   buffers (and, measured at 13B, layout-transposed copies of the s8 stacks);
   the kernel reads s8 straight from HBM and widens only a (block_d, block_f)
   tile in VMEM.
2. HBM BANDWIDTH — single-token decode is weight-bandwidth-bound, so moving
   s8 instead of bf16 halves the bytes per step: the same lever the
   reference's dequant-fused GEMMs pull on V100.

Quantization layout matches ``ops/quantizer/quantize`` as used by
``models/gpt.quantize_for_inference``: a weight [D, F] is flattened row-major
and split into contiguous ``group_size`` runs, so with ``F % group_size == 0``
the scales reshape to [D, F // group_size] — each scale covers a run along F
within one row.

Grid = (F / block_f, D / block_d): the contraction (D) axis is innermost, so
the f32 accumulator lives in VMEM scratch across its steps; x stays whole
(decode M = B*T is tiny) with rows padded to the 8-sublane tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret

_LANE = 128
_SUBLANE = 8


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_d: int, group: int):
    di = pl.program_id(1)

    @pl.when(di == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = q_ref[...].astype(jnp.float32)  # [bd, bf] s8 -> f32
    s = s_ref[0]  # [bd, bf // group] f32 (scales pre-tiled per f-block)
    bd, bf = w.shape
    w = (w.reshape(bd, bf // group, group) * s[:, :, None]).reshape(bd, bf)
    x = x_ref[...].astype(jnp.float32)  # [M, bd]
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(di == n_d - 1)
    def _out():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


_MAX_M = 256  # beyond this (large prefill) x + the f32 accumulator overflow
# VMEM — the XLA fallback is compute-bound there anyway


def _eligible(M: int, D: int, F: int, group: int, block_d: int,
              block_f: int) -> bool:
    return (M <= _MAX_M
            and F % group == 0 and group % _LANE == 0
            and D % block_d == 0 and F % block_f == 0
            and block_f % group == 0)


@functools.partial(jax.jit, static_argnames=("group", "block_d", "block_f",
                                             "out_dtype"))
def _int8_matmul_kernel_call(x, q, s2d, group, block_d, block_f, out_dtype):
    M, D = x.shape
    F = q.shape[1]
    Mp = max(_SUBLANE, ((M + _SUBLANE - 1) // _SUBLANE) * _SUBLANE)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    # scales pre-tiled [F/block_f, D, block_f/group]: Mosaic requires a
    # block's last dim to be lane-divisible OR the full array dim — the
    # per-f-block scale tile (block_f/group columns) is only legal as a
    # full trailing dim
    nf = block_f // group
    s3 = s2d.reshape(D, F // block_f, nf).transpose(1, 0, 2)
    out = pl.pallas_call(
        functools.partial(_kernel, n_d=D // block_d, group=group),
        grid=(F // block_f, D // block_d),
        in_specs=[
            pl.BlockSpec((Mp, block_d), lambda fi, di: (0, di)),
            pl.BlockSpec((block_d, block_f), lambda fi, di: (di, fi)),
            pl.BlockSpec((1, block_d, nf), lambda fi, di: (fi, di, 0)),
        ],
        out_specs=pl.BlockSpec((Mp, block_f), lambda fi, di: (0, fi)),
        out_shape=jax.ShapeDtypeStruct((Mp, F), out_dtype),
        scratch_shapes=[pltpu.VMEM((Mp, block_f), jnp.float32)],
        interpret=_interpret(),
    )(x, q, s3)
    return out[:M]


def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
                group_size: int = 64, block_d: int = 256,
                block_f: int = 512) -> jnp.ndarray:
    """``x @ dequantize(q, s)`` without materializing the bf16 weight.

    x: [M, D] (float); q: [D, F] int8; s: flat scales for row-major
    ``group_size`` runs (``models/gpt.quantize_for_inference`` layout).
    Falls back to XLA dequantize-then-matmul off-TPU or for ineligible
    shapes/groupings.
    """
    M, D = x.shape
    Dq, F = q.shape
    assert D == Dq, (x.shape, q.shape)
    block_d = min(block_d, D)
    block_f = min(block_f, F)
    # kernel path when: on a TPU backend, in interpret mode (tests), OR when
    # real Mosaic lowering is forced (DS_TPU_PALLAS_INTERPRET=0 — the AOT
    # compile-only flow targets a TPU topology from a CPU host, where
    # default_backend() says "cpu" but the program IS for TPU)
    import os

    on_tpu = (jax.default_backend() == "tpu" or _interpret()
              or os.environ.get("DS_TPU_PALLAS_INTERPRET") == "0")
    if not (on_tpu and _eligible(M, D, F, group_size, block_d, block_f)):
        # flat-group dequant (handles F % group != 0 — groups are runs of the
        # row-major flatten, the quantizer's only real invariant)
        w = (q.astype(jnp.float32).reshape(-1, group_size)
             * s.astype(jnp.float32)[:, None]).reshape(D, F).astype(x.dtype)
        return x @ w
    s2d = s.reshape(D, F // group_size).astype(jnp.float32)
    return _int8_matmul_kernel_call(x, q, s2d, group_size, block_d, block_f,
                                    x.dtype)
