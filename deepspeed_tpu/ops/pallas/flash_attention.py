"""Flash attention for TPU in Pallas.

Capability parity with the reference's fused attention kernels — training softmax
(``csrc/transformer/softmax_kernels.cu``) and the attention core of the fused
transformer layer (``csrc/transformer/ds_transformer_cuda.cpp``) — rebuilt as a
blockwise online-softmax kernel so the [T, T] score matrix never materializes in
HBM. This lifts the memory ceiling that forces full-recompute activation
checkpointing on long sequences (the reference's sparse-attention pillar targets the
same ceiling; blocksparse lives in ``blocksparse.py``).

Design (TPU-first, per the Pallas TPU guide):
- grid = (batch*heads, T/Bq): each program owns one q block in VMEM and streams
  k/v blocks with an online (max, sum) rescale — MXU does the two matmuls per
  block, VPU the rescale.
- causal masking skips whole k blocks above the diagonal: the fori_loop bound
  depends on the q block index, so work is triangular like the reference's
  ``attn_softmax`` triangular mode.
- fp32 accumulators; the saved logsumexp rides a 128-lane broadcast layout
  ([BH, T, 128]) because TPU VMEM tiles are (8, 128) — a bare [BH, T] residual
  would violate the layout constraints (same trick as jax's reference TPU kernel).
- backward = two kernels (dq over q blocks; dk/dv over k blocks) using the saved
  logsumexp; delta = rowsum(dO*O) is computed in-kernel from the o/do blocks.
  Wrapped in ``jax.custom_vjp``.
- ``interpret=True`` automatically off-TPU so the same code runs in CPU CI.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128  # TPU lane count; lse residual is broadcast across it


def _interpret() -> bool:
    # DS_TPU_PALLAS_INTERPRET=0 forces real Mosaic lowering even when the
    # process backend is CPU — the AOT compile-only flow (bench pipeline_aot)
    # targets a TPU topology from a CPU host, and interpret-mode HLO would
    # both misrepresent the real program and OOM the compiler
    env = os.environ.get("DS_TPU_PALLAS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "False")
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------- fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int, q_offset: int, stochastic_mode: bool):
    """One (q block, k block) tile of the online softmax. The k axis streams
    through the innermost grid dimension (whole-sequence k/v in VMEM trips
    the Mosaic scoped-VMEM limit past ~8k); the (acc, m, l) state lives in
    VMEM scratch, persisting across the revisited output window."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    bq = q_ref.shape[1]
    # stochastic mode (parity: ds_transformer_cuda.cpp:63 stochastic_mode —
    # speed over run-exactness): matmul operands stay in the input dtype so
    # the MXU runs its native bf16 pass (fp32 upcast costs multiple passes);
    # accumulation and the softmax state remain fp32
    lo = q_ref.dtype if stochastic_mode else jnp.float32

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = (q_ref[0].astype(jnp.float32) * sm_scale).astype(lo)  # [Bq, D]
        k = k_ref[0].astype(lo)  # [Bk, D]
        v = v_ref[0].astype(lo)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [Bq, Bk]
        if causal:
            # q rows sit at absolute positions q_offset + qi*Bq + i
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_i = m_ref[:, :1]
        l_i = l_ref[:, :1]
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha
                        + jax.lax.dot(p.astype(lo), v,
                                      preferred_element_type=jnp.float32))
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # only blocks intersecting the lower triangle of this q block
        pl.when(ki * block_k
                <= q_offset + qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l_i = l_ref[:, :1]
        l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l_safe)  # [Bq, 1]
        lse_ref[0] = jnp.broadcast_to(lse, (bq, LANES))


def _fwd(q, k, v, sm_scale: float, causal: bool, block_q: int, block_k: int,
         stochastic_mode: bool = False):
    """q,k,v: [BH, T, D] -> (o [BH, T, D], lse [BH, T, LANES])."""
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    S = k.shape[1]
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=S // block_k,
        q_offset=S - T, stochastic_mode=stochastic_mode)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),      # acc
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running sum
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------- bwd
# Backward kernels stream the CONTRACTED sequence axis through the grid
# (3D grid, innermost axis revisits the same output window, accumulating)
# instead of holding whole-sequence refs in VMEM — a [1, S, D] VMEM block
# trips the Mosaic scoped-VMEM limit (16M, double-buffered) past seq ~4-8k.
# Per grid step VMEM holds one (block_q, D) + one (block_k, D) tile set, so
# the sequence ceiling is gone; causal skipping is a pl.when on whole blocks
# (the out-of-triangle fetches still stream, the MXU work is skipped).


def _bwd_delta_kernel(o_ref, do_ref, delta_ref):
    """delta = rowsum(dO * O), computed ONCE per q block (it is k-invariant;
    recomputing it per streamed k block would re-DMA the o tile S/block_k
    times) and broadcast across lanes like the lse residual."""
    delta = jnp.sum(do_ref[0].astype(jnp.float32)
                    * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True)
    delta_ref[0] = jnp.broadcast_to(delta, delta_ref.shape[1:])


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   sm_scale: float, causal: bool, block_q: int, block_k: int,
                   q_offset: int, stochastic_mode: bool):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    lo = q_ref.dtype if stochastic_mode else jnp.float32

    @pl.when(ki == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    bq = q_ref.shape[1]

    def _compute():
        q = q_ref[0].astype(lo)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]  # [Bq, 1]
        delta = delta_ref[0][:, :1]  # [Bq, 1]
        k = k_ref[0].astype(lo)
        v = v_ref[0].astype(lo)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dp = jax.lax.dot_general(do.astype(lo), v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_ref[0] += jax.lax.dot(
            ds.astype(lo), k,
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)

    if causal:
        # any row of this q block can see the k block's first column?
        pl.when(ki * block_k
                <= q_offset + qi * block_q + block_q - 1)(_compute)
    else:
        _compute()


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                    block_q: int, block_k: int, q_offset: int,
                    stochastic_mode: bool):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    lo = k_ref.dtype if stochastic_mode else jnp.float32

    @pl.when(qi == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    bk = k_ref.shape[1]

    def _compute():
        k = k_ref[0].astype(lo)  # [Bk, D]
        v = v_ref[0].astype(lo)
        q = q_ref[0].astype(lo)  # [Bq, D]
        do_lo = do_ref[0].astype(lo)
        lse = lse_ref[0][:, :1]  # [Bq, 1]
        delta = delta_ref[0][:, :1]  # [Bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [Bq, Bk]
        if causal:
            bq = q.shape[0]
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_ref[0] += jax.lax.dot_general(
            p.astype(lo), do_lo, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(do_lo, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_ref[0] += jax.lax.dot_general(
            ds.astype(lo), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)

    if causal:
        # does the last row of this q block reach the k block at all?
        pl.when(ki * block_k
                <= q_offset + qi * block_q + block_q - 1)(_compute)
    else:
        _compute()


def _bwd(sm_scale, causal, block_q, block_k, stochastic_mode, res, do):
    q, k, v, o, lse = res
    BH, T, D = q.shape
    S = k.shape[1]

    # prologue: delta = rowsum(dO*O) once per q row (k-invariant), in the
    # same 128-lane broadcast layout as the lse residual
    delta = pl.pallas_call(
        _bwd_delta_kernel,
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, LANES), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        interpret=_interpret(),
    )(o, do)

    # accumulators are the (revisited) fp32 OUTPUT windows; cast at the end —
    # accumulating in bf16 across S/block_k grid steps would lose precision
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_offset=S - T, stochastic_mode=stochastic_mode),
        grid=(BH, T // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), jnp.float32),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_offset=S - T, stochastic_mode=stochastic_mode),
        grid=(BH, S // block_k, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------- api
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode):
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode)
    return o


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode)
    return o, (q, k, v, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, stochastic_mode, res, do):
    return _bwd(sm_scale, causal, block_q, block_k, stochastic_mode, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, H, D]
    v: jnp.ndarray,  # [B, S, H, D]
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    stochastic_mode: bool = False,
) -> jnp.ndarray:
    """Blockwise attention with online softmax; differentiable (custom VJP).

    ``stochastic_mode`` trades bit-exactness for speed (parity:
    ``csrc/transformer/ds_transformer_cuda.cpp:63``): matmul operands ride the
    input dtype onto the MXU's native bf16 pass instead of being upcast to
    fp32; accumulators and softmax state stay fp32. Off by default."""
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    # shrink blocks to the largest 128-multiple that divides the sequence
    while block_q > 128 and T % block_q:
        block_q //= 2
    while block_k > 128 and S % block_k:
        block_k //= 2
    if T % block_q or S % block_k:
        raise ValueError(f"seq lens ({T},{S}) must divide blocks ({block_q},{block_k})")
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(D)
    # [B, T, H, D] -> [B*H, T, D]
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    o = _flash(qt, kt, vt, scale, causal, block_q, block_k,
               bool(stochastic_mode))
    return o.reshape(B, H, T, D).transpose(0, 2, 1, 3)
