"""Flash attention for TPU in Pallas.

Capability parity with the reference's fused attention kernels — training softmax
(``csrc/transformer/softmax_kernels.cu``) and the attention core of the fused
transformer layer (``csrc/transformer/ds_transformer_cuda.cpp``) — rebuilt as a
blockwise online-softmax kernel so the [T, T] score matrix never materializes in
HBM. This lifts the memory ceiling that forces full-recompute activation
checkpointing on long sequences (the reference's sparse-attention pillar targets the
same ceiling; blocksparse lives in ``blocksparse.py``).

Design (TPU-first, per the Pallas TPU guide):
- grid = (batch*heads, T/Bq): each program owns one q block in VMEM and streams
  k/v blocks with an online (max, sum) rescale — MXU does the two matmuls per
  block, VPU the rescale.
- causal masking skips whole k blocks above the diagonal: the fori_loop bound
  depends on the q block index, so work is triangular like the reference's
  ``attn_softmax`` triangular mode.
- fp32 accumulators; the saved logsumexp rides a 128-lane broadcast layout
  ([BH, T, 128]) because TPU VMEM tiles are (8, 128) — a bare [BH, T] residual
  would violate the layout constraints (same trick as jax's reference TPU kernel).
- backward = two kernels (dq over q blocks; dk/dv over k blocks) using the saved
  logsumexp; delta = rowsum(dO*O) is computed in-kernel from the o/do blocks.
  Wrapped in ``jax.custom_vjp``.
- ``interpret=True`` automatically off-TPU so the same code runs in CPU CI.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128  # TPU lane count; lse residual is broadcast across it


def _interpret() -> bool:
    # DS_TPU_PALLAS_INTERPRET=0 forces real Mosaic lowering even when the
    # process backend is CPU — the AOT compile-only flow (bench pipeline_aot)
    # targets a TPU topology from a CPU host, and interpret-mode HLO would
    # both misrepresent the real program and OOM the compiler
    env = os.environ.get("DS_TPU_PALLAS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "False")
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------- fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale: float,
                causal: bool, block_q: int, block_k: int, kv_len: int,
                q_offset: int, stochastic_mode: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [Bq, D]
    bq = q.shape[0]
    # stochastic mode (parity: ds_transformer_cuda.cpp:63 stochastic_mode —
    # speed over run-exactness): matmul operands stay in the input dtype so
    # the MXU runs its native bf16 pass (fp32 upcast costs multiple passes);
    # accumulation and the softmax state remain fp32
    lo = q_ref.dtype if stochastic_mode else jnp.float32
    q_lo = q.astype(lo)

    acc = jnp.zeros((bq, v_ref.shape[-1]), jnp.float32)
    m_i = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l_i = jnp.zeros((bq, 1), jnp.float32)

    num_k_blocks = kv_len // block_k
    if causal:
        # only blocks intersecting the lower triangle of this q block; q rows sit
        # at absolute positions q_offset + qi*Bq + i (q_offset = kv_len - q_len)
        upper = (q_offset + qi * block_q + block_q + block_k - 1) // block_k
        num_k_blocks = jnp.minimum(num_k_blocks, upper)
    q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 0)

    def body(ki, carry):
        acc, m_i, l_i = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(lo)  # [Bk, D]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(lo)
        s = jax.lax.dot_general(q_lo, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [Bq, Bk]
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_i + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot(p.astype(lo), v,
                                        preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc, m_i, l_i = jax.lax.fori_loop(0, num_k_blocks, body, (acc, m_i, l_i))
    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse = m_i + jnp.log(l_safe)  # [Bq, 1]
    lse_ref[0] = jnp.broadcast_to(lse, (bq, LANES))


def _fwd(q, k, v, sm_scale: float, causal: bool, block_q: int, block_k: int,
         stochastic_mode: bool = False):
    """q,k,v: [BH, T, D] -> (o [BH, T, D], lse [BH, T, LANES])."""
    BH, T, D = q.shape
    S = k.shape[1]
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=S, q_offset=S - T,
        stochastic_mode=stochastic_mode)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------- bwd
def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *,
                   sm_scale: float, causal: bool, block_q: int, block_k: int,
                   kv_len: int, q_offset: int, stochastic_mode: bool):
    qi = pl.program_id(1)
    lo = q_ref.dtype if stochastic_mode else jnp.float32
    q = q_ref[0].astype(lo)
    do = do_ref[0].astype(jnp.float32)
    o = o_ref[0].astype(jnp.float32)
    do_lo = do.astype(lo)
    lse = lse_ref[0][:, :1]  # [Bq, 1]
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [Bq, 1]
    bq = q.shape[0]

    num_k_blocks = kv_len // block_k
    if causal:
        upper = (q_offset + qi * block_q + block_q + block_k - 1) // block_k
        num_k_blocks = jnp.minimum(num_k_blocks, upper)
    q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_k), 0)

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(lo)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(lo)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dp = jax.lax.dot_general(do_lo, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [Bq, Bk]
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot(ds.astype(lo), k,
                                preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_k_blocks, body, jnp.zeros((bq, q_ref.shape[-1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                    block_q: int, block_k: int, q_len: int, q_offset: int,
                    stochastic_mode: bool):
    ki = pl.program_id(1)
    lo = k_ref.dtype if stochastic_mode else jnp.float32
    k = k_ref[0].astype(lo)  # [Bk, D]
    v = v_ref[0].astype(lo)
    bk = k.shape[0]

    # first q block whose absolute position can reach this k block
    first_q_block = jnp.maximum(0, ki * block_k - q_offset) // block_q if causal else 0
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(lo)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        o = o_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_lo = do.astype(lo)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :1]  # [Bq, 1]
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [Bq, Bk]
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p.astype(lo), do_lo,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)  # [Bk, D]
        dp = jax.lax.dot_general(do_lo, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [Bq, Bk]
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(ds.astype(lo), q,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)  # [Bk, D]
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        first_q_block, q_len // block_q, body,
        (jnp.zeros((bk, k.shape[-1]), jnp.float32),
         jnp.zeros((bk, v.shape[-1]), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, stochastic_mode, res, do):
    q, k, v, o, lse = res
    BH, T, D = q.shape
    S = k.shape[1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=S,
                          q_offset=S - T, stochastic_mode=stochastic_mode),
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret(),
    )(q, k, v, o, do, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, q_len=T,
                          q_offset=S - T, stochastic_mode=stochastic_mode),
        grid=(BH, S // block_k),
        in_specs=[
            pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, T, LANES), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, o, do, lse)
    return dq, dk, dv


# --------------------------------------------------------------------------- api
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode):
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode)
    return o


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, stochastic_mode)
    return o, (q, k, v, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, stochastic_mode, res, do):
    return _bwd(sm_scale, causal, block_q, block_k, stochastic_mode, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, H, D]
    v: jnp.ndarray,  # [B, S, H, D]
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    stochastic_mode: bool = False,
) -> jnp.ndarray:
    """Blockwise attention with online softmax; differentiable (custom VJP).

    ``stochastic_mode`` trades bit-exactness for speed (parity:
    ``csrc/transformer/ds_transformer_cuda.cpp:63``): matmul operands ride the
    input dtype onto the MXU's native bf16 pass instead of being upcast to
    fp32; accumulators and softmax state stay fp32. Off by default."""
    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    # shrink blocks to the largest 128-multiple that divides the sequence
    while block_q > 128 and T % block_q:
        block_q //= 2
    while block_k > 128 and S % block_k:
        block_k //= 2
    if T % block_q or S % block_k:
        raise ValueError(f"seq lens ({T},{S}) must divide blocks ({block_q},{block_k})")
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(D)
    # [B, T, H, D] -> [B*H, T, D]
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    o = _flash(qt, kt, vt, scale, causal, block_q, block_k,
               bool(stochastic_mode))
    return o.reshape(B, H, T, D).transpose(0, 2, 1, 3)
