"""Async file I/O handle over the native thread pool.

Capability parity with the reference's ``deepspeed_py_aio_handle.cpp:282``
(``aio_handle`` with submit/wait semantics) and its python surface
(``ops/aio/__init__.py`` AsyncIOBuilder load). Works on numpy arrays (pinned host
memory on a TPU VM is plain host memory).

Falls back to synchronous numpy file I/O when no C++ toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Optional

import numpy as np

from ...utils.logging import warning_once
from ..op_builder import get_builder


class AsyncIOHandle:
    """Submit async reads/writes of numpy buffers; wait on request ids."""

    def __init__(self, num_threads: int = 4):
        self.num_threads = num_threads
        self._lib = None
        self._pool = None
        self._fallback_results: Dict[int, int] = {}
        self._fallback_next = 1
        self._lock = threading.Lock()
        builder = get_builder("ds_aio")
        if builder.is_compatible():
            try:
                self._lib = builder.load()
                self._pool = self._lib.ds_aio_create(num_threads)
            except Exception as e:
                warning_once(f"aio: native build failed ({e}); synchronous fallback")
        else:
            warning_once("aio: no C++ toolchain; synchronous fallback")

    @property
    def is_native(self) -> bool:
        return self._pool is not None

    # ------------------------------------------------------------------ ops
    def pread(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        """Async read ``buf.nbytes`` bytes from ``path`` into ``buf``."""
        assert buf.flags["C_CONTIGUOUS"]
        if self._pool is not None:
            return self._lib.ds_aio_pread(
                self._pool, path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(buf.nbytes), ctypes.c_int64(offset))
        with self._lock:
            rid = self._fallback_next
            self._fallback_next += 1
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(buf.nbytes)
            if len(data) < buf.nbytes:  # short read = corrupt state, like native -EIO
                self._fallback_results[rid] = -5
            else:
                flat = buf.reshape(-1).view(np.uint8)
                flat[: len(data)] = np.frombuffer(data, np.uint8)
                self._fallback_results[rid] = 0
        except OSError as e:
            self._fallback_results[rid] = -e.errno
        return rid

    def pwrite(self, path: str, buf: np.ndarray, offset: int = 0,
               fsync: bool = False) -> int:
        assert buf.flags["C_CONTIGUOUS"]
        if self._pool is not None:
            return self._lib.ds_aio_pwrite(
                self._pool, path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(buf.nbytes), ctypes.c_int64(offset),
                ctypes.c_int(1 if fsync else 0))
        with self._lock:
            rid = self._fallback_next
            self._fallback_next += 1
        try:
            mode = "r+b" if os.path.exists(path) else "wb"
            with open(path, mode) as f:
                f.seek(offset)
                f.write(buf.tobytes())
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            self._fallback_results[rid] = 0
        except OSError as e:
            self._fallback_results[rid] = -e.errno
        return rid

    def wait(self, request_id: int) -> int:
        """Block until the request completes; 0 = success, -errno = failure,
        -22 (EINVAL) for an unknown/already-consumed id."""
        if self._pool is not None:
            return self._lib.ds_aio_wait(self._pool, request_id)
        return self._fallback_results.pop(request_id, -22)

    def drain(self) -> None:
        """Block until every submitted request completes. Raises if any
        fire-and-forget request failed since the last drain."""
        if self._pool is not None:
            rc = self._lib.ds_aio_drain(self._pool)
            if rc < 0:
                raise IOError(f"aio: {-rc} async request(s) failed before drain")
            return
        failed = [r for r in self._fallback_results.values() if r < 0]
        self._fallback_results.clear()
        if failed:
            raise IOError(f"aio: {len(failed)} async request(s) failed before drain")

    def close(self) -> None:
        if self._pool is not None:
            self._lib.ds_aio_destroy(self._pool)
            self._pool = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass
