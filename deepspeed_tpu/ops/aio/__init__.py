from .handle import AsyncIOHandle  # noqa: F401
