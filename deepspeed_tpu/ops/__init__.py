from .optimizers import adagrad, fused_adam, fused_lamb, get_optimizer, sgd

__all__ = ["fused_adam", "fused_lamb", "adagrad", "sgd", "get_optimizer"]
