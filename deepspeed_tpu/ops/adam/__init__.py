from .cpu_adam import DeepSpeedCPUAdam, DeepSpeedCPUAdagrad  # noqa: F401
