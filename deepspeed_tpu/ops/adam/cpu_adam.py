"""Host-side SIMD Adam/Adagrad (ZeRO-Offload's CPU optimizer).

Capability parity with the reference's ``DeepSpeedCPUAdam``
(``ops/adam/cpu_adam.py:12`` wrapping ``csrc/adam/cpu_adam.cpp``) and
``DeepSpeedCPUAdagrad`` (``ops/adagrad/cpu_adagrad.py``): the optimizer step runs
on the host CPU over fp32 master state with hand-written SIMD (AVX2+FMA via
:mod:`deepspeed_tpu.ops.op_builder`), producing a bf16 copy-back buffer for the
device in the same pass (the reference's async fp16 copy-back,
``cpu_adam.cpp:216-239``).

Operates on numpy arrays in place; the engine-side driver is
:class:`deepspeed_tpu.runtime.zero.offload.HostOffloadRunner`. Falls back to a
pure-numpy step when no C++ toolchain is available (is_compatible probing,
parity: ``op_builder/builder.py:236``).
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from ...utils.logging import logger, warning_once
from ..op_builder import get_builder


def _as_f32(x: np.ndarray) -> np.ndarray:
    assert x.dtype == np.float32 and x.flags["C_CONTIGUOUS"]
    return x


def _write_bf16(p: np.ndarray, bf16_out: np.ndarray) -> None:
    """Round-to-nearest-even fp32 -> bf16 (ml_dtypes does the bit math)."""
    import ml_dtypes

    bf16_out[:] = p.astype(ml_dtypes.bfloat16).view(np.uint16)


def _ptr(x: Optional[np.ndarray], typ):
    if x is None:
        return ctypes.cast(None, ctypes.POINTER(typ))
    return x.ctypes.data_as(ctypes.POINTER(typ))


class _NativeLib:
    _lib = None
    _tried = False

    @classmethod
    def get(cls):
        if not cls._tried:
            cls._tried = True
            builder = get_builder("ds_cpu_ops")
            if builder.is_compatible():
                try:
                    cls._lib = builder.load()
                except Exception as e:  # toolchain present but build failed
                    warning_once(f"cpu_adam: native build failed ({e}); numpy fallback")
            else:
                warning_once("cpu_adam: no C++ toolchain; numpy fallback")
        return cls._lib


class DeepSpeedCPUAdam:
    """Fused host Adam/AdamW over flat fp32 arrays (in-place).

    Unlike the torch reference there is no param-group machinery here — the
    offload runner drives one flat buffer per pytree leaf.
    """

    def __init__(self, lr: float = 1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adamw_mode: bool = True, bias_correction: bool = True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self._lib = _NativeLib.get()

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def step(self, p: np.ndarray, m: np.ndarray, v: np.ndarray, g: np.ndarray,
             step_count: int, lr: Optional[float] = None,
             bf16_out: Optional[np.ndarray] = None) -> None:
        """One Adam step over flat arrays; ``step_count`` is 1-based."""
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step_count
            bc2 = 1.0 - b2 ** step_count
        else:
            bc1 = bc2 = 1.0
        n = p.size
        if self._lib is not None:
            self._lib.ds_adam_step(
                _ptr(_as_f32(p), ctypes.c_float), _ptr(_as_f32(m), ctypes.c_float),
                _ptr(_as_f32(v), ctypes.c_float), _ptr(_as_f32(g), ctypes.c_float),
                ctypes.c_int64(n), ctypes.c_float(lr), ctypes.c_float(b1),
                ctypes.c_float(b2), ctypes.c_float(self.eps),
                ctypes.c_float(self.weight_decay), ctypes.c_float(bc1),
                ctypes.c_float(bc2), ctypes.c_int(1 if self.adamw_mode else 0),
                _ptr(bf16_out, ctypes.c_uint16))
            return
        # numpy fallback (same math)
        gi = g if (self.adamw_mode or not self.weight_decay) else g + self.weight_decay * p
        np.multiply(m, b1, out=m)
        m += (1.0 - b1) * gi
        np.multiply(v, b2, out=v)
        v += (1.0 - b2) * gi * gi
        upd = (m / bc1) / (np.sqrt(v / bc2) + self.eps)
        if self.weight_decay and self.adamw_mode:
            upd += self.weight_decay * p
        p -= lr * upd
        if bf16_out is not None:
            _write_bf16(p, bf16_out)


class DeepSpeedCPUAdagrad:
    """Host Adagrad (parity: ``ops/adagrad/cpu_adagrad.py:138``)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._lib = _NativeLib.get()

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def step(self, p: np.ndarray, a: np.ndarray, g: np.ndarray,
             lr: Optional[float] = None,
             bf16_out: Optional[np.ndarray] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            self._lib.ds_adagrad_step(
                _ptr(_as_f32(p), ctypes.c_float), _ptr(_as_f32(a), ctypes.c_float),
                _ptr(_as_f32(g), ctypes.c_float), ctypes.c_int64(p.size),
                ctypes.c_float(lr), ctypes.c_float(self.eps),
                ctypes.c_float(self.weight_decay), _ptr(bf16_out, ctypes.c_uint16))
            return
        gi = g + self.weight_decay * p
        a += gi * gi
        p -= lr * gi / (np.sqrt(a) + self.eps)
        if bf16_out is not None:
            _write_bf16(p, bf16_out)
