"""Grafting utilities: retrofit blocksparse attention onto existing models.

Capability parity with the reference's ``ops/sparse_attention/
sparse_attention_utils.py:225`` (``replace_model_self_attention_with_sparse_
self_attention`` for HF BERT, ``extend_position_embedding`` replicating the
learned position table to longer sequences, ``pad_to_block_size``/unpad).

TPU-native shape: models here are (config, params) pairs, so grafting is a
config transform (``replace_self_attention_with_sparse``) plus a parameter
transform (``extend_position_embedding``) — no module-tree surgery.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ...utils.logging import log_dist
from .sparsity_config import SparsityConfig

_POSITION_KEYS = ("wpe",)  # learned-position tables across model families


def replace_self_attention_with_sparse(cfg, sparsity_config: SparsityConfig):
    """Return a config whose every attention layer runs the blocksparse
    kernel. Works for any model config with a ``sparse_attention`` field
    (GPTConfig, BertConfig). Parity: ``replace_model_self_attention_with_
    sparse_self_attention`` (sparse_attention_utils.py:225).
    """
    if not hasattr(cfg, "sparse_attention"):
        raise TypeError(
            f"{type(cfg).__name__} has no sparse_attention field — model "
            f"family not graftable")
    if sparsity_config.num_heads != cfg.n_head:
        raise ValueError(
            f"sparsity config declares {sparsity_config.num_heads} heads, "
            f"model has {cfg.n_head}")
    new = dataclasses.replace(cfg, sparse_attention=sparsity_config)
    log_dist(f"grafted {type(sparsity_config).__name__} onto "
             f"{type(cfg).__name__} ({cfg.n_layer} layers)")
    return new


def extend_position_embedding(params: Dict[str, Any], new_max_seq: int,
                              key: Optional[str] = None) -> Dict[str, Any]:
    """Stretch a learned position table to ``new_max_seq`` rows by tiling the
    original embeddings (the reference replicates the trained table rather
    than re-initializing — ``extend_position_embedding``). Returns a new
    params dict; pair with ``dataclasses.replace(cfg, max_seq_len=...)``.
    """
    if key is None:
        key = next((k for k in _POSITION_KEYS if k in params), None)
        if key is None:
            raise ValueError(
                f"no learned position table among {_POSITION_KEYS} — rotary/"
                f"ALiBi models extend for free (no table to stretch)")
    table = np.asarray(params[key])
    old = table.shape[0]
    if new_max_seq <= old:
        raise ValueError(f"new_max_seq {new_max_seq} <= current {old}")
    reps = -(-new_max_seq // old)  # ceil
    out = dict(params)
    out[key] = jnp.asarray(np.tile(table, (reps, 1))[:new_max_seq])
    log_dist(f"extended position embedding {old} -> {new_max_seq} "
             f"(tiled x{reps})")
    return out


def pad_to_block_size(input_ids: jnp.ndarray, block: int,
                      pad_token_id: int = 0,
                      attention_mask: Optional[jnp.ndarray] = None
                      ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], int]:
    """Right-pad ``[B, T]`` token ids (and mask) so T is a block multiple —
    the kernel's layout granularity. Returns (ids, mask, pad_len). Parity:
    ``sparse_attention_utils.py`` pad_to_block_size."""
    T = input_ids.shape[-1]
    pad = (-T) % block
    if pad == 0:
        return input_ids, attention_mask, 0
    widths = [(0, 0)] * (input_ids.ndim - 1) + [(0, pad)]
    ids = jnp.pad(input_ids, widths, constant_values=pad_token_id)
    mask = None
    if attention_mask is not None:
        mask = jnp.pad(attention_mask, widths, constant_values=0)
    return ids, mask, pad


def unpad_sequence_output(output: jnp.ndarray, pad_len: int) -> jnp.ndarray:
    """Drop the rows ``pad_to_block_size`` appended ([B, T+pad, ...] -> [B, T, ...])."""
    if pad_len == 0:
        return output
    return output[:, :-pad_len]
