"""Sparse self-attention module.

Capability parity with the reference's ``SparseSelfAttention``
(``ops/sparse_attention/sparse_self_attention.py:11``): computes
softmax(QK^T * scale + mask) V restricted to a :class:`SparsityConfig` block
layout. The reference composes three Triton kernels (SDD matmul, blocksparse
softmax, DSD matmul); here it is one fused Pallas kernel
(:func:`deepspeed_tpu.ops.pallas.blocksparse_attention.blocksparse_attention`).

Layouts are cached per sequence length (parity: the reference's
``master_layout`` buffer + ``get_layout``).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from .sparsity_config import FixedSparsityConfig, SparsityConfig


def sparse_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    config: SparsityConfig,
    causal: Optional[bool] = None,
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Functional one-shot convenience (no cross-call caching — construct a
    :class:`SparseSelfAttention` once for repeated eager use)."""
    return SparseSelfAttention(config, causal=causal)(
        q, k, v, softmax_scale=softmax_scale)


class SparseSelfAttention:
    """Holds a sparsity config; callable on [B, T, H, D] q/k/v. Layouts AND the
    kernel's index tables are cached per sequence length, so eager per-step use
    pays the O(H·n²) table construction once."""

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 causal: Optional[bool] = None):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        attn = getattr(self.sparsity_config, "attention", "bidirectional")
        self.causal = causal if causal is not None else (attn == "unidirectional")
        self._layouts: Dict[int, np.ndarray] = {}
        self._tables: Dict[int, Tuple] = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def _get_tables(self, seq_len: int) -> Tuple:
        if seq_len not in self._tables:
            from ..pallas.blocksparse_attention import layout_tables

            self._tables[seq_len] = tuple(
                jnp.asarray(t) for t in layout_tables(self.get_layout(seq_len)))
        return self._tables[seq_len]

    def density(self, seq_len: int) -> float:
        layout = self.get_layout(seq_len)
        return float(layout.mean())

    def __call__(self, q, k, v, softmax_scale: Optional[float] = None):
        from ..pallas.blocksparse_attention import blocksparse_attention

        B, T, H, D = q.shape
        if H != self.sparsity_config.num_heads:
            raise ValueError(
                f"q has {H} heads but the sparsity config declares "
                f"{self.sparsity_config.num_heads}")
        layout = self.get_layout(T)
        return blocksparse_attention(
            q, k, v, layout, self.sparsity_config.block,
            causal=self.causal, softmax_scale=softmax_scale,
            tables=self._get_tables(T))
