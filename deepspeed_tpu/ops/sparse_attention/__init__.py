from .sparsity_config import (  # noqa: F401
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
from .sparse_self_attention import SparseSelfAttention, sparse_attention  # noqa: F401
