from .sparsity_config import (  # noqa: F401
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
from .sparse_self_attention import SparseSelfAttention, sparse_attention  # noqa: F401
from .sparse_attention_utils import (  # noqa: F401
    extend_position_embedding,
    pad_to_block_size,
    replace_self_attention_with_sparse,
    unpad_sequence_output,
)
