"""Blocksparse attention layout builders.

Capability parity with the reference's sparsity-structure family
(``ops/sparse_attention/sparsity_config.py`` — ``SparsityConfig`` base at ``:9``,
``Dense`` ``:94``, ``Fixed`` ``:243``, ``Variable`` ``:421``, ``BigBird`` ``:559``,
``BSLongformer`` ``:686``, plus the sliding-window structure): each config maps a
sequence length to a **block-level layout** ``[num_heads, T/block, T/block]`` of
0/1 entries; only active blocks are computed by the Pallas kernel
(:mod:`deepspeed_tpu.ops.pallas.blocksparse_attention`).

Patterns follow the originating papers (Sparse Transformers' fixed pattern,
BigBird's window+global+random, Longformer's window+global), re-derived here —
pure numpy, layout algebra only.

TPU note: the reference defaults to 16x16 blocks (GPU warp-friendly); on TPU the
MXU/VMEM tile wants 128-multiples, so the default ``block=128``. Any block size
works functionally (CPU CI uses small blocks).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base. Parity: ``sparsity_config.py:9``."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} must be a multiple of block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=np.int64)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    @staticmethod
    def _check_attention(attention: str) -> str:
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention type {attention!r}")
        return attention

    def _global_cols_mask(self, n: int, global_block_indices,
                          global_block_end_indices) -> np.ndarray:
        """Boolean column mask from explicit global block indices (optionally
        start/end ranges)."""
        cols = np.zeros(n, dtype=bool)
        if global_block_end_indices is None:
            for i in global_block_indices:
                if 0 <= i < n:
                    cols[i] = True
        else:
            for s, e in zip(global_block_indices, global_block_end_indices):
                cols[max(0, s):min(e, n)] = True
        return cols

    def _finalize(self, layout: np.ndarray, causal: bool) -> np.ndarray:
        if causal:
            n = layout.shape[1]
            tril = np.tril(np.ones((n, n), dtype=np.int64))
            layout = layout * tril
        # every query block must see at least its own diagonal block, or its
        # softmax rows would be empty
        idx = np.arange(layout.shape[1])
        layout[:, idx, idx] = 1
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active. Parity: ``sparsity_config.py:94``."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers 'fixed' pattern. Parity: ``sparsity_config.py:243``.

    Queries attend within their local window of ``num_local_blocks`` blocks, plus
    to the trailing ``num_global_blocks`` blocks of every preceding window (the
    'summary' columns). ``num_different_global_patterns`` rotates which slice of
    the window acts as the summary across heads (requires
    ``different_layout_per_head``).
    """

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must divide by num_global_blocks")
        attention = self._check_attention(attention)
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "num_different_global_patterns > 1 requires different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError("too many global patterns for the window size")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        causal = self.attention == "unidirectional"
        for h in range(self.num_heads):
            pattern = (h % self.num_different_global_patterns
                       if self.different_layout_per_head else 0)
            # global columns sit at the (last - pattern*G) slice of each window
            first = L - (pattern + 1) * G
            for i in range(n):
                w0 = (i // L) * L
                # local window
                layout[h, i, w0:min(w0 + L, n)] = 1
                # global columns of every window
                for w in range(0, n, L):
                    g0 = w + first
                    layout[h, i, g0:min(g0 + G, n)] = 1
                if self.horizontal_global_attention and (i - w0) >= first \
                        and (i - w0) < first + G:
                    layout[h, i, :] = 1  # global row
        return self._finalize(layout, causal)


class VariableSparsityConfig(SparsityConfig):
    """Variable local windows + explicit global blocks + random blocks.
    Parity: ``sparsity_config.py:421``."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        attention = self._check_attention(attention)
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed
        if self.global_block_end_indices is not None and \
                len(self.global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global start/end index lists must have equal length")

    def _global_cols(self, n: int) -> np.ndarray:
        return self._global_cols_mask(
            n, self.global_block_indices, self.global_block_end_indices)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        causal = self.attention == "unidirectional"
        rng = np.random.default_rng(self.seed)
        gcols = self._global_cols(n)
        for h in range(self.num_heads):
            # variable local windows: consecutive windows take sizes from the
            # list; the last size repeats
            i = 0
            wi = 0
            while i < n:
                size = self.local_window_blocks[min(wi, len(self.local_window_blocks) - 1)]
                hi = min(i + size, n)
                layout[h, i:hi, i:hi] = 1
                i = hi
                wi += 1
            layout[h, :, gcols] = 1
            if self.horizontal_global_attention:
                layout[h, gcols, :] = 1
            for _ in range(self.num_random_blocks):
                cols = rng.integers(0, n, size=n)
                layout[h, np.arange(n), cols] = 1
            if not self.different_layout_per_head:
                layout[1:] = layout[0]
                break
        return self._finalize(layout, causal)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: sliding window + global first/last + random. Parity:
    ``sparsity_config.py:559``."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = self._check_attention(attention)
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        causal = self.attention == "unidirectional"
        w = self.num_sliding_window_blocks // 2
        G = min(self.num_global_blocks, n)
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_heads):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = 1  # window
            layout[h, :, :G] = 1  # global cols (first blocks)
            layout[h, :G, :] = 1  # global rows
            if not causal:
                layout[h, :, n - G:] = 1
                layout[h, n - G:, :] = 1
            for i in range(n):
                lo, hi = (0, max(1, i - w)) if causal else (0, n)
                k = min(self.num_random_blocks, hi - lo)
                if k > 0:
                    cols = rng.choice(np.arange(lo, hi), size=k, replace=False)
                    layout[h, i, cols] = 1
            if not self.different_layout_per_head:
                layout[1:] = layout[0]
                break
        return self._finalize(layout, causal)


class BSLongformerSparsityConfig(SparsityConfig):
    """Blocksparse Longformer: sliding window + designated global blocks.
    Parity: ``sparsity_config.py:686``."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = self._check_attention(attention)
        if self.global_block_end_indices is not None and \
                len(self.global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global start/end index lists must have equal length")

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        causal = self.attention == "unidirectional"
        w = self.num_sliding_window_blocks // 2
        gcols = self._global_cols_mask(
            n, self.global_block_indices, self.global_block_end_indices)
        for h in range(self.num_heads):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = 1
            layout[h, :, gcols] = 1
            layout[h, gcols, :] = 1
        return self._finalize(layout, causal)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (the reference's sliding-window structure)."""

    def __init__(self, num_heads: int, block: int = 128,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = self._check_attention(attention)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        causal = self.attention == "unidirectional"
        w = self.num_sliding_window_blocks // 2 if not causal \
            else self.num_sliding_window_blocks - 1
        for i in range(n):
            lo = max(0, i - w)
            hi = i + 1 if causal else min(n, i + w + 1)
            layout[:, i, lo:hi] = 1
        return self._finalize(layout, causal)
