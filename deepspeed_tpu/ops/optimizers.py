"""Fused optimizers.

Capability parity with the reference's native optimizer kernels:
- ``FusedAdam``   (CUDA multi-tensor Adam, ``csrc/adam/multi_tensor_adam.cu``,
  wrapper ``ops/adam/fused_adam.py:16``)
- ``FusedLamb``   (``csrc/lamb/fused_lamb_cuda_kernel.cu``, ``ops/lamb/fused_lamb.py:16``)
- ``Adagrad``     (``csrc/adagrad/cpu_adagrad.cpp``)
- ``SGD`` / momentum.

TPU-native design: the reference needs hand-written multi-tensor CUDA kernels because
eager torch launches one kernel per tensor per op. Under ``jit`` the whole update is
one XLA program — tree-wide elementwise math fuses into a handful of kernels across
all parameters automatically, which *is* the multi-tensor-apply optimization. The
update math below is written tree-at-once and dtype-explicit (state in fp32, params
may be bf16 masters handled by the precision layer).

Interface: ``init(params) -> state`` and
``update(grads, state, params, lr) -> (new_params, new_state)`` with ``lr`` a traced
scalar so LR schedules live inside the compiled step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Any
Grads = Any
State = Any


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """A pure optimizer: pytree-in, pytree-out, safe to call inside jit.

    ``state_spec(param_like, scalar_like)`` maps a per-param-leaf value tree (e.g.
    shardings) + a scalar value into the optimizer-state structure, so the engine can
    place ZeRO-sharded optimizer state without knowing each optimizer's layout.
    """

    init: Callable[[Params], State]
    update: Callable[[Grads, State, Params, jnp.ndarray], Tuple[Params, State]]
    state_spec: Callable[[Any, Any], Any] = None
    name: str = "optimizer"


class AdamState(NamedTuple):
    count: jnp.ndarray
    mu: Params
    nu: Params


def _tree_zeros_like(params, dtype=jnp.float32):
    return jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, dtype), params)


def fused_adam(
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
) -> Optimizer:
    """Adam/AdamW. Parity: ``ops/adam/fused_adam.py:16`` (FusedAdam)."""
    b1, b2 = betas

    def init(params):
        return AdamState(count=jnp.zeros((), jnp.int32),
                         mu=_tree_zeros_like(params), nu=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - b1 ** cf
            bc2 = 1.0 - b2 ** cf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def upd(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay and not adam_w_mode:  # L2-style
                g = g + weight_decay * p32
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            denom = jnp.sqrt(v_new / bc2) + eps
            step_ = (m_new / bc1) / denom
            if weight_decay and adam_w_mode:  # decoupled
                step_ = step_ + weight_decay * p32
            return (p32 - lr * step_).astype(p.dtype), m_new, v_new

        flat = jax.tree_util.tree_map(upd, grads, state.mu, state.nu, params)
        new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                            is_leaf=lambda x: isinstance(x, tuple))
        new_mu = jax.tree_util.tree_map(lambda t: t[1], flat,
                                        is_leaf=lambda x: isinstance(x, tuple))
        new_nu = jax.tree_util.tree_map(lambda t: t[2], flat,
                                        is_leaf=lambda x: isinstance(x, tuple))
        return new_params, AdamState(count=count, mu=new_mu, nu=new_nu)

    return Optimizer(init=init, update=update, name="FusedAdam",
                     state_spec=lambda per_param, scalar: AdamState(
                         count=scalar, mu=per_param, nu=per_param))


def fused_lamb(
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    max_coeff: float = 10.0,
    min_coeff: float = 0.01,
    bias_correction: bool = True,
) -> Optimizer:
    """LAMB with per-tensor trust ratio. Parity: ``ops/lamb/fused_lamb.py:16``."""
    b1, b2 = betas

    def init(params):
        return AdamState(count=jnp.zeros((), jnp.int32),
                         mu=_tree_zeros_like(params), nu=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf if bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - b2 ** cf if bias_correction else jnp.float32(1.0)

        def upd(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            upd_ = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay:
                upd_ = upd_ + weight_decay * p32
            # NOTE: per-tensor norms; with ZeRO-sharded tensors these are norms of the
            # full logical tensor because jnp reductions see the global array.
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(upd_)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
            return (p32 - lr * trust * upd_).astype(p.dtype), m_new, v_new

        flat = jax.tree_util.tree_map(upd, grads, state.mu, state.nu, params)
        is_tup = lambda x: isinstance(x, tuple)  # noqa: E731
        return (jax.tree_util.tree_map(lambda t: t[0], flat, is_leaf=is_tup),
                AdamState(count=count,
                          mu=jax.tree_util.tree_map(lambda t: t[1], flat, is_leaf=is_tup),
                          nu=jax.tree_util.tree_map(lambda t: t[2], flat, is_leaf=is_tup)))

    return Optimizer(init=init, update=update, name="FusedLamb",
                     state_spec=lambda per_param, scalar: AdamState(
                         count=scalar, mu=per_param, nu=per_param))


class AdagradState(NamedTuple):
    count: jnp.ndarray
    accum: Params


def adagrad(eps: float = 1e-10, weight_decay: float = 0.0,
            initial_accumulator_value: float = 0.0) -> Optimizer:
    """Parity: ``ops/adagrad/cpu_adagrad.py`` (DeepSpeedCPUAdagrad math)."""

    def init(params):
        return AdagradState(
            count=jnp.zeros((), jnp.int32),
            accum=jax.tree_util.tree_map(
                lambda p: jnp.full(p.shape, initial_accumulator_value, jnp.float32), params))

    def update(grads, state, params, lr):
        def upd(g, a, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p32
            a_new = a + g * g
            return (p32 - lr * g / (jnp.sqrt(a_new) + eps)).astype(p.dtype), a_new

        flat = jax.tree_util.tree_map(upd, grads, state.accum, params)
        is_tup = lambda x: isinstance(x, tuple)  # noqa: E731
        return (jax.tree_util.tree_map(lambda t: t[0], flat, is_leaf=is_tup),
                AdagradState(count=state.count + 1,
                             accum=jax.tree_util.tree_map(lambda t: t[1], flat, is_leaf=is_tup)))

    return Optimizer(init=init, update=update, name="Adagrad",
                     state_spec=lambda per_param, scalar: AdagradState(
                         count=scalar, accum=per_param))


class SGDState(NamedTuple):
    momentum: Optional[Params]


def sgd(momentum: float = 0.0, weight_decay: float = 0.0, nesterov: bool = False) -> Optimizer:
    def init(params):
        return SGDState(momentum=_tree_zeros_like(params) if momentum else None)

    def update(grads, state, params, lr):
        def upd(g, m, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p32
            if momentum:
                m_new = momentum * m + g
                g_eff = g + momentum * m_new if nesterov else m_new
            else:
                m_new, g_eff = m, g
            return (p32 - lr * g_eff).astype(p.dtype), m_new

        if momentum:
            flat = jax.tree_util.tree_map(upd, grads, state.momentum, params)
            is_tup = lambda x: isinstance(x, tuple)  # noqa: E731
            return (jax.tree_util.tree_map(lambda t: t[0], flat, is_leaf=is_tup),
                    SGDState(momentum=jax.tree_util.tree_map(lambda t: t[1], flat, is_leaf=is_tup)))
        new_params = jax.tree_util.tree_map(
            lambda g, p: (p.astype(jnp.float32) - lr * g.astype(jnp.float32)).astype(p.dtype),
            grads, params)
        return new_params, state

    return Optimizer(init=init, update=update, name="SGD",
                     state_spec=lambda per_param, scalar: SGDState(
                         momentum=per_param if momentum else None))


# --------------------------------------------------------------------------- registry
def get_optimizer(name: str, params: Dict[str, Any]) -> Optimizer:
    """Build an optimizer from a DeepSpeed ``"optimizer"`` config block.

    Parity: ``runtime/engine.py:1315`` (_configure_basic_optimizer) name dispatch.
    1-bit variants return their dense counterparts — that IS the warmup-phase math;
    the engine routes the compressed stage through
    :class:`deepspeed_tpu.runtime.fp16.onebit.OnebitRunner`.
    """
    name_l = name.lower()
    lr_ignored = {k: v for k, v in params.items() if k != "lr"}
    betas = tuple(lr_ignored.get("betas", (0.9, 0.999)))
    eps = lr_ignored.get("eps", 1e-8)
    wd = lr_ignored.get("weight_decay", 0.0)
    if name_l in ("adam", "adamw", "fusedadam"):
        return fused_adam(betas=betas, eps=eps, weight_decay=wd,
                          adam_w_mode=(name_l != "adam") or lr_ignored.get("adam_w_mode", True),
                          bias_correction=lr_ignored.get("bias_correction", True))
    if name_l in ("onebitadam", "zerooneadam"):
        return fused_adam(betas=betas, eps=eps, weight_decay=wd)
    if name_l in ("lamb", "fusedlamb", "onebitlamb"):
        return fused_lamb(betas=betas, eps=eps, weight_decay=wd,
                          max_coeff=lr_ignored.get("max_coeff", 10.0),
                          min_coeff=lr_ignored.get("min_coeff", 0.01))
    if name_l == "adagrad":
        return adagrad(eps=lr_ignored.get("eps", 1e-10), weight_decay=wd)
    if name_l == "sgd":
        return sgd(momentum=lr_ignored.get("momentum", 0.0), weight_decay=wd,
                   nesterov=lr_ignored.get("nesterov", False))
    raise ValueError(f"unknown optimizer type {name!r}")
