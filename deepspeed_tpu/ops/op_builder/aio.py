"""Builder for the async file-I/O library (parity: ``op_builder/async_io.py``)."""

from __future__ import annotations

import ctypes

from .builder import OpBuilder


class AsyncIOBuilder(OpBuilder):
    NAME = "ds_aio"
    SOURCES = ["aio.cpp"]
    EXTRA_LDFLAGS = ["-lpthread"]

    def load(self) -> ctypes.CDLL:
        lib = super().load()
        assert lib.ds_aio_version() == 1
        lib.ds_aio_create.restype = ctypes.c_void_p
        lib.ds_aio_create.argtypes = [ctypes.c_int]
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_pread.restype = ctypes.c_int
        lib.ds_aio_pread.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64]
        lib.ds_aio_pwrite.restype = ctypes.c_int
        lib.ds_aio_pwrite.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_wait.restype = ctypes.c_int
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ds_aio_drain.restype = ctypes.c_int
        lib.ds_aio_drain.argtypes = [ctypes.c_void_p]
        return lib
