"""JIT builder for native (C++) components.

Capability parity with the reference's ``op_builder/`` system (``builder.py:112``
``OpBuilder.load()/jit_load()``, compatibility probing ``:236-465``): one builder
class per native op, lazily compiled on first use with the results cached, plus an
``is_compatible()`` probe so ops degrade gracefully where the toolchain or CPU
features are missing.

TPU-native differences: there is no CUDA arch matrix; native components here are
host-side C++ (SIMD optimizers for ZeRO-Offload, async file I/O for
ZeRO-Infinity-style swapping) loaded via ``ctypes`` — no torch extension machinery,
no pybind11 dependency. Feature probing is try-compile (``-mavx2 -mfma``,
``-fopenmp``) instead of compute-capability filtering.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import Dict, List, Optional

from ...utils.logging import logger

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
CSRC_DIR = os.path.join(_REPO_ROOT, "csrc")


def _build_dir() -> str:
    d = os.environ.get(
        "DS_TPU_BUILD_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "build"))
    os.makedirs(d, exist_ok=True)
    return d


def _cpu_supports(feature: str) -> bool:
    """True if /proc/cpuinfo lists the feature; optimistic (True) where cpuinfo
    is unavailable (non-Linux) so the try-compile gate still decides there."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return feature in line.split()
    except OSError:
        return True
    return False


def _try_compile(cxx: str, flags: List[str]) -> bool:
    src = "int main(){return 0;}"
    with tempfile.TemporaryDirectory() as td:
        sp = os.path.join(td, "probe.cpp")
        with open(sp, "w") as f:
            f.write(src)
        try:
            r = subprocess.run([cxx, *flags, sp, "-o", os.path.join(td, "a.out")],
                               capture_output=True, timeout=60)
            return r.returncode == 0
        except Exception:
            return False


class OpBuilder:
    """Base: compile ``sources`` (paths under ``csrc/``) into one shared object."""

    NAME = "op"
    SOURCES: List[str] = []
    EXTRA_FLAGS: List[str] = []
    EXTRA_LDFLAGS: List[str] = []

    def __init__(self):
        self._lib: Optional[ctypes.CDLL] = None

    # -------------------------------------------------------------- probing
    def cxx(self) -> Optional[str]:
        return shutil.which(os.environ.get("CXX", "g++")) or shutil.which("clang++")

    def is_compatible(self) -> bool:
        cxx = self.cxx()
        if cxx is None:
            logger.warning(f"{self.NAME}: no C++ compiler found")
            return False
        return all(os.path.exists(os.path.join(CSRC_DIR, s)) for s in self.SOURCES)

    def simd_flags(self) -> List[str]:
        cxx = self.cxx()
        flags = []
        if os.environ.get("DS_TPU_DISABLE_SIMD"):
            return flags
        # the compiler accepting -mavx2 says nothing about the host CPU; gate on
        # the actual cpuinfo flags or the binary dies with SIGILL at first use
        if _cpu_supports("avx2") and _cpu_supports("fma") and \
                _try_compile(cxx, ["-mavx2", "-mfma"]):
            flags += ["-mavx2", "-mfma"]
        if _try_compile(cxx, ["-fopenmp"]):
            flags += ["-fopenmp"]
        return flags

    # -------------------------------------------------------------- build
    def _signature(self, cmd: List[str]) -> str:
        h = hashlib.sha256(" ".join(cmd).encode())
        for s in self.SOURCES:
            with open(os.path.join(CSRC_DIR, s), "rb") as f:
                h.update(f.read())
        return h.hexdigest()[:16]

    def build(self) -> str:
        cxx = self.cxx()
        if cxx is None:
            raise RuntimeError(f"{self.NAME}: no C++ compiler available")
        srcs = [os.path.join(CSRC_DIR, s) for s in self.SOURCES]
        base_flags = ["-O3", "-shared", "-fPIC", "-std=c++17", *self.simd_flags(),
                      *self.EXTRA_FLAGS]
        cmd = [cxx, *base_flags, *srcs]
        sig = self._signature(cmd)
        out = os.path.join(_build_dir(), f"{self.NAME}-{sig}.so")
        if os.path.exists(out):
            return out
        tmp = f"{out}.{os.getpid()}.tmp"  # unique per process: concurrent cold
        # builds each publish atomically via os.replace instead of interleaving
        r = subprocess.run([*cmd, "-o", tmp, *self.EXTRA_LDFLAGS],
                           capture_output=True, text=True, timeout=600)
        if r.returncode != 0:
            raise RuntimeError(
                f"{self.NAME}: native build failed:\n{r.stderr[-2000:]}")
        os.replace(tmp, out)
        logger.info(f"{self.NAME}: built {os.path.basename(out)} "
                    f"({' '.join(base_flags)})")
        return out

    def load(self) -> ctypes.CDLL:
        """Compile (cached) + dlopen. Parity: ``OpBuilder.load`` (``builder.py:474``)."""
        if self._lib is None:
            self._lib = ctypes.CDLL(self.build())
        return self._lib


class CpuOpBuilder(OpBuilder):
    """Host SIMD optimizers (parity: ``op_builder/cpu_adam.py`` + adagrad)."""

    NAME = "ds_cpu_ops"
    SOURCES = ["cpu_adam.cpp"]

    def load(self) -> ctypes.CDLL:
        lib = super().load()
        assert lib.ds_cpu_ops_version() >= 1
        import ctypes as ct

        lib.ds_adam_step.argtypes = [
            ct.POINTER(ct.c_float), ct.POINTER(ct.c_float), ct.POINTER(ct.c_float),
            ct.POINTER(ct.c_float), ct.c_int64, ct.c_float, ct.c_float, ct.c_float,
            ct.c_float, ct.c_float, ct.c_float, ct.c_float, ct.c_int,
            ct.POINTER(ct.c_uint16)]
        lib.ds_adagrad_step.argtypes = [
            ct.POINTER(ct.c_float), ct.POINTER(ct.c_float), ct.POINTER(ct.c_float),
            ct.c_int64, ct.c_float, ct.c_float, ct.c_float, ct.POINTER(ct.c_uint16)]
        return lib


_builders: Dict[str, OpBuilder] = {}


def get_builder(name: str) -> OpBuilder:
    """Registry access. Parity: ``op_builder/all_ops.py``."""
    if name not in _builders:
        classes = {cls.NAME: cls for cls in (CpuOpBuilder,)}
        try:
            from .aio import AsyncIOBuilder  # noqa: F401 (registered on import)

            classes[AsyncIOBuilder.NAME] = AsyncIOBuilder
        except ImportError:
            pass
        if name not in classes:
            raise KeyError(f"unknown op builder {name!r}")
        _builders[name] = classes[name]()
    return _builders[name]
