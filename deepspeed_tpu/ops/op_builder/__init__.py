from .builder import CpuOpBuilder, OpBuilder, get_builder  # noqa: F401
