"""Ring attention: exact attention over sequences sharded across the ``sp`` axis.

The long-context pillar. The reference fork predates sequence parallelism — its
long-sequence story is blocksparse attention (``ops/sparse_attention/``) and
activation partitioning (``activation_checkpointing/checkpointing.py:372``); SURVEY.md
§5 directs this build to provide real SP as the capability equivalent.

Design (Ring Attention with blockwise softmax, à la Liu et al. 2023, TPU-first):

- Q/K/V live sharded on the sequence axis: ``P(batch, "sp", heads, None)`` — each
  of the S devices holds one contiguous sequence block.
- K/V blocks rotate around the ring with ``jax.lax.ppermute`` (neighbor hops over
  ICI) while each device's Q block stays resident. After S hops every Q block has
  seen every K/V block: exact attention, O(T/S) memory per device, compute
  overlapping the permute (XLA schedules the next block's matmul against the
  in-flight collective).
- The running (max, denominator, accumulator) triple is the same online-softmax
  recurrence the flash kernel uses, so precision matches the fused path (fp32
  accumulation).
- Causality: block ``j`` contributes to query block ``i`` fully when ``j < i``,
  with a triangular mask when ``j == i``, not at all when ``j > i`` (masked to
  ``-inf`` — all ranks run the same program, SPMD-style).

Autodiff gives the backward ring for free (transpose of ``ppermute`` is the
reverse permute), replacing hand-written backward comm.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import shard_map

_NEG_INF = jnp.float32(-1e30)


def _block_update(q, k, v, m, l, acc, allowed_mask, scale):
    """One online-softmax accumulation step against K/V block (k, v).

    q: [B, Tq, H, Dh]; k/v: [B, Tk, H, Dh]; m/l: [B, H, Tq]; acc: [B, Tq, H, Dh];
    allowed_mask: [Tq, Tk] bool (True = may attend).
    """
    s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    s = jnp.where(allowed_mask[None, None, :, :], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # all-masked rows keep m at -1e30; exp(s - m) is then exp(0)=1 on masked
    # entries — guard by masking p as well
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(allowed_mask[None, None, :, :], p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhts,bshd->bthd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          softmax_scale: Optional[float]):
    """Per-shard body: q/k/v are the LOCAL sequence blocks [B, Tl, H, Dh]."""
    B, Tl, H, Dh = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(Dh)
    size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    m0 = jnp.full((B, H, Tl), _NEG_INF)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, Tl, H, Dh), jnp.float32)
    tri = jnp.tril(jnp.ones((Tl, Tl), bool))  # intra-block causal mask

    # rotate K/V: source p sends to p-1, so at step r we hold block (my_idx + r) % S
    perm = [(p, (p - 1) % size) for p in range(size)]

    def step(carry, r):
        k_blk, v_blk, m, l, acc = carry
        j = (my_idx + r) % size  # origin of the block we currently hold
        if causal:
            allowed = jnp.where(
                j < my_idx, jnp.ones((Tl, Tl), bool),
                jnp.where(j == my_idx, tri, jnp.zeros((Tl, Tl), bool)))
        else:
            allowed = jnp.ones((Tl, Tl), bool)
        m, l, acc = _block_update(q, k_blk, v_blk, m, l, acc, allowed, scale)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    (k, v, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(size))
    # normalize; fully-masked rows (can't happen with causal: own block always
    # contributes) guarded by the max
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, T, H, Dh] — T sharded over `axis_name`
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "sp",
    batch_axes=("dp", "ep"),
    head_axis: Optional[str] = "tp",
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Composes with data parallelism (batch over ``batch_axes``) and tensor
    parallelism (heads over ``head_axis``): the ring only ever communicates over
    ``axis_name`` neighbors.
    """
    spec = P(batch_axes, axis_name, head_axis, None)
    body = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal,
        softmax_scale=softmax_scale)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
