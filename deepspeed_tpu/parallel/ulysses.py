"""Ulysses-style sequence parallelism: all-to-all head-scatter / sequence-gather.

Capability-equivalent long-context mechanism (SURVEY.md §5 "long-context pillar").
Complementary to :mod:`.ring_attention`:

- **ring**: K/V rotate; comm volume O(T·D) per device per step, S neighbor hops —
  best when T is huge and heads are few.
- **ulysses**: one ``all_to_all`` converts sequence sharding into head sharding,
  attention runs *locally* over the full sequence with H/S heads, a second
  ``all_to_all`` converts back — two collectives total, best when H ≥ S and T
  moderate. Maps directly onto ``jax.lax.all_to_all`` over the ``sp`` mesh axis
  (the reference's EP dispatch uses the same primitive shape, ``moe/sharded_moe.py:89``).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import shard_map

from ..ops.attention import dot_product_attention


def _ulysses_local(q, k, v, attn_fn: Callable, axis_name: str):
    """Per-shard body. In: [B, T/S, H, Dh] (sequence-sharded). all_to_all to
    [B, T, H/S, Dh], local attention over the full sequence, all_to_all back."""
    # scatter heads (axis 2), gather sequence (axis 1)
    q = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = attn_fn(q, k, v)
    # scatter sequence, gather heads: back to [B, T/S, H, Dh]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,  # [B, T, H, Dh] — T sharded over `axis_name`
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "sp",
    batch_axes=("dp", "ep"),
    head_axis: Optional[str] = "tp",
    attn_fn: Optional[Callable] = None,
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention via two all-to-alls. The head count must divide
    by the ``axis_name`` extent (times ``head_axis`` extent if TP-sharded)."""
    if attn_fn is None:
        attn_fn = functools.partial(dot_product_attention, causal=causal,
                                    softmax_scale=softmax_scale)
    spec = P(batch_axes, axis_name, head_axis, None)
    body = functools.partial(_ulysses_local, attn_fn=attn_fn, axis_name=axis_name)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
