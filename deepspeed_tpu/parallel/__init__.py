"""Sequence/context parallelism (the long-context pillar, SURVEY.md §5).

The reference has no sequence parallelism (it predates Ulysses/ring attention);
its long-context capability is blocksparse attention. This package provides the
modern capability equivalents over the ``sp`` mesh axis.
"""

from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
