"""Compression config parsing.

Capability parity with the reference's ``compression/config.py``: normalizes the
``"compression_training"`` JSON block — weight quantization (MoQ),
activation quantization, sparse/row/head/channel pruning, layer reduction —
into a flat, defaulted structure. Schema keys follow the reference
(``compression/constants.py``).
"""

from __future__ import annotations

from typing import Any, Dict


def _shared(block: Dict[str, Any], defaults: Dict[str, Any]) -> Dict[str, Any]:
    shared = dict(defaults)
    shared.update(block.get("shared_parameters", {}))
    return shared


def get_compression_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a ``compression_training`` dict (missing pieces -> disabled)."""
    cfg = cfg or {}
    out: Dict[str, Any] = {}

    wq = cfg.get("weight_quantization", {})
    out["weight_quantization"] = {
        "shared": _shared(wq, {
            "enabled": False,
            "quantizer_kernel": False,
            "schedule_offset": 0,
            "quantize_groups": 1,
            "quantize_verbose": False,
            "quantization_type": "symmetric",
            "quantize_weight_in_forward": True,
            "rounding": "nearest",
            "fp16_mixed_quantize": False,
        }),
        "groups": wq.get("different_groups", {}),
    }

    aq = cfg.get("activation_quantization", {})
    out["activation_quantization"] = {
        "shared": _shared(aq, {
            "enabled": False,
            "quantization_type": "symmetric",
            "range_calibration": "dynamic",
            "schedule_offset": 0,
        }),
        "groups": aq.get("different_groups", {}),
    }

    for name in ("sparse_pruning", "row_pruning", "head_pruning", "channel_pruning"):
        blk = cfg.get(name, {})
        out[name] = {
            "shared": _shared(blk, {
                "enabled": False,
                "method": "l1",
                "schedule_offset": 0,
            }),
            "groups": blk.get("different_groups", {}),
        }

    lr = cfg.get("layer_reduction", {})
    out["layer_reduction"] = {
        "enabled": lr.get("enabled", False),
        "keep_number_layer": lr.get("keep_number_layer"),
        "teacher_layer": lr.get("teacher_layer", []),
        "module_name_prefix": lr.get("module_name_prefix", ""),
        "other_module_name": lr.get("other_module_name", []),
    }
    return out
