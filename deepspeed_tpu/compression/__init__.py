from .compress import (  # noqa: F401
    CompressionScheduler,
    init_compression,
    quantize_params_for_inference,
)
from .config import get_compression_config  # noqa: F401
