from .compress import (  # noqa: F401
    CompressionScheduler,
    init_compression,
    layer_reduction_map,
    quantize_params_for_inference,
    redundancy_clean,
)
from .config import get_compression_config  # noqa: F401
