"""Compression-in-training: QAT weight quantization (MoQ), pruning masks,
layer reduction, and post-training int8 weight quantization for inference.

Capability parity with the reference's compression stack
(``compression/compress.py`` ``init_compression``/``redundancy_clean``,
``compression/basic_layer.py`` LinearLayer_Compress et al.,
``compression/scheduler.py`` compression scheduler): the reference swaps
nn.Modules for compression-aware clones; in a functional JAX framework the same
capability is a **parameter-tree transform** applied inside the jitted loss —
fake-quant (straight-through) and pruning masks gate on the traced global step
against each group's ``schedule_offset``, so one compiled program covers the
whole schedule.

Param selection: the reference keys groups on module-name patterns; here
patterns match the parameter tree's key paths (substring or fnmatch). Default
targets are matmul weights (ndim >= 2), excluding embeddings and norms.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.quantizer import annealed_bits, fake_quant, fake_quant_dynamic, quantize
from ..utils.logging import log_dist
from .config import get_compression_config

# params whose key path contains one of these are never quantized/pruned
_EXCLUDE_DEFAULT = ("ln", "layernorm", "norm", "bias", "wpe", "wte", "embed")


def _is_weight(key: str, leaf) -> bool:
    """Quantize/prune matmul weights only — stacked per-layer biases are 2-D
    ([L, F]) but are still biases (the reference GroupQuantizer is weights-only)."""
    if not hasattr(leaf, "ndim") or leaf.ndim < 2:
        return False
    last = key.rsplit("/", 1)[-1].lower()
    return not (last.endswith("_b") or "bias" in last)


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                    for p in path)


def _key_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_path_str(path), leaf) for path, leaf in flat]


def _matches(key: str, patterns: List[str]) -> bool:
    k = key.lower()
    return any(p.lower() in k or fnmatch.fnmatch(k, p.lower()) for p in patterns)


class CompressionScheduler:
    """Per-leaf compression plan applied inside the training step."""

    def __init__(self, config: Dict[str, Any], param_tree):
        self.cfg = get_compression_config(config)
        # resolve each leaf to its (bits, groups, offset) plan at build time
        self.plan: Dict[str, Dict[str, Any]] = {}
        wq = self.cfg["weight_quantization"]
        sp = self.cfg["sparse_pruning"]
        for key, leaf in _key_paths(param_tree):
            if not _is_weight(key, leaf) or _matches(key, list(_EXCLUDE_DEFAULT)):
                continue
            entry: Dict[str, Any] = {}
            if wq["shared"]["enabled"]:
                gp = self._group_params(key, wq["groups"])
                entry["quant_bits"] = int(gp.get("start_bits", 8))
                entry["quant_groups"] = int(gp.get(
                    "quantize_groups", wq["shared"]["quantize_groups"]))
                entry["quant_offset"] = int(wq["shared"]["schedule_offset"])
                # progressive MoQ: bits anneal start->target over doubling
                # periods (parity: runtime/quantize.py compute_quantization)
                entry["quant_target_bits"] = int(gp.get(
                    "target_bits", entry["quant_bits"]))
                entry["quant_period"] = int(gp.get(
                    "quantization_period", 1000))
            if sp["shared"]["enabled"]:
                ratio, _ = self._group_lookup(
                    key, sp["groups"], ("dense_ratio", 0.5), ("unused", 0))
                entry["prune_ratio"] = float(ratio)
                entry["prune_offset"] = int(sp["shared"]["schedule_offset"])
                entry["prune_method"] = sp["shared"]["method"]
            # structured pruning families (parity: compression/basic_layer.py
            # LinearLayer_Compress row/head pruning, Conv2dLayer channel)
            rp = self.cfg["row_pruning"]
            if rp["shared"]["enabled"] and leaf.ndim >= 2:
                ratio, _ = self._group_lookup(
                    key, rp["groups"], ("dense_ratio", 0.5), ("unused", 0))
                entry["row_ratio"] = float(ratio)
                entry["row_offset"] = int(rp["shared"]["schedule_offset"])
            hp = self.cfg["head_pruning"]
            if hp["shared"]["enabled"] and "attn_out" in key.lower():
                gp = self._group_params(key, hp["groups"])
                heads = gp.get("num_heads", hp["shared"].get("num_heads"))
                if heads is None:
                    raise ValueError(
                        "head_pruning requires num_heads (shared_parameters "
                        "or the matching group's params)")
                ratio, _ = self._group_lookup(
                    key, hp["groups"], ("dense_ratio", 0.5), ("unused", 0))
                entry["head_ratio"] = float(ratio)
                entry["head_offset"] = int(hp["shared"]["schedule_offset"])
                entry["num_heads"] = int(heads)
            cp = self.cfg["channel_pruning"]
            if cp["shared"]["enabled"] and leaf.ndim >= 4:
                ratio, _ = self._group_lookup(
                    key, cp["groups"], ("dense_ratio", 0.5), ("unused", 0))
                entry["chan_ratio"] = float(ratio)
                entry["chan_offset"] = int(cp["shared"]["schedule_offset"])
            if entry:
                self.plan[key] = entry
        if self.cfg["activation_quantization"]["shared"]["enabled"]:
            # activations are produced inside the model, out of reach of a
            # parameter transform; refusing loudly beats training
            # full-precision under a config that claims otherwise
            raise NotImplementedError(
                "activation_quantization is not supported: this framework "
                "applies compression as a parameter-tree transform inside the "
                "loss; quantizing activations requires model support")
        if self.plan:
            log_dist(f"compression: {len(self.plan)} tensors under "
                     f"{'QAT ' if wq['shared']['enabled'] else ''}"
                     f"{'pruning' if sp['shared']['enabled'] else ''}".strip())
        # key-path prefix of the stacked layer subtree ([n_layer, ...] leaves);
        # the engine overwrites it with the eigenvalue probe's resolved subtree
        self.curvature_scope = "blocks"

    @staticmethod
    def _group_lookup(key: str, groups: Dict[str, Any], first: Tuple[str, Any],
                      second: Tuple[str, Any]):
        """different_groups entries: {name: {params: {...}, modules: [patterns]}}."""
        for _, g in (groups or {}).items():
            mods = g.get("modules", ["*"])
            if _matches(key, mods):
                p = g.get("params", {})
                return p.get(first[0], first[1]), p.get(second[0], second[1])
        return first[1], second[1]

    @staticmethod
    def _group_params(key: str, groups: Dict[str, Any]) -> Dict[str, Any]:
        """The full params dict of the first matching different_groups entry."""
        for _, g in (groups or {}).items():
            if _matches(key, g.get("modules", ["*"])):
                return g.get("params", {})
        return {}

    @property
    def enabled(self) -> bool:
        return bool(self.plan)

    # ------------------------------------------------------------------ in-step
    def transform(self, params, step: jnp.ndarray, curvature=None):
        """Apply scheduled fake-quant / pruning to planned leaves. ``step`` is
        traced; gating is a select so one program covers the schedule.

        ``curvature``: optional traced ``[n_layer]`` vector of normalized
        ([0, 1]) per-layer Hessian eigenvalues (``runtime/eigenvalue.py``).
        Stacked per-layer leaves (leading dim == n_layer) then quantize on a
        per-layer stretched schedule — offset x (1 + floor(ev * 4)) — so
        high-curvature layers quantize later. Parity: the reference quantizer's
        eigenvalue factor (``runtime/quantize.py:63-68``)."""
        if not self.plan:
            return params
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in flat:
            entry = self.plan.get(_path_str(path))
            x = leaf
            if entry is not None:
                # pruning masks apply to the raw weights, THEN fake-quant
                # (the reference's LinearLayer_Compress order: weight*mask
                # before quantization — also avoids magnitude ties on the
                # quantized grid inflating the keep set)
                if "prune_ratio" in entry:
                    # lax.cond, not where: the pruning branch sorts |W| (O(n log n))
                    # and must not execute during the pre-offset steps
                    x = jax.lax.cond(
                        step >= entry["prune_offset"],
                        lambda t: _prune_l1(t, entry["prune_ratio"]),
                        lambda t: t, x)
                if "row_ratio" in entry:
                    x = jax.lax.cond(
                        step >= entry["row_offset"],
                        lambda t: _prune_rows(t, entry["row_ratio"]),
                        lambda t: t, x)
                if "head_ratio" in entry:
                    x = jax.lax.cond(
                        step >= entry["head_offset"],
                        lambda t: _prune_heads(t, entry["head_ratio"],
                                               entry["num_heads"]),
                        lambda t: t, x)
                if "chan_ratio" in entry:
                    x = jax.lax.cond(
                        step >= entry["chan_offset"],
                        lambda t: _prune_rows(t, entry["chan_ratio"]),
                        lambda t: t, x)
                if "quant_bits" in entry:
                    offset = entry["quant_offset"]
                    start_b = entry["quant_bits"]
                    target_b = entry.get("quant_target_bits", start_b)
                    key = _path_str(path)
                    in_scope = key.startswith(self.curvature_scope + "/")
                    per_layer = (curvature is not None and in_scope
                                 and x.ndim >= 1
                                 and x.shape[0] == curvature.shape[0])
                    factor = (1.0 + jnp.floor(curvature * 4.0) if per_layer
                              else jnp.float32(1.0))
                    if target_b < start_b:
                        # progressive anneal; the eigenvalue factor stretches
                        # both the onset and the drop periods per layer
                        bits_now = annealed_bits(
                            step - (offset * factor).astype(jnp.float32),
                            start_b, target_b, entry["quant_period"], factor)
                        xq = fake_quant_dynamic(x, bits_now,
                                                entry["quant_groups"])
                    else:
                        xq = fake_quant(x, start_b, entry["quant_groups"])
                    if per_layer:
                        gate = step >= (offset * factor).astype(step.dtype)
                        x = jnp.where(
                            gate.reshape((-1,) + (1,) * (x.ndim - 1)), xq, x)
                    else:
                        x = jnp.where(step >= offset, xq, x)
            out.append(x)
        return jax.tree_util.tree_unflatten(treedef, out)


def _prune_l1(x: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Magnitude (L1) pruning to ``dense_ratio`` density: smallest-|x| entries
    zeroed. Parity: compression/basic_layer sparse_pruning l1 method."""
    k = max(1, int(round(x.size * dense_ratio)))
    flat = jnp.abs(x.ravel())
    threshold = jnp.sort(flat)[x.size - k]
    return jnp.where(jnp.abs(x) >= threshold, x, 0.0).astype(x.dtype)


def _prune_rows(x: jnp.ndarray, dense_ratio: float) -> jnp.ndarray:
    """Structured pruning: zero whole output units (last dim) below the
    top-``dense_ratio`` by L2 norm. Parity: ``LinearLayer_Compress`` row
    pruning (and Conv2d channel pruning, whose kernels are ``[..., cout]``)."""
    norms = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2,
                             axis=tuple(range(x.ndim - 1))))
    n = norms.shape[0]
    k = max(1, int(round(n * dense_ratio)))
    thr = jnp.sort(norms)[n - k]
    return jnp.where(norms >= thr, x, 0.0).astype(x.dtype)


def _prune_heads(x: jnp.ndarray, dense_ratio: float,
                 num_heads: int) -> jnp.ndarray:
    """Structured pruning of whole attention heads: the output projection's
    input dim (-2) groups into ``[num_heads, head_dim]``; the lowest-norm
    heads are zeroed per layer. Parity: ``LinearLayer_Compress`` head
    pruning on the attention output matrix."""
    d_in = x.shape[-2]
    if d_in % num_heads:
        raise ValueError(f"head_pruning: dim {d_in} not divisible by "
                         f"{num_heads} heads")
    dh = d_in // num_heads
    xh = x.reshape(x.shape[:-2] + (num_heads, dh, x.shape[-1]))
    norms = jnp.sqrt(jnp.sum(xh.astype(jnp.float32) ** 2, axis=(-2, -1)))
    k = max(1, int(round(num_heads * dense_ratio)))
    thr = jnp.sort(norms, axis=-1)[..., num_heads - k]
    mask = norms >= thr[..., None]
    return (xh * mask[..., None, None]).reshape(x.shape).astype(x.dtype)


def init_compression(param_tree, ds_config) -> CompressionScheduler:
    """Build a scheduler from a DeepSpeedConfig (or raw dict). Parity:
    ``compression/compress.py`` init_compression."""
    block = (ds_config.compression_training
             if hasattr(ds_config, "compression_training") else ds_config)
    return CompressionScheduler(block, param_tree)


def redundancy_clean(params, ds_config, step: Optional[int] = None):
    """Bake the terminal compression transform into the weights for
    deployment: fake-quant at the annealed target bits and every pruning mask
    applied permanently, so inference runs on the cleaned tree with no
    scheduler in the loop. Parity: ``compression/compress.py:127``
    ``redundancy_clean`` (the reference mutates modules in place; here a new
    tree is returned).

    ``step`` defaults to far past every schedule (offsets and anneals fully
    realized)."""
    if isinstance(ds_config, dict):
        cfg = ds_config.get("compression_training", ds_config)
    else:  # a DeepSpeedConfig model (e.g. engine.config)
        cfg = getattr(ds_config, "compression_training", None) or {}
    sched = CompressionScheduler(cfg, params)
    if not sched.enabled:
        return params
    horizon = step if step is not None else 2**30
    return jax.tree_util.tree_map(
        lambda x: x, sched.transform(params, jnp.int32(horizon)))


def layer_reduction_map(n_teacher_layers: int, keep: int,
                        teacher_layer: Optional[List[int]] = None) -> List[int]:
    """Which teacher layers a reduced student keeps. Parity:
    ``compression/helper.py`` student initialization mapping."""
    if teacher_layer:
        assert len(teacher_layer) == keep
        return list(teacher_layer)
    if keep <= 1:
        return [n_teacher_layers - 1]
    stride = (n_teacher_layers - 1) / (keep - 1)
    return [int(round(i * stride)) for i in range(keep)]


def quantize_params_for_inference(params, bits: int = 8, num_groups: int = 1,
                                  group_size: Optional[int] = None,
                                  exclude=_EXCLUDE_DEFAULT):
    """Post-training weight quantization: returns (int8 tree, scales tree,
    metadata) for storage, and a dequantize closure for load. Parity: the
    inference GroupQuantizer (``module_inject/replace_module.py:144``)."""
    from ..ops.quantizer import dequantize

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    q_leaves, s_leaves = [], []
    quantized_keys = []
    for path, leaf in flat:
        key = _path_str(path)
        if _is_weight(key, leaf) and not _matches(key, list(exclude)):
            ng = num_groups
            if group_size and leaf.size % group_size == 0:
                ng = leaf.size // group_size
            q, s = quantize(leaf, bits=bits, num_groups=ng)
            q_leaves.append(q)
            s_leaves.append(s)
            quantized_keys.append(key)
        else:
            q_leaves.append(leaf)
            s_leaves.append(None)
    qtree = jax.tree_util.tree_unflatten(treedef, q_leaves)

    def dequantize_tree(dtype=jnp.bfloat16):
        out = []
        for (path, _), q, s in zip(flat, q_leaves, s_leaves):
            out.append(q if s is None else dequantize(q, s, dtype=dtype))
        return jax.tree_util.tree_unflatten(treedef, out)

    return qtree, s_leaves, {"quantized": quantized_keys,
                             "dequantize": dequantize_tree}
