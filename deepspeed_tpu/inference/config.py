"""Inference configuration.

Parity: reference ``inference/config.py:121`` (``DeepSpeedInferenceConfig``) — same
JSON keys: dtype, tensor_parallel{tp_size}, moe{ep_size}, max_out_tokens,
replace_with_kernel_inject, enable_cuda_graph (mapped to AOT compilation, the TPU
analog), quant. Unknown/unsupported CUDA-only knobs parse and warn.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """Parity: inference/config.py:42."""

    enabled: bool = True
    tp_size: int = 1


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    """Parity: inference/config.py:60."""

    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field(default_factory=lambda: [1])


class QuantizationConfig(DeepSpeedConfigModel):
    """Parity: inference/config.py:83-111."""

    enabled: bool = False
    qkv: bool = True
    bits: int = 8
    group_size: int = 128


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    dtype: str = "bfloat16"  # torch-style names also accepted ("half", "float16", ...)
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig, alias="tp")
    moe: DeepSpeedMoEConfig = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = 1
    # 0 = unbounded (the engine compiles per batch shape anyway); a positive
    # value is ENFORCED at generate() — unlike the reference, which accepts
    # the field but never checks it
    max_batch_size: int = 0
    # decode shape buckets (shared with the serving path,
    # ``inference/serving/buckets.py``): when set, ``generate`` rounds
    # max_new_tokens UP to the nearest bucket and slices the output back, so
    # nearby request shapes reuse one compiled program instead of compiling
    # per (B, T, max_new) triple. Costs eos-frozen no-op steps up to the
    # bucket boundary; sampling draws per-step keys, so bucketed and
    # unbucketed runs of the same seed can sample differently.
    decode_buckets: Optional[list] = None
    # every compiled-program cache miss is appended to ``engine.compile_log``
    # and (when a monitor is attached via ``set_monitor``) emitted as an
    # ``Inference/compile_events`` scalar — silent per-shape recompiles are
    # the decode hot path's classic perf bug (dslint:
    # serving/unbucketed-decode-shape)
    log_compile_events: bool = True
    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    enable_cuda_graph: bool = True  # TPU analog: AOT-compiled fixed-shape decode step
    replace_method: str = "auto"
    injection_policy: Optional[Dict[Any, Any]] = None
    checkpoint: Optional[Union[str, Dict]] = None
    zero: Dict[str, Any] = Field(default_factory=dict)
    triangular_masking: bool = True
    return_tuple: bool = True

    def jax_dtype(self):
        import jax.numpy as jnp

        name = {"half": "float16", "fp16": "float16", "float": "float32",
                "fp32": "float32", "bf16": "bfloat16", "int8": "int8",
                "torch.half": "float16", "torch.float16": "float16",
                "torch.bfloat16": "bfloat16", "torch.float32": "float32"}.get(
                    str(self.dtype).lower(), str(self.dtype).lower())
        return jnp.dtype(name)
