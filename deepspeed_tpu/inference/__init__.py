from .config import DeepSpeedInferenceConfig
from .engine import InferenceEngine, for_gpt

__all__ = ["InferenceEngine", "DeepSpeedInferenceConfig", "for_gpt"]
