"""Fixed-size KV page allocator (host side).

The device holds one page pool per layer (``models/gpt.init_paged_cache``);
this allocator hands out pool slot ids. Page 0 is RESERVED as the sink that
inactive decode slots and masked scatter lanes write into — a block-table
entry of 0 therefore always names a valid (garbage) page, which is what lets
the Pallas kernel's ``index_map`` read table rows past a request's length
without bounds checks.

Allocation is all-or-nothing (a request either gets every page it asked for
or none), frees are checked (double-free and foreign pages raise), and the
free list is LIFO so recently-touched pages — still warm in whatever cache
level applies — are reused first.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

RESERVED_PAGE = 0


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` KV entries."""
    if tokens <= 0:
        return 0
    return -(-tokens // page_size)


class PageAllocator:
    """Free-list allocator over a pool of ``num_pages`` pages (ids
    ``1 .. num_pages-1``; page 0 reserved)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved sink), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._allocated = set()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return len(self._allocated)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages, or None (and allocate NOTHING) if the pool
        cannot cover the request — the caller decides between queueing and
        preempting."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._allocated.update(pages)
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            p = int(p)
            if p == RESERVED_PAGE:
                raise ValueError("freeing the reserved sink page 0")
            if p not in self._allocated:
                raise ValueError(f"double-free or foreign page {p}")
            self._allocated.remove(p)
            self._free.append(p)
