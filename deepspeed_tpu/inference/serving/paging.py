"""Fixed-size KV page allocator (host side).

The device holds one page pool per layer (``models/gpt.init_paged_cache``);
this allocator hands out pool slot ids. Page 0 is RESERVED as the sink that
inactive decode slots and masked scatter lanes write into — a block-table
entry of 0 therefore always names a valid (garbage) page, which is what lets
the Pallas kernel's ``index_map`` read table rows past a request's length
without bounds checks.

Allocation is all-or-nothing (a request either gets every page it asked for
or none), frees are checked (double-free and foreign pages raise), and the
free list is LIFO so recently-touched pages — still warm in whatever cache
level applies — are reused first.

Two robustness hooks (docs/SERVING.md "Overload & failure"):

- :meth:`PageAllocator.audit` — the conservation invariant (free + allocated
  == total, no duplicates, no reserved-page escapes). The scheduler runs it
  after every recovery action (dispatch failure, deadline eviction, shed):
  a page leak under fault handling must be loud, not a slow HBM bleed.
- chaos: an armed :class:`~deepspeed_tpu.resilience.chaos.FaultPlan` with
  ``alloc_fail_at`` makes the Nth ``alloc`` call report pool exhaustion
  (return None) — admission/growth paths must degrade exactly as they do
  under real pool pressure.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Sequence

RESERVED_PAGE = 0


def _alloc_fault_armed(index: int) -> bool:
    """Whether an armed FaultPlan wants alloc call ``index`` to fail (lazy
    import: the allocator must stay importable without the resilience
    package fully loaded, e.g. from setup-time tooling)."""
    try:
        from ...resilience.chaos import serving_alloc_fault
    except ImportError:  # partial install / doc builds
        return False
    return serving_alloc_fault(index)


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` KV entries."""
    if tokens <= 0:
        return 0
    return -(-tokens // page_size)


class PageAllocator:
    """Free-list allocator over a pool of ``num_pages`` pages (ids
    ``1 .. num_pages-1``; page 0 reserved)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved sink), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._allocated = set()
        self._alloc_calls = 0  # chaos injection index (alloc_fail_at)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return len(self._allocated)

    @property
    def allocated_ids(self) -> FrozenSet[int]:
        """The allocator's ledger of outstanding pages — what the scheduler
        cross-checks its slot page lists against in :meth:`audit`."""
        return frozenset(self._allocated)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages, or None (and allocate NOTHING) if the pool
        cannot cover the request — the caller decides between queueing and
        preempting."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        idx = self._alloc_calls
        self._alloc_calls += 1
        if _alloc_fault_armed(idx):
            return None  # chaos: report exhaustion through the normal path
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._allocated.update(pages)
        return pages

    def audit(self) -> Dict[str, object]:
        """Conservation invariant over the pool: every page id 1..N-1 is in
        exactly one of {free list, allocated set}, with no duplicates and no
        reserved-page escapes. Returns ``{"ok", "free", "allocated",
        "total", "errors"}`` — ``errors`` names each violated invariant.
        Run by the scheduler after every recovery action; a non-clean audit
        there is a page leak in the fault-handling path."""
        errors: List[str] = []
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            errors.append("duplicate ids in the free list")
        overlap = free_set & self._allocated
        if overlap:
            errors.append(f"pages both free and allocated: {sorted(overlap)}")
        if RESERVED_PAGE in free_set or RESERVED_PAGE in self._allocated:
            errors.append("reserved sink page 0 escaped into the pool")
        bad = [p for p in free_set | self._allocated
               if not (1 <= p < self.num_pages)]
        if bad:
            errors.append(f"page ids outside the pool: {sorted(bad)}")
        total = self.num_pages - 1
        if len(free_set) + len(self._allocated) != total:
            errors.append(
                f"conservation broken: free {len(free_set)} + allocated "
                f"{len(self._allocated)} != total {total}")
        return {"ok": not errors, "free": len(free_set),
                "allocated": len(self._allocated), "total": total,
                "errors": errors}

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            p = int(p)
            if p == RESERVED_PAGE:
                raise ValueError("freeing the reserved sink page 0")
            if p not in self._allocated:
                raise ValueError(f"double-free or foreign page {p}")
            self._allocated.remove(p)
            self._free.append(p)
