"""Fixed-size KV page allocator (host side), with copy-on-write sharing.

The device holds one page pool per layer (``models/gpt.init_paged_cache``);
this allocator hands out pool slot ids. Page 0 is RESERVED as the sink that
inactive decode slots and masked scatter lanes write into — a block-table
entry of 0 therefore always names a valid (garbage) page, which is what lets
the Pallas kernel's ``index_map`` read table rows past a request's length
without bounds checks.

Allocation is all-or-nothing (a request either gets every page it asked for
or none), frees are checked (over-free and foreign pages raise), and the
free list is LIFO so recently-touched pages — still warm in whatever cache
level applies — are reused first.

**Copy-on-write sharing** (docs/SERVING.md "KV quantization & prefix
caching"): every allocated page carries a refcount. :meth:`PageAllocator.
share` takes an extra reference (shared-prefix reuse: two requests whose
prompts begin with the same page-aligned token blocks read the SAME physical
page), :meth:`PageAllocator.free` drops one reference per call and only
returns the page to the free list when the last reference dies, and
:meth:`PageAllocator.materialize` is the write trigger — a writer holding a
shared page trades its reference for a fresh private copy (the caller copies
the device bytes). The scheduler's sharing discipline makes materialize a
defensive path: only FULL prefix pages are ever shared, and the decode
append frontier is always past them, so no write can land on a shared page
— an invariant :meth:`ContinuousBatchingScheduler.audit` enforces.

:class:`PrefixIndex` is the host-side lookup that makes sharing happen: a
hash CHAIN over page-sized prompt token blocks (block j's key commits to
blocks 0..j), mapping each chain hash to the physical page holding that
block's KV. Chat-style traffic (system prompts, few-shot headers) hits the
chain for its common prefix and admits with those pages shared instead of
re-allocated.

Two robustness hooks (docs/SERVING.md "Overload & failure"):

- :meth:`PageAllocator.audit` — the conservation invariant (free +
  Σ(unique allocated) == total, every refcount >= 1, no duplicates, no
  reserved-page escapes). The scheduler runs it after every recovery action
  (dispatch failure, deadline eviction, shed): a page leak under fault
  handling must be loud, not a slow HBM bleed.
- chaos: an armed :class:`~deepspeed_tpu.resilience.chaos.FaultPlan` with
  ``alloc_fail_at`` makes the Nth ``alloc`` call report pool exhaustion
  (return None) — admission/growth paths must degrade exactly as they do
  under real pool pressure.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence

import numpy as np

RESERVED_PAGE = 0


def _alloc_fault_armed(index: int) -> bool:
    """Whether an armed FaultPlan wants alloc call ``index`` to fail (lazy
    import: the allocator must stay importable without the resilience
    package fully loaded, e.g. from setup-time tooling)."""
    try:
        from ...resilience.chaos import serving_alloc_fault
    except ImportError:  # partial install / doc builds
        return False
    return serving_alloc_fault(index)


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` KV entries."""
    if tokens <= 0:
        return 0
    return -(-tokens // page_size)


class PageAllocator:
    """Refcounted free-list allocator over a pool of ``num_pages`` pages
    (ids ``1 .. num_pages-1``; page 0 reserved)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved sink), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}  # page id -> live references
        self._alloc_calls = 0  # chaos injection index (alloc_fail_at)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        """UNIQUE physical pages outstanding (a shared page counts once)."""
        return len(self._ref)

    @property
    def allocated_ids(self) -> FrozenSet[int]:
        """The allocator's ledger of outstanding pages — what the scheduler
        cross-checks its slot page lists against in :meth:`audit`."""
        return frozenset(self._ref)

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 if not allocated)."""
        return self._ref.get(int(page), 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages (each at refcount 1), or None (and allocate
        NOTHING) if the pool cannot cover the request — the caller decides
        between queueing and preempting."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        idx = self._alloc_calls
        self._alloc_calls += 1
        if _alloc_fault_armed(idx):
            return None  # chaos: report exhaustion through the normal path
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def share(self, pages: Sequence[int]) -> None:
        """Take one extra reference on each page (prefix reuse: a second
        request now reads the same physical page). Sharing an unallocated
        or reserved page is a caller bug and raises."""
        pages = [int(p) for p in pages]
        for p in pages:
            if p == RESERVED_PAGE:
                raise ValueError("sharing the reserved sink page 0")
            if p not in self._ref:
                raise ValueError(f"sharing unallocated page {p}")
        for p in pages:
            self._ref[p] += 1

    def materialize(self, page: int) -> Optional[int]:
        """Copy-on-write trigger: make ``page`` privately writable.

        With a single reference the page is already private and is returned
        as-is. Shared, the caller's reference is traded for a freshly
        allocated page (the caller must copy the device bytes before
        writing). Returns None — and keeps the original reference — when
        the pool has no page to give."""
        page = int(page)
        if self._ref.get(page, 0) == 0:
            raise ValueError(f"materializing unallocated page {page}")
        if self._ref[page] == 1:
            return page
        fresh = self.alloc(1)
        if fresh is None:
            return None
        self._ref[page] -= 1
        return fresh[0]

    def audit(self, expected_fingerprints: Optional[Dict[int, int]] = None,
              fingerprint_fn: Optional[Callable[[Sequence[int]], List[int]]]
              = None) -> Dict[str, object]:
        """Conservation invariant over the pool: every page id 1..N-1 is in
        exactly one of {free list, allocated set}, with no duplicates, no
        reserved-page escapes, and every allocated page holding >= 1 live
        reference. Returns ``{"ok", "free", "allocated", "total", "refs",
        "errors", "fingerprinted", "mismatches"}`` — ``errors`` names each
        violated invariant. Run by the scheduler after every recovery
        action; a non-clean audit there is a page leak in the fault-handling
        path.

        Opt-in fingerprint sweep (docs/RESILIENCE.md "Data integrity"):
        given ``expected_fingerprints`` (page id → fingerprint, stamped when
        the page froze behind the write frontier) and ``fingerprint_fn``
        (page ids → current content fingerprints), every SHARED page
        (refcount > 1 — the pages more than one request reads verbatim) with
        a stamp is re-fingerprinted; a mismatch is silent corruption of an
        immutable page and fails the audit by name."""
        errors: List[str] = []
        fingerprinted = 0
        fp_mismatches: List[int] = []
        if expected_fingerprints and fingerprint_fn is not None:
            shared = sorted(p for p, c in self._ref.items()
                            if c > 1 and p in expected_fingerprints)
            if shared:
                actual = fingerprint_fn(shared)
                fingerprinted = len(shared)
                for p, fp in zip(shared, actual):
                    if int(fp) != int(expected_fingerprints[p]):
                        fp_mismatches.append(p)
                if fp_mismatches:
                    errors.append(
                        f"shared-page fingerprint mismatch (silent "
                        f"corruption of immutable pages): {fp_mismatches}")
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            errors.append("duplicate ids in the free list")
        overlap = free_set & set(self._ref)
        if overlap:
            errors.append(f"pages both free and allocated: {sorted(overlap)}")
        if RESERVED_PAGE in free_set or RESERVED_PAGE in self._ref:
            errors.append("reserved sink page 0 escaped into the pool")
        bad = [p for p in free_set | set(self._ref)
               if not (1 <= p < self.num_pages)]
        if bad:
            errors.append(f"page ids outside the pool: {sorted(bad)}")
        leaked_refs = sorted(p for p, c in self._ref.items() if c < 1)
        if leaked_refs:
            errors.append(
                f"allocated pages with refcount < 1 (leaked reference "
                f"accounting): {leaked_refs}")
        total = self.num_pages - 1
        if len(free_set) + len(self._ref) != total:
            errors.append(
                f"conservation broken: free {len(free_set)} + unique "
                f"allocated {len(self._ref)} != total {total}")
        return {"ok": not errors, "free": len(free_set),
                "allocated": len(self._ref), "total": total,
                "refs": sum(self._ref.values()), "errors": errors,
                "fingerprinted": fingerprinted,
                "mismatches": fp_mismatches}

    def free(self, pages: Sequence[int]) -> List[int]:
        """Drop one reference per page. Pages whose LAST reference died are
        returned to the free list and reported back (the caller invalidates
        any prefix-index entries pointing at them — their bytes are about to
        be recycled). Over-freeing (more frees than references) raises."""
        released: List[int] = []
        for p in pages:
            p = int(p)
            if p == RESERVED_PAGE:
                raise ValueError("freeing the reserved sink page 0")
            if p not in self._ref:
                raise ValueError(f"double-free or foreign page {p}")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)
                released.append(p)
        return released


# ---------------------------------------------------------------- prefix index
def prefix_chain_hashes(tokens: Sequence[int], page_size: int) -> List[bytes]:
    """Hash chain over page-sized token blocks: entry j commits to blocks
    0..j (a block's key includes its whole prefix, so equal hashes mean
    equal page-aligned prompt prefixes, not just equal blocks). Only FULL
    blocks participate — a partial tail block is never shareable (its page
    would be written at different offsets by different requests)."""
    toks = np.asarray(tokens, np.int64)
    out: List[bytes] = []
    h = hashlib.blake2b(digest_size=16)
    for j in range(len(toks) // page_size):
        h.update(toks[j * page_size:(j + 1) * page_size].tobytes())
        out.append(h.digest())
    return out


class PrefixIndex:
    """Host-side map from prompt-prefix hash chains to the physical pages
    holding their KV. Entries are registered AFTER a prefill writes the
    page (first writer wins) and forgotten the moment the page's last
    reference dies (``PageAllocator.free`` reports released pages) — a
    recycled page can never serve stale prefix bytes."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._by_hash: Dict[bytes, int] = {}
        self._by_page: Dict[int, bytes] = {}
        self.hits = 0      # pages served from the index
        self.misses = 0    # lookup blocks not present

    def __len__(self) -> int:
        return len(self._by_hash)

    def lookup(self, tokens: Sequence[int]) -> List[int]:
        """Physical pages for the LONGEST indexed page-aligned prefix of
        ``tokens`` (the chain stops at the first miss — later blocks commit
        to earlier ones, so holes cannot match)."""
        hashes = prefix_chain_hashes(tokens, self.page_size)
        pages = self.lookup_chain(hashes)
        self.count(hashes, pages)
        return pages

    def count(self, hashes: Sequence[bytes], pages: Sequence[int]) -> None:
        """Record one lookup's outcome in the hit statistics (split out so
        admission retries under head-of-line blocking count ONCE, at the
        admission that actually succeeds)."""
        self.hits += len(pages)
        if len(pages) < len(hashes):
            self.misses += 1

    def lookup_chain(self, hashes: Sequence[bytes]) -> List[int]:
        """Counter-free :meth:`lookup` over a PRECOMPUTED hash chain — the
        scheduler caches each request's chain (prompts are immutable) and
        retries admission every step under head-of-line blocking, so the
        hot path must not re-hash the prompt or skew hit statistics."""
        pages: List[int] = []
        for h in hashes:
            page = self._by_hash.get(h)
            if page is None:
                break
            pages.append(page)
        return pages

    def register(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Index the full prompt blocks of ``tokens`` against the pages
        that hold them (``pages`` = the owning request's block-table pages
        in order). Existing entries win (the earlier page is the one other
        requests already share). Returns the number of NEW entries."""
        added = 0
        for j, h in enumerate(prefix_chain_hashes(tokens, self.page_size)):
            if j >= len(pages):
                break
            page = int(pages[j])
            if page == RESERVED_PAGE or h in self._by_hash:
                continue
            if page in self._by_page:
                continue  # page already indexed under another chain
            self._by_hash[h] = page
            self._by_page[page] = h
            added += 1
        return added

    def forget(self, released_pages: Sequence[int]) -> None:
        """Invalidate entries for pages whose storage was just recycled."""
        for p in released_pages:
            h = self._by_page.pop(int(p), None)
            if h is not None:
                self._by_hash.pop(h, None)
