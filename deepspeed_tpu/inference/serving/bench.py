"""Request-level serving benchmark: open-loop arrivals, TTFT + tokens/s.

Open loop (arrivals follow a Poisson clock regardless of completions) is the
honest serving load: a closed loop would slow the arrival rate down whenever
the server stalls, hiding exactly the tail it is supposed to expose. The
workload is synthetic but seeded, so A/B runs replay identical requests.

Two runners share a report schema:

- :func:`run_continuous` — the paged continuous-batching stack
  (``ServingEngine`` + ``ContinuousBatchingScheduler``).
- :func:`run_static_baseline` — ``InferenceEngine.generate`` batches in
  arrival order: every request in a batch waits for the batch to fill, pads
  to the longest prompt, decodes to the LONGEST max_new in the batch, and
  nobody's slot frees early. That is today's ``generate`` serving story and
  the baseline the continuous row must beat on aggregate tokens/s at equal
  HBM budget.

Useful tokens are counted identically on both sides (each request's own
``max_new_tokens``), so tokens/s differences come from scheduling, not
accounting.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .scheduler import ContinuousBatchingScheduler, Request, RequestState


def percentile(xs: Sequence[float], p: float) -> float:
    if not xs:
        return float("nan")
    xs = sorted(xs)
    idx = min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1))))
    return float(xs[idx])


def make_open_loop_workload(n_requests: int, rate_rps: float,
                            prompt_len: tuple, max_new: tuple,
                            vocab_size: int, seed: int = 0,
                            eos_token_id: Optional[int] = None
                            ) -> List[Request]:
    """Poisson arrivals at ``rate_rps``; prompt/generation lengths uniform in
    the given inclusive ranges. Mixed lengths on purpose — the paged cache's
    whole value proposition is not paying max_len per request."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for _ in range(n_requests):
        t += float(rng.exponential(1.0 / rate_rps))
        pl = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        mn = int(rng.integers(max_new[0], max_new[1] + 1))
        out.append(Request(
            prompt=rng.integers(0, vocab_size, (pl,)).astype(np.int32),
            max_new_tokens=mn, eos_token_id=eos_token_id, arrival_time=t))
    return out


def make_tiered_workload(n_per_tier: int, rate_rps: float,
                         prompt_len: tuple, max_new: tuple,
                         vocab_size: int, seed: int = 0,
                         eos_token_id: Optional[int] = None,
                         tiers: Sequence[str] = ("interactive", "standard",
                                                 "batch"),
                         shares: Optional[Dict[str, float]] = None
                         ) -> List[Request]:
    """Mixed-tier open-loop stream: one Poisson arrival process per tier,
    one synthetic tenant per tier (``t-<tier>``), merged in arrival order.
    ``shares`` splits ``rate_rps`` across tiers (normalized; default an
    even split) — the noisy-neighbor shape is a LIGHT interactive share
    against a batch-heavy overload, since a tenant whose own demand
    saturates the box is not a neighbor problem. The tiered-overload A/B
    drives the SAME list through a tiered and an untiered scheduler."""
    weights = [float((shares or {}).get(t, 1.0)) for t in tiers]
    total_w = sum(weights) or 1.0
    out: List[Request] = []
    for k, (tier, w) in enumerate(zip(tiers, weights)):
        if w <= 0.0:
            continue
        for r in make_open_loop_workload(
                n_per_tier, rate_rps * w / total_w, prompt_len,
                max_new, vocab_size, seed=seed + 1000 * k,
                eos_token_id=eos_token_id):
            r.tenant_id = f"t-{tier}"
            r.tier = tier
            out.append(r)
    return sorted(out, key=lambda r: r.arrival_time)


def _group_row(reqs: Sequence[Request], t0: float, t_end: float,
               slo_s: Optional[float]) -> Dict:
    """Per-tenant/per-tier sub-report: REJECTED requests count against THIS
    group's shed rate (not the fleet aggregate), and a group's misses stay
    its own — a flood victim's misses no longer dilute the flooder's
    stats."""
    ttft: List[float] = []
    goodput = 0
    late = 0
    shed = sum(1 for r in reqs if r.state is RequestState.REJECTED)
    expired = sum(1 for r in reqs if r.state is RequestState.EXPIRED)
    for r in reqs:
        arrive = t0 + r.arrival_time
        if r.t_first_token is not None:
            ttft.append(r.t_first_token - arrive)
        n = min(len(r.tokens), r.max_new_tokens)
        if r.t_done is not None:
            if slo_s is None or r.t_done - arrive <= slo_s:
                goodput += n
            else:
                late += 1
        elif (slo_s is not None
              and r.state not in (RequestState.REJECTED,
                                  RequestState.EXPIRED)
              and t_end - arrive > slo_s):
            late += 1

    def ms(x, nd=2):
        return None if x != x else round(x * 1e3, nd)

    accepted = len(reqs) - shed
    misses = expired + late
    return {
        "requests": len(reqs),
        "finished": sum(r.t_done is not None for r in reqs),
        "shed": shed,
        "shed_rate": round(shed / max(len(reqs), 1), 4),
        "deadline_misses": misses,
        "deadline_miss_rate": round(misses / max(accepted, 1), 4),
        "goodput_tokens": int(goodput),
        "preemptions": sum(r.preemptions for r in reqs),
        "ttft_p50_ms": ms(percentile(ttft, 50)),
        "ttft_p99_ms": ms(percentile(ttft, 99)),
    }


def _report(requests: Sequence[Request], t0: float, t_end: float,
            mode: str, extra: Optional[Dict] = None,
            slo_s: Optional[float] = None) -> Dict:
    """Shared report schema. ``slo_s`` is an EVALUATION deadline (arrival ->
    completion) applied identically to every run — it lets an uncontrolled
    baseline (which enforces nothing) be scored against the same SLO a
    controlled run enforces, so goodput/deadline-miss numbers are an honest
    A/B. TTFT percentiles cover accepted requests only (a shed request has
    no first token by construction — mixing it in as +inf would charge
    admission control for the latency it avoided)."""
    ttft, per_tok, total_tokens = [], [], 0
    goodput_tokens = 0
    late = 0
    for r in requests:
        arrive = t0 + r.arrival_time
        if r.t_first_token is not None:
            ttft.append(r.t_first_token - arrive)
        n = min(len(r.tokens), r.max_new_tokens)
        total_tokens += n
        if r.t_done is not None:
            if slo_s is None or r.t_done - arrive <= slo_s:
                goodput_tokens += n
            else:
                late += 1
        # run-to-completion baselines deliver every token at once
        # (t_done == t_first): per-token cadence is undefined there, not 0
        if (r.t_done is not None and n > 1
                and r.t_done > r.t_first_token):
            per_tok.append((r.t_done - r.t_first_token) / (n - 1))

    def ms(x, nd=2):
        return None if x != x else round(x * 1e3, nd)  # NaN -> JSON null

    shed = [r for r in requests if r.state is RequestState.REJECTED]
    expired = [r for r in requests if r.state is RequestState.EXPIRED]
    accepted = len(requests) - len(shed)
    # accepted requests still unfinished at run end are the WORST outcomes
    # of an overloaded run — when an SLO is being scored and theirs already
    # lapsed, they count as misses, not as silent omissions (an uncontrolled
    # baseline hitting the wall cap would otherwise look artificially good)
    unfinished = [r for r in requests
                  if r.state not in (RequestState.REJECTED,
                                     RequestState.EXPIRED)
                  and r.t_done is None]
    if slo_s is not None:
        late += sum(1 for r in unfinished
                    if t_end - (t0 + r.arrival_time) > slo_s)
    misses = len(expired) + late
    wall = max(t_end - t0, 1e-9)
    row = {
        "mode": mode,
        "requests": len(requests),
        "finished": sum(r.t_done is not None for r in requests),
        "total_tokens": int(total_tokens),
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(total_tokens / wall, 2),
        "ttft_p50_ms": ms(percentile(ttft, 50)),
        "ttft_p99_ms": ms(percentile(ttft, 99)),
        "per_token_p50_ms": ms(percentile(per_tok, 50), 3),
        "per_token_p99_ms": ms(percentile(per_tok, 99), 3),
        # overload/SLO accounting (docs/SERVING.md "Overload & failure")
        "shed": len(shed),
        "shed_rate": round(len(shed) / max(len(requests), 1), 4),
        "unfinished": len(unfinished),
        "deadline_misses": misses,
        "deadline_miss_rate": round(misses / max(accepted, 1), 4),
        "goodput_tokens_per_sec": round(goodput_tokens / wall, 2),
    }
    if slo_s is not None:
        row["slo_s"] = slo_s
    tagged = [r for r in requests
              if getattr(r, "tenant_id", None) is not None
              or getattr(r, "tier", None) is not None]
    if tagged:
        by_tier: Dict[str, List[Request]] = {}
        by_tenant: Dict[str, List[Request]] = {}
        for r in tagged:
            if r.tier is not None:
                by_tier.setdefault(str(r.tier), []).append(r)
            if r.tenant_id is not None:
                by_tenant.setdefault(str(r.tenant_id), []).append(r)
        row["by_tier"] = {k: _group_row(v, t0, t_end, slo_s)
                          for k, v in sorted(by_tier.items())}
        row["by_tenant"] = {k: _group_row(v, t0, t_end, slo_s)
                            for k, v in sorted(by_tenant.items())}
    if extra:
        row.update(extra)
    return row


def run_continuous(engine, workload: Sequence[Request],
                   max_wall_s: float = 600.0, slo_s: Optional[float] = None,
                   scheduler: Optional[ContinuousBatchingScheduler] = None
                   ) -> Dict:
    """Drive the scheduler under the workload's arrival clock. Rejected
    submissions (typed :class:`AdmissionVerdict`) are terminal — the driver
    does not retry them; they score as shed in the report. Pass
    ``scheduler`` to drive a hand-built one (the overload A/B constructs a
    capped and an uncapped scheduler over the same engine)."""
    sched = scheduler if scheduler is not None else engine.make_scheduler()
    pending = sorted(workload, key=lambda r: r.arrival_time)
    t0 = time.monotonic()
    i = 0
    try:
        while i < len(pending) or not sched.idle:
            now = time.monotonic() - t0
            if now > max_wall_s:
                break
            while i < len(pending) and pending[i].arrival_time <= now:
                sched.submit(pending[i])
                i += 1
            if sched.idle:
                if i < len(pending):  # nothing in flight: sleep to arrival
                    time.sleep(min(max(pending[i].arrival_time - now, 0.0),
                                   0.25))
                continue
            sched.step()
    finally:
        sched.close()
    t_end = time.monotonic()
    stats = dict(sched.page_stats)
    extra = {
        "decode_steps": sched.steps,
        "preemptions": sum(r.preemptions for r in workload),
        "num_slots": sched.num_slots,
        "hbm_token_slots": engine.hbm_token_slots(),
        "compiled_programs": len(engine.compile_log),
        "recovery_counters": dict(sched.counters),
        "pool_audit_ok": bool(sched.audit()["ok"]),
        # copy-on-write prefix reuse: physical/logical < 1 means shared
        # prompt prefixes actually collapsed into the same physical pages
        "page_stats": stats,
        "physical_logical_page_ratio": round(
            stats["physical"] / stats["logical"], 4)
        if stats["logical"] else None,
    }
    if sched.drafter is not None:
        # the speculation ledger: accept rate + the multi-token multiplier
        # (docs/SERVING.md "Speculative decoding" — how to read the A/B row)
        ss = dict(sched.spec_stats)
        ss["accept_rate"] = round(
            ss["accepted"] / max(ss["drafted"], 1), 4)
        # the multi-token multiplier: tokens a verify dispatch produced,
        # averaged over windows (1.0 == no better than plain decode)
        ss["tokens_per_dispatch"] = round(
            ss["committed_tokens"] / max(ss["windows"], 1), 3)
        extra["spec"] = ss
    return _report(workload, t0, t_end, "continuous", slo_s=slo_s,
                   extra=extra)


def estimate_saturation_rps(engine, prompt_len: tuple, max_new: tuple,
                            vocab_size: int, n_requests: int = 8,
                            seed: int = 1234) -> float:
    """Calibrate the server's saturation point: drive a short CLOSED-loop
    batch (every request present at t=0 — the scheduler is never idle) and
    convert its aggregate tokens/s into requests/s at the workload's mean
    generation length. The overload bench row arrives at 2x this rate —
    open-loop load the server provably cannot keep up with."""
    wl = make_open_loop_workload(n_requests, rate_rps=1e9,
                                 prompt_len=prompt_len, max_new=max_new,
                                 vocab_size=vocab_size, seed=seed)
    rep = run_continuous(engine, wl)
    mean_gen = float(np.mean([r.max_new_tokens for r in wl]))
    return float(rep["tokens_per_sec"]) / max(mean_gen, 1.0)


def run_static_baseline(infer_engine, workload: Sequence[Request],
                        batch_size: int, max_wall_s: float = 600.0) -> Dict:
    """Static batching over the same requests: fill a batch in arrival
    order, right-pad prompts, generate everyone to the batch max max_new.
    Request timing: first token and completion both land when the whole
    batch returns (``generate`` is run-to-completion)."""
    pending = sorted(workload, key=lambda r: r.arrival_time)
    # one fixed batch shape for the whole run (workload max prompt/gen):
    # warmup compiles it once, so the A/B times scheduling, not the
    # baseline's per-group recompiles
    tmax = max(len(r.prompt) for r in pending)
    gen = max(r.max_new_tokens for r in pending)
    t0 = time.monotonic()
    for start in range(0, len(pending), batch_size):
        group = pending[start:start + batch_size]
        # open loop: the batch cannot launch before its last member arrives
        launch = t0 + max(r.arrival_time for r in group)
        now = time.monotonic()
        if now + max_wall_s < launch:
            break
        if launch > now:
            time.sleep(launch - now)
        if time.monotonic() - t0 > max_wall_s:
            break
        ids = np.zeros((batch_size, tmax), np.int32)
        for j, r in enumerate(group):
            ids[j, :len(r.prompt)] = r.prompt
        out = np.asarray(infer_engine.generate(ids, max_new_tokens=gen))
        t_batch = time.monotonic()
        for j, r in enumerate(group):
            r.t_first_token = t_batch
            r.t_done = t_batch
            r.tokens = [int(x) for x in
                        out[j, tmax:tmax + r.max_new_tokens]]
    t_end = time.monotonic()
    return _report(workload, t0, t_end, "static", extra={
        "batch_size": batch_size})
