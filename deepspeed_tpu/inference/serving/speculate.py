"""Speculative decoding drafters (docs/SERVING.md "Speculative decoding").

Decode is weight-bound: a verification pass that scores ``k+1`` positions in
one dispatch (``models/gpt.paged_verify_step``) reads every weight matrix
ONCE where ``k+1`` sequential decode steps read it ``k+1`` times — so if a
cheap *drafter* can guess the next few greedy tokens, accepted guesses are
nearly free. This module is the host half of that bet:

- :class:`NGramDrafter` — self-drafting by suffix match over the request's
  OWN prompt + generated tokens. Zero extra HBM, zero device work; it wins
  exactly when generation is locally repetitive (code, templated text, the
  greedy loops small models fall into).
- :class:`DraftModelDrafter` — a small model (e.g. gpt2-125m drafting for a
  760m+ target) greedily proposing ``k`` tokens from its OWN dense KV cache.
  The cache lives outside the target's page pool; rejected drafts roll back
  by rewinding the cache position (stale entries past ``pos`` are masked and
  overwritten — no copy). Its HBM cost is priced into ``num_slots="auto"``
  by ``runtime/aot.speculation_hbm_bytes``.

Both sit behind one protocol the scheduler consumes::

    draft(slot, rid, prompt, tokens, k) -> np.ndarray  # <= k proposed tokens
    release(slot)                                      # slot evicted/reused
    kind                                               # accounting label

Drafters PROPOSE, the target DISPOSES: acceptance is longest-prefix greedy
agreement computed inside the verify program, so a drafter can be arbitrarily
wrong without ever changing outputs — the worst case is wasted verify
positions, which :class:`AdaptiveSpecK` bounds by collapsing ``k`` toward 1
when the accept rate is low.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Protocol, Sequence, Tuple

import numpy as np


class Drafter(Protocol):
    """The scheduler-facing drafter protocol (host-level; a drafter MAY own
    device state, the scheduler never sees it)."""

    kind: str

    def draft(self, slot: int, rid: int, prompt: np.ndarray,
              tokens: Sequence[int], k: int) -> np.ndarray:
        """Up to ``k`` proposed next tokens for the request in ``slot``
        whose verified context is ``prompt + tokens``. Fewer (or zero)
        proposals are fine — unfilled window positions are padded and
        simply fail verification."""
        ...

    def release(self, slot: int) -> None:
        """The slot was evicted/finished/preempted — drop any per-slot
        state (a later ``draft`` for the same slot may carry a new rid)."""
        ...


def spec_k_ladder(max_k: int) -> Tuple[int, ...]:
    """The bounded draft-length set: powers of two up to ``max_k``. Window
    sizes W = k+1 then step 2, 3, 5, 9, ... — unequal strides, so the
    ``serving/unbucketed-decode-shape`` rule never mistakes the verify
    program family for a creeping shape."""
    if max_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {max_k}")
    out = []
    k = 1
    while k <= max_k:
        out.append(k)
        k *= 2
    return tuple(out)


class AdaptiveSpecK:
    """Accept-rate-driven draft length: speculation can never be a
    regression because ``k`` collapses toward the ladder floor (k=1, whose
    verify window costs barely more than a plain decode step in the
    weight-bound regime) whenever drafts stop being accepted, and climbs
    back when they land. EMA-smoothed; the EMA resets on every level change
    so a stale regime cannot echo."""

    def __init__(self, ladder: Sequence[int], adaptive: bool = True,
                 low: float = 0.35, high: float = 0.75, decay: float = 0.8):
        if not ladder:
            raise ValueError("empty spec-k ladder")
        self.ladder = tuple(int(k) for k in ladder)
        self.adaptive = bool(adaptive)
        self.low = float(low)
        self.high = float(high)
        self.decay = float(decay)
        self.level = len(self.ladder) - 1   # start optimistic, back off fast
        self.ema: Optional[float] = None

    @property
    def k(self) -> int:
        return self.ladder[self.level]

    def observe(self, offered: int, accepted: int) -> None:
        """One verification window's outcome: ``offered`` draft positions
        (k x active slots), ``accepted`` of them confirmed."""
        rate = accepted / max(offered, 1)
        self.ema = (rate if self.ema is None
                    else self.decay * self.ema + (1.0 - self.decay) * rate)
        if not self.adaptive or len(self.ladder) == 1:
            return
        if self.ema < self.low and self.level > 0:
            self.level -= 1
            self.ema = None
        elif self.ema > self.high and self.level < len(self.ladder) - 1:
            self.level += 1
            self.ema = None


# ------------------------------------------------------------------- n-gram
class NGramDrafter:
    """Suffix-match self-drafting (prompt-lookup decoding): find the most
    recent earlier occurrence of the context's trailing n-gram and propose
    the tokens that followed it. Tries the longest order first
    (``max_n .. min_n``); among matches prefers the most recent one with a
    full ``k`` tokens of continuation, falling back to the most recent
    match's shorter tail. Pure host work over the request's own tokens —
    the zero-cost drafter."""

    kind = "ngram"

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not (1 <= min_n <= max_n):
            raise ValueError(f"bad n-gram order range [{min_n}, {max_n}]")
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def draft(self, slot: int, rid: int, prompt: np.ndarray,
              tokens: Sequence[int], k: int) -> np.ndarray:
        del slot, rid
        ctx = np.concatenate([np.asarray(prompt, np.int64),
                              np.asarray(list(tokens), np.int64)])
        L = len(ctx)
        if k < 1 or L < 2:
            return np.empty(0, np.int32)   # empty/one-token history
        for n in range(min(self.max_n, L - 1), self.min_n - 1, -1):
            pat = ctx[L - n:]
            # windows starting at s hold ctx[s:s+n] == a match ENDING at
            # j = s + n; j == L is the query suffix itself, excluded
            wins = np.lib.stride_tricks.sliding_window_view(ctx, n)[:L - n]
            hit = np.flatnonzero((wins == pat).all(axis=1))
            if hit.size == 0:
                continue
            js = hit + n
            full = js[js + k <= L]
            # most recent occurrence with k tokens of continuation, else
            # the most recent occurrence's shorter tail (degenerate repeats
            # land here until the period covers k)
            j = int(full[-1]) if full.size else int(js[-1])
            return ctx[j:min(j + k, L)].astype(np.int32)
        return np.empty(0, np.int32)

    def release(self, slot: int) -> None:
        pass


# -------------------------------------------------------------- draft model
class DraftModelDrafter:
    """A small GPT proposing ``k`` greedy tokens from its own dense KV cache.

    Per-slot state: the draft model's contiguous cache plus the exact token
    list it has consumed. On every call the verified context is diffed
    against that list — accepted drafts are already cached (their KV was
    written when they were PROPOSED), rejected ones rewind by truncating the
    host list and resetting ``cache["pos"]`` (entries past ``pos`` are
    masked by the cached-attention validity mask and overwritten in place,
    so rollback costs nothing). The context delta then streams in
    power-of-two chunks (exact sizes — the persistent cache can't absorb
    the padding the target's prefill scatter drops), and ``k`` greedy steps
    propose the window.

    Compile discipline: feed programs per chunk bucket + ONE single-token
    step program, recorded in the serving engine's ``compile_log`` (kinds
    ``draft_feed``/``draft_step``) where the unbucketed-decode-shape rule
    audits them alongside the target's programs."""

    kind = "draft_model"

    def __init__(self, cfg, params, max_len: int, dtype="float32",
                 max_chunk: int = 64, compile_log: Optional[list] = None,
                 monitor=None):
        import jax
        import jax.numpy as jnp

        from ...models import gpt as gpt_mod
        from .buckets import default_buckets

        self.cfg = cfg
        self.max_len = int(max_len)
        self.dtype = jnp.dtype(dtype)
        self._jax = jax
        self._jnp = jnp
        self._gpt = gpt_mod

        def _cast(x):
            if gpt_mod._is_qleaf(x):
                return x
            return (x.astype(self.dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x)

        self.params = jax.tree_util.tree_map(_cast, params,
                                             is_leaf=gpt_mod._is_qleaf)
        self._buckets = default_buckets(1, max(int(max_chunk), 1))
        self._feed_fns: Dict[int, Any] = {}
        self._step_fn = None
        self._slots: Dict[int, Dict[str, Any]] = {}
        self.compile_log = compile_log
        self.monitor = monitor

    # ------------------------------------------------------------- programs
    def _log_compile(self, kind: str, shape) -> None:
        if self.compile_log is not None:
            from .buckets import record_compile

            record_compile(self.compile_log, self.monitor,
                           "Serving/compile_events", kind, shape)

    def _get_feed(self, chunk: int):
        if chunk not in self._feed_fns:
            self._log_compile("draft_feed", (1, chunk))
            jax, gpt_mod = self._jax, self._gpt

            def fn(params, ids, cache):
                return gpt_mod.forward_with_cache(self.cfg, params, ids,
                                                  cache)

            self._feed_fns[chunk] = jax.jit(fn, donate_argnums=(2,))
        return self._feed_fns[chunk]

    def _get_step(self):
        if self._step_fn is None:
            self._log_compile("draft_step", (1, 1))
            jax, jnp, gpt_mod = self._jax, self._jnp, self._gpt

            def fn(params, tok, cache):
                logits, cache = gpt_mod.forward_with_cache(
                    self.cfg, params, tok[None, None], cache)
                return jnp.argmax(logits[0, -1]).astype(jnp.int32), cache

            self._step_fn = jax.jit(fn, donate_argnums=(2,))
        return self._step_fn

    # ------------------------------------------------------------- protocol
    def draft(self, slot: int, rid: int, prompt: np.ndarray,
              tokens: Sequence[int], k: int) -> np.ndarray:
        jnp, gpt_mod = self._jnp, self._gpt
        ctx = [int(t) for t in np.asarray(prompt).tolist()] + \
              [int(t) for t in tokens]
        if k < 1 or len(ctx) + k > self.max_len:
            return np.empty(0, np.int32)   # window would outgrow the cache
        st = self._slots.get(slot)
        if st is None or st["rid"] != rid:
            st = {"rid": rid,
                  "cache": gpt_mod.init_cache(self.cfg, 1, self.max_len,
                                              self.dtype),
                  "fed": []}
            self._slots[slot] = st
        fed: List[int] = st["fed"]
        p = 0
        limit = min(len(fed), len(ctx) - 1)   # always re-feed >= 1 token so
        while p < limit and fed[p] == ctx[p]:  # the draft has fresh logits
            p += 1
        if p < len(fed):
            # rejected drafts (or a preemption replay): rewind — positions
            # past p are masked + overwritten, no device copy needed
            st["fed"] = fed = fed[:p]
            cache = dict(st["cache"])
            cache["pos"] = jnp.int32(p)
            st["cache"] = cache
        delta = ctx[p:]
        cache = st["cache"]
        logits = None
        # exact-size power-of-two pieces: the persistent cache advances by
        # the full fed shape, so padding would poison positions
        while delta:
            piece = 1
            for b in self._buckets:
                if b <= len(delta):
                    piece = b
            ids = np.asarray(delta[:piece], np.int32)[None]
            logits, cache = self._get_feed(piece)(self.params,
                                                  jnp.asarray(ids), cache)
            delta = delta[piece:]
        st["fed"] = fed = fed + ctx[p:]
        nxt = int(jnp.argmax(logits[0, -1]))
        drafts = [nxt]
        step = self._get_step()
        for _ in range(k - 1):
            tok, cache = step(self.params, jnp.int32(drafts[-1]), cache)
            drafts.append(int(tok))
        # the k-th draft was never fed — its KV is not in the cache
        st["fed"] = fed + drafts[:-1]
        st["cache"] = cache
        return np.asarray(drafts, np.int32)

    def release(self, slot: int) -> None:
        self._slots.pop(slot, None)


def make_drafter(engine, serving) -> Optional[Any]:
    """Build the configured drafter for a :class:`~.engine.ServingEngine`
    (``ServingConfig.spec_drafter``: None | "ngram" | "draft_model")."""
    kind = serving.spec_drafter
    if not kind:
        return None
    if kind == "ngram":
        return NGramDrafter(max_n=serving.spec_ngram)
    if kind == "draft_model":
        draft = getattr(engine, "draft", None)
        if draft is None:
            if not serving.spec_draft_model:
                raise ValueError(
                    "spec_drafter='draft_model' needs either "
                    "ServingEngine(draft=(cfg, params)) or "
                    "ServingConfig.spec_draft_model (a PRESETS name; "
                    "seed-0 init — pass real params for real acceptance)")
            import jax

            from ...models import gpt as gpt_mod

            dcfg = gpt_mod.PRESETS[serving.spec_draft_model]
            draft = (dcfg, gpt_mod.init_params(dcfg, jax.random.PRNGKey(0)))
        dcfg, dparams = draft
        return DraftModelDrafter(
            dcfg, dparams, max_len=serving.max_model_len,
            dtype=engine.dtype, max_chunk=serving.prefill_chunk,
            compile_log=engine.compile_log, monitor=engine.monitor)
    raise ValueError(f"unknown spec_drafter {kind!r} "
                     f"(None | 'ngram' | 'draft_model')")


__all__ = ["Drafter", "NGramDrafter", "DraftModelDrafter", "AdaptiveSpecK",
           "spec_k_ladder", "make_drafter"]
