"""SLO tiers and multi-tenant fairness for the serving stack.

The overload controls of the admission-control PR treat all traffic as one
class: a batch tenant flooding ``submit()`` degrades every interactive user
identically.  This module gives the scheduler the vocabulary to honor
per-SLO capacity contracts instead (docs/SERVING.md "Multi-tenancy & SLO
tiers"):

- :class:`TierConfig` — one service class (``interactive`` / ``standard`` /
  ``batch``): WFQ weight, per-tier TTFT/e2e deadline defaults, per-tier
  admission partitions, the brownout ``max_new`` clamp, and the default
  per-tenant token-bucket rate.
- :class:`TenantConfig` — one tenant: its tier plus optional rate overrides.
- :class:`StartTimeFairQueue` — start-time fair queueing (SFQ) virtual-time
  tags: per-tenant flows weighted by tier, provably starvation-free (every
  backlogged flow's start tags advance, so min-tag selection serves each
  flow within a weight-proportional bound).
- :class:`TokenBucket` — per-tenant admission rate limit.
- :class:`BrownoutController` — the degradation ladder: under sustained
  pressure (shed-rate / deadline-miss trend over a sliding window) degrade
  in tier order — shed batch first, then clamp batch ``max_new``, then hold
  standard in the queue; interactive is protected until last.  Every
  transition is reversible (exit hysteresis) and recorded as a typed
  ``Serving/tier_brownout`` event by the scheduler.

Nothing here touches a device: pure host-side bookkeeping the scheduler
consults between dispatches.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Tuple

#: Canonical tier names, most- to least-protected.  Degradation walks this
#: tuple from the right (batch sacrificed first); preemption victim
#: selection uses the same order.
TIER_ORDER: Tuple[str, str, str] = ("interactive", "standard", "batch")

#: Degradation-ladder stage names, index == stage number.
BROWNOUT_STAGES: Tuple[str, str, str, str] = (
    "normal", "shed_batch", "clamp_batch", "hold_standard")

#: Tier assumed for requests that carry no tier (and for unknown tenants).
DEFAULT_TIER = "standard"


@dataclass(frozen=True)
class TierConfig:
    """One service class. ``weight`` is the WFQ share under contention;
    deadline fields are *defaults* applied at submit when the request
    carries none (request-specified deadlines always win)."""

    name: str
    weight: float = 1.0
    ttft_deadline_s: Optional[float] = None
    deadline_s: Optional[float] = None
    #: per-tier admission partition (falls back to the scheduler's global
    #: ``max_queue`` / ``max_queued_tokens`` when None)
    max_queue: Optional[int] = None
    max_queued_tokens: Optional[int] = None
    #: ``max_new_tokens`` clamp applied to this tier while the brownout
    #: ladder is at ``clamp_batch`` or deeper (only meaningful for batch)
    brownout_max_new: Optional[int] = None
    #: default per-tenant token-bucket refill rate / capacity, in request
    #: work-tokens per second (None = unlimited)
    rate_tokens_per_s: Optional[float] = None
    rate_burst_tokens: Optional[float] = None
    #: decode slots held open for THIS tier: less-protected tiers are only
    #: admitted while at least this many slots stay free (strict headroom
    #: — running requests of this tier do NOT repay the reservation), so
    #: an arrival in the protected tier finds a slot without waiting out
    #: (or displacing) anyone. Capacity cost: lower tiers utilize at most
    #: ``num_slots - reserved`` slots under sustained load. The scheduler
    #: rejects tables whose total reservation eats every slot.
    reserved_slots: int = 0

    def validate(self) -> None:
        if self.name not in TIER_ORDER:
            raise ValueError(
                f"unknown tier {self.name!r}: tiers are {TIER_ORDER}")
        if not (self.weight > 0):
            raise ValueError(
                f"tier {self.name!r}: weight must be > 0, got {self.weight}")
        for knob in ("ttft_deadline_s", "deadline_s", "rate_tokens_per_s",
                     "rate_burst_tokens"):
            v = getattr(self, knob)
            if v is not None and not (float(v) > 0):
                raise ValueError(
                    f"tier {self.name!r}: {knob} must be > 0, got {v}")
        for knob in ("max_queue", "max_queued_tokens", "brownout_max_new"):
            v = getattr(self, knob)
            if v is not None and int(v) < 1:
                raise ValueError(
                    f"tier {self.name!r}: {knob} must be >= 1, got {v}")
        if int(self.reserved_slots) < 0:
            raise ValueError(f"tier {self.name!r}: reserved_slots must be "
                             f">= 0, got {self.reserved_slots}")


@dataclass(frozen=True)
class TenantConfig:
    """One tenant: which tier it bills to, plus optional per-tenant
    token-bucket overrides (None = the tier's default)."""

    tenant_id: str
    tier: str = DEFAULT_TIER
    rate_tokens_per_s: Optional[float] = None
    rate_burst_tokens: Optional[float] = None

    def validate(self, tiers: Mapping[str, "TierConfig"]) -> None:
        if self.tier not in tiers:
            raise ValueError(
                f"tenant {self.tenant_id!r}: unknown tier {self.tier!r} "
                f"(configured: {sorted(tiers)})")
        for knob in ("rate_tokens_per_s", "rate_burst_tokens"):
            v = getattr(self, knob)
            if v is not None and not (float(v) > 0):
                raise ValueError(
                    f"tenant {self.tenant_id!r}: {knob} must be > 0, got {v}")


def default_tiers() -> Dict[str, TierConfig]:
    """The shipped 3-tier contract: interactive holds its TTFT under load,
    batch has no deadline and absorbs the shed."""
    return {
        "interactive": TierConfig("interactive", weight=8.0,
                                  ttft_deadline_s=2.0, deadline_s=30.0),
        "standard": TierConfig("standard", weight=3.0,
                               ttft_deadline_s=10.0, deadline_s=120.0),
        "batch": TierConfig("batch", weight=1.0, brownout_max_new=16),
    }


def resolve_tiers(spec: Any) -> Optional[Dict[str, TierConfig]]:
    """Normalize a ``ServingConfig.tiers`` value into a validated
    ``{name: TierConfig}`` table.

    ``None`` → untiered (the scheduler keeps its FIFO semantics);
    ``True`` or ``"default"`` → :func:`default_tiers`; a mapping of
    ``{name: TierConfig | dict}`` → per-tier overrides merged over the
    defaults (a dict value may omit ``name``).
    """
    if spec is None:
        return None
    if spec is True or spec == "default":
        return default_tiers()
    if not isinstance(spec, Mapping):
        raise ValueError(
            f"tiers must be None, True, 'default' or a mapping, "
            f"got {type(spec).__name__}")
    table = default_tiers()
    for name, value in spec.items():
        if isinstance(value, TierConfig):
            cfg = value
        elif isinstance(value, Mapping):
            kw = dict(value)
            kw.setdefault("name", name)
            cfg = TierConfig(**kw)
        else:
            raise ValueError(
                f"tier {name!r}: expected TierConfig or dict, "
                f"got {type(value).__name__}")
        if cfg.name != name:
            raise ValueError(
                f"tier key {name!r} != TierConfig.name {cfg.name!r}")
        table[name] = cfg
    for cfg in table.values():
        cfg.validate()
    return table


def resolve_tenants(spec: Any,
                    tiers: Mapping[str, TierConfig]) -> Dict[str, TenantConfig]:
    """Normalize a ``ServingConfig.tenants`` value into a validated
    ``{tenant_id: TenantConfig}`` table (unknown tenants default to
    :data:`DEFAULT_TIER` at submit time — the table is a contract, not a
    gate)."""
    if spec is None:
        return {}
    if not isinstance(spec, Mapping):
        raise ValueError(
            f"tenants must be None or a mapping, got {type(spec).__name__}")
    table: Dict[str, TenantConfig] = {}
    for tenant_id, value in spec.items():
        if isinstance(value, TenantConfig):
            cfg = value
        elif isinstance(value, Mapping):
            kw = dict(value)
            kw.setdefault("tenant_id", tenant_id)
            cfg = TenantConfig(**kw)
        elif isinstance(value, str):
            cfg = TenantConfig(tenant_id, tier=value)
        else:
            raise ValueError(
                f"tenant {tenant_id!r}: expected TenantConfig, dict or "
                f"tier name, got {type(value).__name__}")
        if cfg.tenant_id != tenant_id:
            raise ValueError(f"tenant key {tenant_id!r} != "
                             f"TenantConfig.tenant_id {cfg.tenant_id!r}")
        cfg.validate(tiers)
        table[tenant_id] = cfg
    return table


def tier_rank(tier: Optional[str]) -> int:
    """Protection rank: 0 = interactive (most protected). Unknown/None
    ranks as :data:`DEFAULT_TIER`."""
    try:
        return TIER_ORDER.index(tier)  # type: ignore[arg-type]
    except ValueError:
        return TIER_ORDER.index(DEFAULT_TIER)


def sacrifice_key(tier: Optional[str], admit_seq: int) -> Tuple[int, int]:
    """Preemption-victim ordering: batch slots die before interactive ones,
    newest-first within a tier (``max()`` over this key picks the victim,
    preserving the growing-slot rule — the grower itself can win)."""
    return (tier_rank(tier), admit_seq)


class TokenBucket:
    """Per-tenant admission rate limit in work-tokens/s. ``try_take``
    refills lazily from the wall clock it is handed (the scheduler's
    injectable clock, so tests drive it manually)."""

    def __init__(self, rate_tokens_per_s: float,
                 burst_tokens: Optional[float] = None):
        self.rate = float(rate_tokens_per_s)
        self.burst = float(burst_tokens if burst_tokens is not None
                           else max(self.rate, 1.0))
        self.tokens = self.burst
        self._last: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._last is not None and now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now if self._last is None else max(self._last, now)

    def try_take(self, n: float, now: float) -> bool:
        self._refill(now)
        if self.tokens + 1e-9 >= n:
            self.tokens -= n
            return True
        return False


class StartTimeFairQueue:
    """Start-time fair queueing (SFQ) virtual-time tags.

    Flows are tenants; a flow's weight is its tier's WFQ weight.  At submit,
    a request is stamped ``start = max(V, finish[flow])``,
    ``finish = start + cost/weight`` (cost = work tokens), which chains a
    tenant's backlog behind itself — a deep batch backlog pushes only its
    *own* tags out, never another tenant's.  Selection takes the minimum
    start tag and advances ``V`` to it, so every backlogged flow is served
    within a weight-proportional bound (the WFQ starvation-freedom
    property tested in tests/test_tenancy.py)."""

    def __init__(self) -> None:
        self.vtime = 0.0
        self._finish: Dict[str, float] = {}

    def stamp(self, flow: str, weight: float,
              cost: float) -> Tuple[float, float]:
        start = max(self.vtime, self._finish.get(flow, 0.0))
        finish = start + max(float(cost), 1.0) / max(float(weight), 1e-9)
        self._finish[flow] = finish
        return start, finish

    def on_select(self, start: float) -> None:
        self.vtime = max(self.vtime, start)


@dataclass
class BrownoutConfig:
    """Ladder thresholds. Pressure = organic shed rate (sheds NOT caused by
    the ladder itself) or deadline misses over the sliding window; the
    dwell time is the enter/exit hysteresis."""

    window_s: float = 5.0
    enter_shed_rate: float = 0.25
    enter_misses: int = 2
    #: exit when the window's shed rate is below this AND misses are quiet
    exit_shed_rate: float = 0.05
    #: minimum seconds between any two stage transitions (hysteresis)
    min_dwell_s: float = 1.0


@dataclass
class BrownoutController:
    """The degradation ladder's brain: feed it organic pressure events,
    poll :meth:`decide` for the stage. One stage step per transition, both
    directions, with ``min_dwell_s`` hysteresis so the ladder cannot
    flap inside a window."""

    cfg: BrownoutConfig = field(default_factory=BrownoutConfig)
    stage: int = 0
    _events: List[Tuple[float, str]] = field(default_factory=list)
    _last_transition: Optional[float] = None

    MAX_STAGE = len(BROWNOUT_STAGES) - 1

    def observe(self, kind: str, now: float) -> None:
        """``kind``: 'submit' | 'shed' (organic only) | 'miss'."""
        self._events.append((float(now), kind))

    def _window(self, now: float) -> Tuple[int, int, int]:
        lo = now - self.cfg.window_s
        self._events = [(t, k) for (t, k) in self._events if t >= lo]
        submits = sum(1 for _, k in self._events if k == "submit")
        sheds = sum(1 for _, k in self._events if k == "shed")
        misses = sum(1 for _, k in self._events if k == "miss")
        return submits, sheds, misses

    def decide(self, now: float) -> int:
        """Returns the (possibly new) stage; at most one step per call."""
        if (self._last_transition is not None
                and now - self._last_transition < self.cfg.min_dwell_s):
            return self.stage
        submits, sheds, misses = self._window(now)
        shed_rate = sheds / max(submits, 1)
        pressured = (shed_rate >= self.cfg.enter_shed_rate
                     or misses >= self.cfg.enter_misses)
        quiet = shed_rate < self.cfg.exit_shed_rate and misses == 0
        if pressured and self.stage < self.MAX_STAGE:
            self.stage += 1
            self._last_transition = now
        elif quiet and self.stage > 0:
            self.stage -= 1
            self._last_transition = now
        return self.stage

    @property
    def stage_name(self) -> str:
        return BROWNOUT_STAGES[self.stage]


__all__ = [
    "TIER_ORDER", "BROWNOUT_STAGES", "DEFAULT_TIER",
    "TierConfig", "TenantConfig", "default_tiers", "resolve_tiers",
    "resolve_tenants", "tier_rank", "sacrifice_key", "TokenBucket",
    "StartTimeFairQueue", "BrownoutConfig", "BrownoutController",
]
