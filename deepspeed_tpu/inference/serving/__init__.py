"""Continuous-batching serving: paged KV cache + per-step scheduler.

See ``docs/SERVING.md``. Layering:

- :mod:`.paging` — host-side page allocator (free list; page 0 reserved).
- :mod:`.buckets` — the shape-bucket helpers the serving engine and
  ``InferenceEngine`` share to bound compile counts.
- :mod:`.scheduler` — device-free admit/evict/preempt over decode slots.
- :mod:`.engine` — compiled prefill/decode/scatter programs (the executor).
- :mod:`.bench` — open-loop workload, TTFT/tokens-per-sec reports, and the
  static-batch baseline A/B.
"""

from .buckets import bucket_for, default_buckets
from .engine import ServingConfig, ServingEngine
from .paging import (PageAllocator, PrefixIndex, RESERVED_PAGE, pages_for,
                     prefix_chain_hashes)
from .scheduler import (AdmissionVerdict, ContinuousBatchingScheduler,
                        Request, RequestState, SHED_POLICIES,
                        ServingFaultError)
from .speculate import (AdaptiveSpecK, DraftModelDrafter, NGramDrafter,
                        spec_k_ladder)
from .tenancy import (BROWNOUT_STAGES, BrownoutConfig, BrownoutController,
                      DEFAULT_TIER, StartTimeFairQueue, TIER_ORDER,
                      TenantConfig, TierConfig, TokenBucket, default_tiers,
                      resolve_tenants, resolve_tiers, sacrifice_key,
                      tier_rank)
from .bench import (estimate_saturation_rps, make_open_loop_workload,
                    make_tiered_workload, percentile, run_continuous,
                    run_static_baseline)

__all__ = [
    "PageAllocator", "PrefixIndex", "RESERVED_PAGE", "pages_for",
    "prefix_chain_hashes",
    "bucket_for", "default_buckets",
    "AdmissionVerdict", "ContinuousBatchingScheduler", "Request",
    "RequestState", "SHED_POLICIES", "ServingFaultError",
    "ServingConfig", "ServingEngine",
    "AdaptiveSpecK", "DraftModelDrafter", "NGramDrafter", "spec_k_ladder",
    "BROWNOUT_STAGES", "BrownoutConfig", "BrownoutController",
    "DEFAULT_TIER", "StartTimeFairQueue", "TIER_ORDER", "TenantConfig",
    "TierConfig", "TokenBucket", "default_tiers", "resolve_tenants",
    "resolve_tiers", "sacrifice_key", "tier_rank",
    "estimate_saturation_rps", "make_open_loop_workload",
    "make_tiered_workload", "percentile",
    "run_continuous", "run_static_baseline",
]
