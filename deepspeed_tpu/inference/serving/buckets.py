"""Shape buckets shared by the serving engine and ``InferenceEngine``.

Every distinct (batch, length) pair a jitted program sees is a compile; an
unbucketed serving path compiles per request shape and a naive decode loop
compiles per STEP (the bug the ``serving/unbucketed-decode-shape`` dslint
rule catches). Rounding lengths up to a small geometric bucket set bounds
the compile count at ``log2(max/min)`` programs, each reused forever.
"""

from __future__ import annotations

from typing import Sequence, Tuple


def default_buckets(lo: int = 32, hi: int = 1024) -> Tuple[int, ...]:
    """Powers of two from ``lo`` up to AND covering ``hi``."""
    if lo < 1 or hi < lo:
        raise ValueError(f"bad bucket range [{lo}, {hi}]")
    out = []
    b = 1
    while b < lo:
        b *= 2
    while b < hi:
        out.append(b)
        b *= 2
    out.append(b)
    return tuple(out)


def record_compile(compile_log: list, monitor, channel: str, kind: str,
                   shape: Tuple[int, ...], hint: str = "") -> None:
    """Append one compiled-program cache-miss record and emit it.

    The single schema both engines log and the
    ``serving/unbucketed-decode-shape`` dslint rule consumes:
    ``{"kind", "shape", "time"}``. ``channel`` names the monitor scalar
    (``Serving/compile_events`` / ``Inference/compile_events``); ``hint`` is
    appended to the log line once misses start repeating (n >= 4)."""
    import time

    from ...utils.logging import log_dist

    compile_log.append({"kind": kind,
                        "shape": tuple(int(x) for x in shape),
                        "time": time.time()})
    n = len(compile_log)
    log_dist(f"{channel.split('/')[0].lower()} engine: compiling {kind} "
             f"shape={shape} (compile #{n})"
             + (f" — {hint}" if hint and n >= 4 else ""))
    if monitor is not None:
        monitor.write_events([(channel, n, n)])


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n. Raises when nothing covers ``n`` (the caller
    sized its bucket set to the model/serving bound on purpose — silently
    exceeding it would recompile)."""
    if n < 0:
        raise ValueError(f"bucket_for({n})")
    for b in sorted(buckets):
        if n <= b:
            return int(b)
    raise ValueError(f"length {n} exceeds the largest bucket "
                     f"{max(buckets)} — raise the bucket set or reject the "
                     f"request at admission")
