"""Serving engine: bucketed chunked prefill + fixed-slot paged decode.

The device half of the continuous-batching stack (the host half is
``scheduler.ContinuousBatchingScheduler``). Three compiled program families,
each with a bounded shape set:

- **decode** — ONE program: ``models/gpt.paged_decode_step`` over the fixed
  decode slot array [num_slots], greedy-sampled in-program. Every serving
  step replays this executable regardless of which requests occupy the
  slots; nothing about request arrival order can cause a recompile.
- **prefill** — one program per chunk bucket (powers of two up to
  ``prefill_chunk``): the prompt streams through the contiguous-cache
  forward in fixed-size chunks, so prompt length changes the chunk COUNT,
  not the compiled shapes. Prefill is disaggregated from decode: it never
  touches the page pool until the final scatter.
- **scatter** — one program: ``write_prompt_kv`` placing the prefilled
  dense K/V into the request's pages.

Every first build of any of these is recorded in ``compile_log`` (and the
optional monitor) — the evidence stream the
``serving/unbucketed-decode-shape`` dslint rule audits.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ...models import gpt as gpt_mod
from ...utils.logging import log_dist
from .buckets import bucket_for, default_buckets, record_compile
from .paging import pages_for
from .scheduler import ContinuousBatchingScheduler


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name from a handoff payload — including the ml_dtypes
    extension types (bfloat16) plain numpy can't look up by string."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


@dataclasses.dataclass
class ServingConfig:
    """Knobs for the serving path. ``num_slots`` is the admission limit —
    pass an int you trust, or "auto" to derive it from the AOT fit ladder
    (``runtime.aot.serving_admission_limit``, compile-time verdicts only)."""

    num_slots: Union[int, str] = 4
    page_size: int = 64
    max_model_len: int = 1024           # prompt + generation bound
    num_pages: Optional[int] = None     # default: every slot can max out
    prefill_chunk: int = 128
    # quantized KV pages (docs/SERVING.md "KV quantization & prefix
    # caching"): 8 or 4 stores the pools int8/int4 with per-(head, page)
    # scales, dequantized inside the decode kernel — 2x/4x the token
    # capacity at fixed HBM vs bf16 pools (4x/8x vs fp32). None = dense.
    kv_bits: Optional[int] = None
    # copy-on-write shared-prefix page reuse: requests whose prompts begin
    # with the same page-aligned token blocks share physical pages through
    # the allocator refcounts + PrefixIndex hash chains
    enable_prefix_cache: bool = False
    # KV-page integrity (docs/RESILIENCE.md "Data integrity"): fingerprint
    # pages as they freeze behind the write frontier (prefix registration,
    # handoff staging) and verify at every trust boundary — prefix share,
    # handoff import, recovery audits, plus a budgeted background sweep of
    # pages_scan_per_step stamped pages per scheduler step. A mismatch
    # evicts the page and re-prefills its borrowers (greedy-identical heal)
    page_fingerprints: bool = False
    pages_scan_per_step: int = 1
    # decode block: when no scheduling event (admission, page growth, eos,
    # slot finish) can occur within the next K steps, the scheduler runs K
    # decode steps as ONE compiled scan — K-1 host round-trips saved per
    # block. Must be <= page_size (inactive slots park on the sink page for
    # at most one page worth of steps).
    decode_block: int = 4
    # ---- speculative decoding (docs/SERVING.md "Speculative decoding"):
    # a drafter proposes up to spec_k tokens per slot; one paged verify
    # dispatch scores k+1 positions; longest-prefix GREEDY acceptance
    # commits only the confirmed prefix, so speculation provably never
    # changes outputs. spec_k is the ladder CEILING — the adaptive
    # controller collapses k toward 1 when drafts stop landing.
    spec_drafter: Optional[str] = None       # None | "ngram" | "draft_model"
    spec_k: int = 4
    spec_adaptive: bool = True
    spec_ngram: int = 3                      # max suffix n-gram order
    spec_draft_model: Optional[str] = None   # PRESETS name: num_slots="auto"
    #                                          HBM accounting + default draft
    # the equivalence-harness flag: set when an A/B run asserts
    # greedy_match_rate == 1.0 itself — silences the
    # serving/speculation-without-greedy-gate rule for non-greedy configs
    spec_equivalence_harness: bool = False
    # acceptance path: the engine implements greedy (temperature-0) only —
    # the invariant that makes longest-prefix acceptance output-preserving.
    # A nonzero temperature with a drafter armed is the misconfiguration
    # the dslint rule flags (sampled acceptance needs rejection sampling,
    # which nothing here implements).
    sampling_temperature: float = 0.0
    dtype: str = "bfloat16"
    kernel_impl: Optional[str] = None   # None=auto | "kernel" | "gather"
    # ---- tensor-parallel replica (docs/SERVING.md "Tensor parallel &
    # disaggregation"): tp > 1 shards the weight stacks, paged pools and
    # every serving program across the first `tp` devices of a dedicated
    # ("tp",) mesh (inference/serving/tp.py). The scheduler, page
    # allocator, speculation and chaos machinery are mesh-oblivious; tp2
    # output is greedy-identical to tp1.
    tp: int = 1
    # ---- disaggregated prefill/decode role. "both" (default) = the fused
    # single-replica engine; "prefill" = fill pages + first token, then
    # hand the request off (scheduler HANDOFF state -> fleet forwarding);
    # "decode" = accept page-handoff admissions. Roles gate which program
    # families warm up eagerly — the rest stay lazily compilable so
    # failover (a decode replica re-prefilling an orphaned request) still
    # works, it just pays a mid-traffic compile.
    role: str = "both"
    eos_token_id: Optional[int] = None
    model_name: Optional[str] = None    # for num_slots="auto"
    # ---- overload control + deadlines (docs/SERVING.md "Overload &
    # failure"). All default OFF — the overload-unsafe default the dslint
    # rule `serving/unbounded-admission` warns about; production configs
    # should arm max_queue (and usually deadlines).
    max_queue: Optional[int] = None          # admission queue depth cap
    max_queued_tokens: Optional[int] = None  # queued-work token budget
    shed_policy: str = "reject_newest"       # or "reject_largest"
    ttft_deadline_s: Optional[float] = None  # default per-request deadlines
    request_deadline_s: Optional[float] = None
    # ---- SLO tiers / multi-tenancy (docs/SERVING.md "Multi-tenancy & SLO
    # tiers"). Default OFF: tiers=None keeps the scheduler's FIFO queue and
    # seed-identical behavior. tiers=True (or "default") arms the built-in
    # interactive/standard/batch ladder; a mapping of TierConfig/dict
    # overrides merges over the defaults. tenants maps tenant_id to a
    # TenantConfig / dict / bare tier name. Both are validated eagerly in
    # ServingEngine.__init__ via tenancy.resolve_tiers / resolve_tenants.
    tiers: Union[None, bool, str, dict] = None
    tenants: Optional[dict] = None
    # degradation-ladder (brownout) controller knobs — only read when tiers
    # are armed; see tenancy.BrownoutConfig for semantics
    brownout_window_s: float = 5.0
    brownout_enter_shed_rate: float = 0.25
    brownout_enter_misses: int = 2
    brownout_exit_shed_rate: float = 0.05
    brownout_min_dwell_s: float = 1.0
    # ---- dispatch fault recovery
    dispatch_retries: int = 2
    quarantine_after: int = 2                # failures before a decode block
    #                                          shape is quarantined
    dispatch_failure_budget: int = 8         # consecutive failed episodes
    #                                          before ServingFaultError
    prefill_deadline_s: Optional[float] = None  # watchdog phase deadlines
    decode_deadline_s: Optional[float] = None
    watchdog_poll_s: float = 0.25
    stacks_dir: Optional[str] = None         # stall stack dumps land here

    @property
    def pages_per_seq(self) -> int:
        return pages_for(self.max_model_len, self.page_size)

    @property
    def spec_k_set(self) -> tuple:
        """The bounded draft-length ladder (compile one verify program per
        entry; empty when no drafter is configured)."""
        if not self.spec_drafter:
            return ()
        from .speculate import spec_k_ladder

        return spec_k_ladder(self.spec_k)

    @property
    def overload_armed(self) -> bool:
        """Whether ANY admission bound or deadline protects this config —
        what the ``serving/unbounded-admission`` rule checks."""
        return (self.max_queue is not None
                or self.max_queued_tokens is not None
                or self.ttft_deadline_s is not None
                or self.request_deadline_s is not None)

    @property
    def tiers_armed(self) -> bool:
        """Whether SLO-tier scheduling is configured — what the
        ``serving/untiered-multi-tenant`` rule checks when it sees multiple
        tenant_ids in the submit evidence."""
        return bool(self.tiers)

    def resolved_tiers(self):
        """Validated (tiers, tenants, brownout) triple for the scheduler —
        (None, {}, None) when tiers are unarmed."""
        from .tenancy import (BrownoutConfig, resolve_tenants, resolve_tiers)

        tiers = resolve_tiers(self.tiers)
        tenants = resolve_tenants(self.tenants, tiers)
        brownout = None
        if tiers is not None:
            brownout = BrownoutConfig(
                window_s=float(self.brownout_window_s),
                enter_shed_rate=float(self.brownout_enter_shed_rate),
                enter_misses=int(self.brownout_enter_misses),
                exit_shed_rate=float(self.brownout_exit_shed_rate),
                min_dwell_s=float(self.brownout_min_dwell_s))
        return tiers, tenants, brownout


class ServingEngine:
    """Executor over a GPT config + params (see module docstring)."""

    def __init__(self, cfg: gpt_mod.GPTConfig, params,
                 serving: Optional[ServingConfig] = None, monitor=None,
                 draft=None):
        self.cfg = cfg
        self.serving = serving or ServingConfig()
        self.monitor = monitor
        self.compile_log: List[dict] = []
        # draft-model speculation: (GPTConfig, params) of the SMALL model
        # that proposes tokens for this engine's target (speculate.py)
        self.draft = draft
        s = self.serving
        if s.max_model_len > cfg.max_seq_len and not (cfg.rotary or cfg.alibi):
            raise ValueError(
                f"max_model_len {s.max_model_len} exceeds the model's learned "
                f"position table ({cfg.max_seq_len})")
        if s.sampling_temperature:
            raise NotImplementedError(
                "serving programs sample greedily (temperature 0) — the "
                "invariant speculative acceptance relies on; "
                f"sampling_temperature={s.sampling_temperature} is not "
                "implemented")
        if s.spec_drafter and not (1 <= s.spec_k <= 16):
            raise ValueError(f"spec_k {s.spec_k} outside [1, 16]")
        if s.role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, got "
                             f"{s.role!r}")
        # tier/tenant specs fail fast at engine construction, not first
        # submit — resolved_tiers() raises on malformed configs
        s.resolved_tiers()
        self.num_slots = self._resolve_slots()
        self.num_pages = (s.num_pages if s.num_pages is not None
                          else self.num_slots * s.pages_per_seq + 1)
        self.dtype = jnp.dtype({"bf16": "bfloat16", "fp32": "float32",
                                "fp16": "float16"}.get(s.dtype, s.dtype))

        def _cast(x):
            if gpt_mod._is_qleaf(x):
                return x
            return (x.astype(self.dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x)

        self.params = jax.tree_util.tree_map(_cast, params,
                                             is_leaf=gpt_mod._is_qleaf)
        self.paged_cache = gpt_mod.init_paged_cache(
            cfg, self.num_pages, s.page_size, self.dtype,
            kv_bits=s.kv_bits)
        # tensor-parallel replica: relayout + shard the weight tree and the
        # paged pools over a dedicated ("tp",) mesh; every program getter
        # below dispatches to the shard_map builders in tp.py
        self.tp_context = None
        if int(s.tp or 1) > 1:
            from .tp import TPContext

            self.tp_context = TPContext(cfg, int(s.tp))
            self.params = self.tp_context.shard_params(self.params)
            self.paged_cache = self.tp_context.shard_cache(self.paged_cache)
        self.last_scheduler = None  # most recent make_scheduler product —
        # the capacity-pressure evidence dslint's dense-kv-at-capacity reads
        # prefill's contiguous scratch cache: chunks append at chunk-aligned
        # positions, so it must cover the bucket-padded context
        chunks = -(-s.max_model_len // s.prefill_chunk)
        self._dense_S = chunks * s.prefill_chunk
        self._chunk_buckets = default_buckets(
            min(32, s.prefill_chunk), s.prefill_chunk)
        if not (1 <= s.decode_block <= s.page_size):
            raise ValueError(f"decode_block {s.decode_block} must be in "
                             f"[1, page_size={s.page_size}]")
        self._prefill_fns = {}
        self._prefill_fused_fns = {}
        self._prefill_batch_fns = {}
        self._decode_fns = {}
        self._verify_fns = {}
        self._scatter_fn = None

    def _resolve_slots(self) -> int:
        s = self.serving
        if s.num_slots != "auto":
            return int(s.num_slots)
        if not s.model_name:
            raise ValueError("num_slots='auto' needs model_name for the AOT "
                             "fit ladder")
        from ...runtime.aot import serving_admission_limit

        # kv_bits reaches the fit ladder: the compiled probe serves from
        # quantized pools, so "auto" sizes slots from the KV bytes the pool
        # ACTUALLY holds (a dense-page ladder under-admits ~2x at int8).
        # With speculation armed, the drafter's HBM (draft params + dense
        # draft cache + k-token verify activations) is charged against the
        # same budget so "auto" stays honest (aot.speculation_hbm_bytes).
        draft_model = None
        if s.spec_drafter:
            # price the draft model that will ACTUALLY be resident: an
            # explicit draft=(cfg, params) pair wins over the preset name
            draft_model = (self.draft[0] if self.draft is not None
                           else s.spec_draft_model)
        # tp + role reach the ladder too: a tp replica's per-chip HBM holds
        # 1/tp of the weights and pools, and a prefill-only replica never
        # pays the drafter/verify residency (aot prices per-role program
        # sets since PR 16)
        limit = serving_admission_limit(
            s.model_name, prompt=min(128, s.max_model_len),
            gen=min(128, s.max_model_len), kv_bits=s.kv_bits or 0,
            page_size=s.page_size, draft_model=draft_model,
            spec_k=(s.spec_k if s.spec_drafter else 0),
            spec_max_len=s.max_model_len, tp=int(s.tp or 1), role=s.role)
        if limit["max_slots"] < 1:
            raise ValueError(
                f"AOT fit ladder found no decode batch that fits for "
                f"{s.model_name}: {limit}")
        log_dist(f"serving: admission limit {limit['max_slots']} slots "
                 f"(AOT fit ladder, {s.model_name})")
        return int(limit["max_slots"])

    # -------------------------------------------------------------- programs
    def _log_compile(self, kind: str, shape: Tuple[int, ...]) -> None:
        record_compile(self.compile_log, self.monitor,
                       "Serving/compile_events", kind, shape)

    # ---- tp dispatch: each model program either calls the gpt.py
    # single-device function or its shard_map twin (tp.py) over the replica
    # mesh. Same signatures/semantics, so the jitted wrappers below stay
    # tp-oblivious.
    def _forward_with_cache(self, params, ids, cache):
        if self.tp_context is not None:
            from .tp import tp_forward_with_cache

            return tp_forward_with_cache(self.cfg, params, ids, cache,
                                         self.tp_context.mesh)
        return gpt_mod.forward_with_cache(self.cfg, params, ids, cache)

    def _write_prompt(self, paged, dense, table, length, start):
        if self.tp_context is not None:
            from .tp import tp_write_prompt_kv

            return tp_write_prompt_kv(paged, dense, table, length, start,
                                      self.tp_context.mesh)
        return gpt_mod.write_prompt_kv(paged, dense, table, length,
                                       start=start)

    def _write_prompt_batch(self, paged, dense, tables, lengths, starts):
        if self.tp_context is not None:
            from .tp import tp_write_prompt_kv_batch

            return tp_write_prompt_kv_batch(paged, dense, tables, lengths,
                                            starts, self.tp_context.mesh)
        return gpt_mod.write_prompt_kv_batch(paged, dense, tables, lengths,
                                             starts=starts)

    def _decode_step(self, params, toks, cache, tables, lengths, impl):
        if self.tp_context is not None:
            from .tp import tp_paged_decode_step

            return tp_paged_decode_step(self.cfg, params, toks, cache,
                                        tables, lengths,
                                        self.tp_context.mesh, impl=impl)
        return gpt_mod.paged_decode_step(self.cfg, params, toks, cache,
                                         tables, lengths, impl=impl)

    def _verify_step(self, params, toks, cache, tables, lengths, impl):
        if self.tp_context is not None:
            from .tp import tp_paged_verify_step

            return tp_paged_verify_step(self.cfg, params, toks, cache,
                                        tables, lengths,
                                        self.tp_context.mesh, impl=impl)
        return gpt_mod.paged_verify_step(self.cfg, params, toks, cache,
                                         tables, lengths, impl=impl)

    def _commit_window(self, cache, win_k, win_v, tables, lengths, n):
        if self.tp_context is not None:
            from .tp import tp_commit_window_kv

            return tp_commit_window_kv(cache, win_k, win_v, tables, lengths,
                                       n, self.tp_context.mesh)
        return gpt_mod.commit_window_kv(cache, win_k, win_v, tables,
                                        lengths, n)

    def _get_prefill(self, chunk: int):
        if chunk not in self._prefill_fns:
            self._log_compile("serving_prefill", (1, chunk))

            def fn(params, ids, cache):
                return self._forward_with_cache(params, ids, cache)

            self._prefill_fns[chunk] = jax.jit(fn, donate_argnums=(2,))
        return self._prefill_fns[chunk]

    def _get_prefill_fused(self, chunk: int):
        """Single-dispatch prefill for contexts <= one chunk: dense forward,
        page scatter, and the next-token argmax fused into one program (the
        common short-prompt admission path — 3 dispatches + a host sync
        collapse into 1)."""
        if chunk not in self._prefill_fused_fns:
            self._log_compile("serving_prefill_fused", (1, chunk))

            def fn(params, ids, paged, table, length, start):
                cache = gpt_mod.init_cache(self.cfg, 1, chunk, self.dtype)
                logits, cache = self._forward_with_cache(params, ids, cache)
                # start > 0: shared prefix pages already hold [0, start) —
                # never write a borrowed page (start is traced, so shared
                # and unshared admissions hit the same compiled program)
                paged = self._write_prompt(paged, cache, table, length, start)
                last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                                    keepdims=False)
                return jnp.argmax(last).astype(jnp.int32), paged

            self._prefill_fused_fns[chunk] = jax.jit(fn, donate_argnums=(2,))
        return self._prefill_fused_fns[chunk]

    def _get_prefill_batch(self, chunk: int):
        """Admission-batch prefill: every request admitted in one scheduler
        cycle (short prompts) prefills as ONE [num_slots, chunk] program —
        the prefill analog of the fixed decode slot array. Inactive rows
        carry length 0 + sink tables, so their writes drop."""
        if chunk not in self._prefill_batch_fns:
            self._log_compile("serving_prefill_batch",
                              (self.num_slots, chunk))

            def fn(params, ids, paged, tables, lengths, starts):
                cache = gpt_mod.init_cache(self.cfg, self.num_slots, chunk,
                                           self.dtype)
                logits, cache = self._forward_with_cache(params, ids, cache)
                paged = self._write_prompt_batch(paged, cache, tables,
                                                 lengths, starts)
                idx = jnp.maximum(lengths - 1, 0)[:, None, None]
                last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
                return jnp.argmax(last, axis=-1).astype(jnp.int32), paged

            self._prefill_batch_fns[chunk] = jax.jit(fn, donate_argnums=(2,))
        return self._prefill_batch_fns[chunk]

    def _get_decode(self, steps: int = 1):
        """The decode program for a ``steps``-long block (the scheduler uses
        only 1 and ``decode_block``, so at most two shapes compile)."""
        if steps not in self._decode_fns:
            self._log_compile("serving_decode", (steps, self.num_slots))
            impl = self.serving.kernel_impl

            def one(cache, toks, tables, lengths, params):
                logits, cache = self._decode_step(params, toks, cache,
                                                  tables, lengths, impl)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

            if steps == 1:
                def fn(params, cache, toks, tables, lengths):
                    nxt, cache = one(cache, toks, tables, lengths, params)
                    return nxt[None], cache
            else:
                def fn(params, cache, toks, tables, lengths):
                    def body(carry, _):
                        toks, lengths, cache = carry
                        nxt, cache = one(cache, toks, tables, lengths, params)
                        return (nxt, lengths + 1, cache), nxt

                    (_, _, cache), out = jax.lax.scan(
                        body, (toks, lengths, cache), None, length=steps)
                    return out, cache

            self._decode_fns[steps] = jax.jit(fn, donate_argnums=(1,))
        return self._decode_fns[steps]

    def _get_verify(self, W: int):
        """The speculative verification program for a ``W``-token window
        (W = k+1, k from the bounded ``spec_k_set`` ladder — at most
        log2(spec_k)+1 shapes ever compile). One dispatch: score every
        window position over the paged pool
        (``models/gpt.paged_verify_step``), greedy longest-prefix
        acceptance (truncated at a per-row eos and the remaining max_new
        budget) computed IN-program, and the accepted prefix's KV committed
        with sequential-append semantics (``commit_window_kv``). Returns
        (outputs [slots, W], n_accept [slots]) — n_accept counts both the
        tokens to append and the cache-length advance (they are equal by
        construction)."""
        if W not in self._verify_fns:
            self._log_compile("serving_verify", (W, self.num_slots))
            impl = self.serving.kernel_impl

            def fn(params, cache, toks, tables, lengths, eos, budget):
                logits, win_k, win_v = self._verify_step(
                    params, toks, cache, tables, lengths, impl)
                outs = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # longest-prefix greedy acceptance: draft i (toks[:, i+1])
                # survives iff it equals the target's output at position i
                # AND every earlier draft survived
                agree = (toks[:, 1:] == outs[:, :-1]).astype(jnp.int32)
                n = 1 + jnp.sum(jnp.cumprod(agree, axis=1), axis=1)
                # an accepted eos ends the request AT that token
                is_eos = (outs == eos[:, None]) & (eos[:, None] >= 0)
                eos_pos = jnp.argmax(is_eos, axis=1)
                n = jnp.where(jnp.any(is_eos, axis=1),
                              jnp.minimum(n, eos_pos + 1), n)
                # never accept past max_new (budget 0 = inactive slot:
                # nothing commits, nothing is written anywhere)
                n = jnp.clip(n, 0, jnp.maximum(budget, 0))
                cache = self._commit_window(cache, win_k, win_v, tables,
                                            lengths, n)
                return outs, n, cache

            self._verify_fns[W] = jax.jit(fn, donate_argnums=(1,))
        return self._verify_fns[W]

    def _get_scatter(self):
        if self._scatter_fn is None:
            self._log_compile("serving_scatter", (self._dense_S,))

            def fn(paged, dense, table, length, start):
                return self._write_prompt(paged, dense, table, length, start)

            self._scatter_fn = jax.jit(fn, donate_argnums=(0,))
        return self._scatter_fn

    # -------------------------------------------------------------- executor
    def prefill(self, slot: int, tokens: np.ndarray,
                table_row: np.ndarray, start: int = 0) -> int:
        """Chunked prefill of one request's context; writes its KV into the
        slot's pages; returns the greedy next token. ``start`` > 0 skips the
        scatter of positions [0, start) — those live in shared prefix pages
        the request only borrows (the forward still computes the full
        context; sharing saves pages, not prefill FLOPs)."""
        del slot  # pages are named by table_row; the slot id is host-side
        s = self.serving
        tokens = np.asarray(tokens, np.int32)
        T = int(tokens.shape[0])
        if T < 1 or T > s.max_model_len:
            raise ValueError(f"context length {T} outside (0, "
                             f"{s.max_model_len}]")
        if T <= s.prefill_chunk:  # fused short-prompt path: one dispatch
            chunk = bucket_for(T, self._chunk_buckets)
            ids = np.zeros((1, chunk), np.int32)
            ids[0, :T] = tokens
            tok, self.paged_cache = self._get_prefill_fused(chunk)(
                self.params, jnp.asarray(ids), self.paged_cache,
                jnp.asarray(table_row, jnp.int32), jnp.int32(T),
                jnp.int32(start))
            return int(tok)
        cache = gpt_mod.init_cache(self.cfg, 1, self._dense_S, self.dtype)
        if self.tp_context is not None:
            # carried between chunked-prefill dispatches: keep the dense
            # scratch on the head-sharded layout the tp programs expect
            cache = self.tp_context.shard_dense_cache(cache)
        pos = 0
        logits = None
        while pos < T:
            rem = T - pos
            chunk = (s.prefill_chunk if rem >= s.prefill_chunk
                     else bucket_for(rem, self._chunk_buckets))
            ids = np.zeros((1, chunk), np.int32)
            ids[0, :min(rem, chunk)] = tokens[pos:pos + chunk]
            logits, cache = self._get_prefill(chunk)(
                self.params, jnp.asarray(ids), cache)
            last_idx = min(rem, chunk) - 1
            pos += chunk
        self.paged_cache = self._get_scatter()(
            self.paged_cache, cache, jnp.asarray(table_row, jnp.int32),
            jnp.int32(T), jnp.int32(start))
        return int(jnp.argmax(logits[0, last_idx]))

    def prefill_many(self, items) -> dict:
        """Prefill one admission cycle's requests: short prompts (<= one
        chunk) batch into a single dispatch; longer prompts take the serial
        chunked path. ``items``: [(slot, tokens, table_row)] or
        [(slot, tokens, table_row, start)] (shared-prefix admissions);
        returns {slot: first_token}."""
        s = self.serving
        out = {}
        items = [(it[0], np.asarray(it[1], np.int32), it[2],
                  int(it[3]) if len(it) > 3 else 0) for it in items]
        short = [it for it in items if len(it[1]) <= s.prefill_chunk]
        for slot, t, row, start in items:
            if len(t) > s.prefill_chunk:
                out[slot] = self.prefill(slot, t, row, start)
        if not short:
            return out
        if len(short) == 1:  # no batching win; reuse the fused single path
            slot, t, row, start = short[0]
            out[slot] = self.prefill(slot, t, row, start)
            return out
        chunk = bucket_for(max(len(t) for _, t, _, _ in short),
                           self._chunk_buckets)
        ids = np.zeros((self.num_slots, chunk), np.int32)
        tables = np.zeros((self.num_slots, s.pages_per_seq), np.int32)
        lengths = np.zeros(self.num_slots, np.int32)
        starts = np.zeros(self.num_slots, np.int32)
        for j, (slot, t, row, start) in enumerate(short):
            ids[j, :len(t)] = t
            tables[j] = row
            lengths[j] = len(t)
            starts[j] = start
        toks, self.paged_cache = self._get_prefill_batch(chunk)(
            self.params, jnp.asarray(ids), self.paged_cache,
            jnp.asarray(tables), jnp.asarray(lengths), jnp.asarray(starts))
        toks = np.asarray(toks)
        for j, (slot, _, _, _) in enumerate(short):
            out[slot] = int(toks[j])
        return out

    def decode(self, tokens: np.ndarray, tables: np.ndarray,
               lengths: np.ndarray, active: np.ndarray,
               steps: int = 1) -> np.ndarray:
        """``steps`` fixed-shape decode steps over every slot as one
        dispatch; returns [steps, num_slots] sampled tokens (inactive slots
        write to the reserved sink page and their outputs are ignored)."""
        del active  # the program runs all slots; masking is host-side
        out, self.paged_cache = self._get_decode(steps)(
            self.params, self.paged_cache, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(tables, jnp.int32), jnp.asarray(lengths, jnp.int32))
        return np.asarray(out)

    def verify(self, tokens: np.ndarray, tables: np.ndarray,
               lengths: np.ndarray, active: np.ndarray, eos: np.ndarray,
               budget: np.ndarray):
        """Speculative verification executor call: ``tokens`` [slots, W]
        windows (verified input + drafts), per-slot ``eos`` (-1 = none) and
        remaining-budget vectors. Returns (outputs [slots, W],
        n_accept [slots]); the accepted prefix's KV is already committed."""
        del active  # the program runs all slots; masking rides budget == 0
        W = int(np.asarray(tokens).shape[1])
        outs, n, self.paged_cache = self._get_verify(W)(
            self.params, self.paged_cache, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(tables, jnp.int32), jnp.asarray(lengths, jnp.int32),
            jnp.asarray(eos, jnp.int32), jnp.asarray(budget, jnp.int32))
        return np.asarray(outs), np.asarray(n)

    # ----------------------------------------------- disaggregated handoff
    def export_pages(self, page_ids) -> dict:
        """Serialize the KV held in ``page_ids`` (a request's block-table
        prefix, in table order) for a prefill->decode handoff. Returns a
        payload of raw little-endian buffers per pool tensor — quantized
        pools ship their int8/int4-packed payload plus fp32 per-page scales,
        so an int8 pool serializes ~4x cheaper than fp32 (the EQuARX-style
        cheap wire the disaggregation design rides). The pages themselves
        are NOT freed here: the scheduler keeps ownership until the decode
        side acknowledges (export-before-free)."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        tensors = {}
        for key, arr in self.paged_cache.items():
            # every pool tensor indexes pages on axis 2:
            # pages [L, H, P, ps, Dq], scales [L, H, P]
            sel = np.asarray(arr[:, :, ids])
            tensors[key] = {"dtype": sel.dtype.name,
                            "shape": list(sel.shape),
                            "data": sel.tobytes()}
        payload = {"page_ids": [int(p) for p in np.asarray(page_ids)],
                   "tensors": tensors}
        if self.serving.page_fingerprints:
            # stamp the exact bytes crossing the trust boundary; the
            # importer re-fingerprints and refuses a torn transfer
            from ...resilience.integrity import payload_fingerprints

            payload["fingerprints"] = payload_fingerprints(tensors)
        return payload

    def import_pages(self, page_ids, payload: dict) -> None:
        """Install a handoff payload (``export_pages`` on the prefill side)
        into locally-owned pages. ``page_ids`` are THIS engine's freshly
        claimed pages, in the same table order the exporter used — the page
        numbers themselves need not match across replicas, only the order."""
        src = payload["tensors"]
        if set(src) != set(self.paged_cache):
            raise ValueError(
                f"handoff pool mismatch: payload has {sorted(src)}, engine "
                f"pools are {sorted(self.paged_cache)} (kv_bits must match "
                f"across prefill and decode replicas)")
        stamp = payload.get("fingerprints")
        if stamp:
            # any stamped payload is verified regardless of the local flag:
            # the exporter paid for the stamp precisely so a torn transfer
            # is refused here rather than decoded into garbage tokens
            from ...resilience.integrity import verify_payload_fingerprints

            bad = verify_payload_fingerprints(src, stamp)
            if bad:
                raise ValueError(
                    "handoff payload failed fingerprint verification "
                    f"({bad}) — refusing the transfer")
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        cache = dict(self.paged_cache)
        for key, rec in src.items():
            dt = _np_dtype(rec["dtype"])
            vals = np.frombuffer(rec["data"], dtype=dt).reshape(rec["shape"])
            if list(vals.shape[2:3]) != [len(np.asarray(page_ids))]:
                raise ValueError(
                    f"handoff {key}: payload carries {vals.shape[2]} pages, "
                    f"importer claimed {len(np.asarray(page_ids))}")
            cache[key] = cache[key].at[:, :, ids].set(
                jnp.asarray(vals, cache[key].dtype))
        if self.tp_context is not None:
            # the functional .at[].set above may drop the NamedSharding —
            # pin the pools back onto the tp mesh before the next dispatch
            cache = self.tp_context.shard_cache(cache)
        self.paged_cache = cache

    def fingerprint_pages(self, page_ids) -> list:
        """Fingerprint the CURRENT pool contents of ``page_ids``: one crc
        per page, chained across every pool tensor in sorted-key order so a
        flip in any of k/v (or their quantization scales) changes the page's
        print. This is the scheduler's scan/audit primitive — pulled to host
        once per call, so callers budget the page count."""
        from ...resilience.fingerprint import CHECKSUMS, preferred_checksum

        fn = CHECKSUMS[preferred_checksum()]
        ids = np.asarray(page_ids, np.int32)
        if ids.size == 0:
            return []
        host = {key: np.asarray(arr[:, :, jnp.asarray(ids)])
                for key, arr in sorted(self.paged_cache.items())}
        out = []
        for j in range(ids.size):
            crc = 0
            for key in sorted(host):
                crc = fn(np.ascontiguousarray(host[key][:, :, j]).tobytes(),
                         crc)
            out.append(int(crc))
        return out

    def corrupt_page_bit(self, page: int) -> None:
        """Chaos-only: flip one real bit in ``page``'s content in the first
        pool tensor — the scheduler's ``flip_bit_at`` (domain ``kv_page``)
        injection lands here so SDC detection is exercised against genuine
        pool bytes, not a mocked flag."""
        key = sorted(self.paged_cache)[0]
        arr = self.paged_cache[key]
        host = np.array(arr[:, :, int(page)])  # forced writable host copy
        flat = host.reshape(-1).view(np.uint8)
        flat[flat.size // 2] ^= 0x01
        cache = dict(self.paged_cache)
        cache[key] = arr.at[:, :, int(page)].set(
            jnp.asarray(host, arr.dtype))
        if self.tp_context is not None:
            cache = self.tp_context.shard_cache(cache)
        self.paged_cache = cache

    def warmup(self) -> int:
        """Compile every serving program shape before traffic arrives:
        fused prefill per chunk bucket, the chunked long-prompt path (+
        scatter) when configured, and both decode block sizes. Safe against
        live state — warmup tokens carry all-zero block tables and zero
        lengths, so every write lands on the reserved sink page. Returns the
        number of compiled programs."""
        s = self.serving
        sink_row = np.zeros(s.pages_per_seq, np.int32)
        # per-role program sets: a decode-specialist replica admits page
        # handoffs (import, no prefill programs); a prefill specialist never
        # decodes past the first token. The skipped families stay lazily
        # compilable for failover — they just aren't paid for up front
        # (aot.serving_admission_limit prices the same split).
        if s.role != "decode":
            for chunk in self._chunk_buckets:
                # cap at prefill_chunk: the top bucket can exceed it
                # (non-pow2 prefill_chunk) and a longer probe would take the
                # chunked path, leaving the fused/batch programs for this
                # bucket uncompiled
                t = np.zeros(min(chunk, s.prefill_chunk, s.max_model_len),
                             np.int32)
                self.prefill(0, t, sink_row)
                if self.num_slots >= 2:  # the admission-batch program
                    self.prefill_many([(0, t, sink_row), (1, t, sink_row)])
            if s.max_model_len > s.prefill_chunk:
                # the chunked long-prompt path: full chunks compile ONE
                # program, but the final partial chunk lands on any
                # REACHABLE bucket — compile each (a long prompt's remainder
                # must not pay a mid-traffic compile). Bucket b is reachable
                # when some legal remainder maps to it, even if
                # prefill_chunk + b itself overshoots max_model_len.
                max_rem = s.max_model_len - s.prefill_chunk
                prev = 0
                for b in self._chunk_buckets:
                    if max_rem > prev:
                        n = s.prefill_chunk + min(b, max_rem)
                        self.prefill(0, np.zeros(n, np.int32), sink_row)
                    prev = b
        zeros = np.zeros(self.num_slots, np.int32)
        tables = np.zeros((self.num_slots, s.pages_per_seq), np.int32)
        mask = np.zeros(self.num_slots, bool)
        if s.role != "prefill":
            steps_set = {1}
            k = 1
            while k * 2 <= s.decode_block:  # scheduler's power-of-two blocks
                k *= 2
                steps_set.add(k)
            for steps in sorted(steps_set):
                self.decode(zeros, tables, zeros, mask, steps=steps)
            # every verify window shape in the spec ladder (budget all-zero:
            # nothing commits, every write is masked to nowhere)
            for k in s.spec_k_set:
                self.verify(np.zeros((self.num_slots, k + 1), np.int32),
                            tables, zeros, mask,
                            np.full(self.num_slots, -1, np.int32), zeros)
        if self.tp_context is not None:
            # trace (not execute) the tp decode/verify programs to jaxprs
            # for the serving/tp-collective-order dslint audit
            self.tp_context.capture_programs(self)
        return len(self.compile_log)

    # -------------------------------------------------------------- assembly
    def make_scheduler(self, clock=time.monotonic, recovery_log=None
                       ) -> ContinuousBatchingScheduler:
        """Assemble the scheduler with the config's overload/deadline/fault
        knobs. ``recovery_log`` (a
        :class:`~deepspeed_tpu.resilience.events.RecoveryLog`) receives the
        serving recovery trail; when omitted and a monitor is attached, a
        monitor-only log is created so ``Serving/*`` scalars still flow. A
        watchdog is created (and owned by the scheduler — ``close()`` stops
        it) when either serving phase deadline is armed."""
        s = self.serving
        if recovery_log is None and self.monitor is not None:
            from ...resilience.events import RecoveryLog

            recovery_log = RecoveryLog(monitor=self.monitor, role="serving",
                                       prefix="Serving")
        watchdog = None
        owns = False
        if s.prefill_deadline_s or s.decode_deadline_s:
            from ...resilience.watchdog import HealthWatchdog

            deadlines = {}
            if s.prefill_deadline_s:
                deadlines["serving_prefill"] = float(s.prefill_deadline_s)
            if s.decode_deadline_s:
                deadlines["serving_decode"] = float(s.decode_deadline_s)
                # a speculative verify window is one decode-analog dispatch
                # (k+1 positions, weights read once) — it rides the decode
                # deadline so arming spec never silently disarms the PR 7
                # stall ladder
                deadlines["serving_verify"] = float(s.decode_deadline_s)
            watchdog = HealthWatchdog(
                deadlines, poll_interval=s.watchdog_poll_s,
                recovery_log=recovery_log,
                stacks_dir=s.stacks_dir).start()
            owns = True
        prefix_cache = None
        if s.enable_prefix_cache:
            from .paging import PrefixIndex

            prefix_cache = PrefixIndex(s.page_size)
        drafter = None
        if s.spec_drafter:
            from .speculate import make_drafter

            drafter = make_drafter(self, s)
        tiers, tenants, brownout = s.resolved_tiers()
        sched = ContinuousBatchingScheduler(
            executor=self, num_slots=self.num_slots,
            num_pages=self.num_pages, page_size=s.page_size,
            pages_per_seq=s.pages_per_seq,
            decode_block=s.decode_block,
            max_context=s.max_model_len, clock=clock,
            max_queue=s.max_queue, max_queued_tokens=s.max_queued_tokens,
            shed_policy=s.shed_policy, ttft_deadline_s=s.ttft_deadline_s,
            deadline_s=s.request_deadline_s,
            dispatch_retries=s.dispatch_retries,
            quarantine_after=s.quarantine_after,
            dispatch_failure_budget=s.dispatch_failure_budget,
            recovery_log=recovery_log, watchdog=watchdog,
            prefix_cache=prefix_cache, drafter=drafter, spec_k=s.spec_k,
            spec_adaptive=s.spec_adaptive, role=s.role,
            tiers=tiers, tenants=tenants, brownout=brownout,
            page_fingerprints=s.page_fingerprints,
            pages_scan_per_step=s.pages_scan_per_step)
        sched._owns_watchdog = owns
        self.last_scheduler = sched
        return sched

    def hbm_token_slots(self) -> int:
        """Token capacity of the pool (page 0 excluded) — the "equal HBM
        budget" side of the static-batch A/B."""
        return (self.num_pages - 1) * self.serving.page_size

    def kv_bytes_per_token(self) -> float:
        """HBM bytes one cached token costs in THIS config's pools (payload
        + amortized per-page scales) — the honest equal-HBM-bytes axis of
        the dense-vs-quantized A/B."""
        s = self.serving
        return gpt_mod.paged_kv_bytes_per_token(
            self.cfg, s.kv_bits, s.page_size, self.dtype)
