"""Continuous-batching scheduler: per-decode-step admit / evict / preempt.

The unit of scheduling is a **decode slot**: the decode program is compiled
ONCE for a fixed slot count (the admission limit — ``runtime/aot.py``'s
``find_max_decode_batch`` verdict, see :func:`serving_admission_limit`), and
every step runs all slots whether occupied or not. Requests flow:

    submit -> queue -> [admit: alloc pages, chunked prefill] -> slot
           -> one token per scheduler step -> [finish: free pages, evict]

against the static-batch ``InferenceEngine.generate`` baseline this recycles
a slot the moment its request finishes instead of holding it until the whole
batch drains — at equal HBM (same pool, same slot count) the decode steps
spend no work on finished sequences.

Page growth is on demand: a slot crossing a page boundary allocates one page
mid-flight; when the pool is exhausted the most-recently-admitted other slot
is **preempted** (pages freed, request requeued at the FRONT with its
generated tokens kept — re-admission re-prefills prompt+tokens, the
vLLM-style recompute preemption), so the oldest work always completes.

The scheduler is host-pure and device-free: all device work goes through an
*executor* with two methods (implemented by ``serving.engine.ServingEngine``;
tests drive a fake):

- ``prefill(slot, tokens, table_row) -> first_token`` — run the context,
  write its KV into the slot's pages, return the next-token sample
  (optional ``prefill_many(items) -> {slot: first_token}`` batches one
  admission cycle).
- ``decode(tokens, tables, lengths, active, steps=1) -> [steps, num_slots]``
  — ``steps`` fixed-shape decode steps over every slot as one dispatch
  (a flat ``[num_slots]`` return is accepted only for ``steps == 1``).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import time
from collections import deque
from typing import Any, Deque, List, Optional

import numpy as np

from .paging import PageAllocator, pages_for


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"


_rid = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request plus its lifecycle bookkeeping."""

    prompt: np.ndarray                  # [T] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_time: float = 0.0           # offset into the workload (open loop)
    rid: int = dataclasses.field(default_factory=lambda: next(_rid))

    # lifecycle (filled by the scheduler)
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    t_submit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    preemptions: int = 0

    @property
    def context_len(self) -> int:
        """Tokens whose KV must be live to continue this request."""
        return len(self.prompt) + len(self.tokens)

    @property
    def done(self) -> bool:
        return (len(self.tokens) >= self.max_new_tokens
                or (self.eos_token_id is not None and self.tokens
                    and self.tokens[-1] == self.eos_token_id))


class ContinuousBatchingScheduler:
    def __init__(self, executor: Any, num_slots: int, num_pages: int,
                 page_size: int, pages_per_seq: int, decode_block: int = 1,
                 max_context: Optional[int] = None, clock=time.monotonic):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.executor = executor
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.pages_per_seq = int(pages_per_seq)
        if not (1 <= decode_block <= self.page_size):
            raise ValueError(f"decode_block {decode_block} outside "
                             f"[1, page_size]")
        self.decode_block = int(decode_block)
        # the engine's model-length bound can sit BELOW the page capacity by
        # a partial page — admission must honor the tighter of the two
        self.max_context = int(max_context if max_context is not None
                               else pages_per_seq * page_size)
        self.allocator = PageAllocator(num_pages)
        self.clock = clock
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * self.num_slots
        self._slot_pages: List[List[int]] = [[] for _ in range(self.num_slots)]
        self._admit_seq: List[int] = [0] * self.num_slots  # admission order
        self._admissions = 0
        self.tables = np.zeros((self.num_slots, self.pages_per_seq), np.int32)
        self.lengths = np.zeros(self.num_slots, np.int32)
        self.next_input = np.zeros(self.num_slots, np.int32)
        self.finished: List[Request] = []
        self.steps = 0

    # ------------------------------------------------------------ bookkeeping
    @property
    def active_slots(self) -> List[int]:
        return [i for i, r in enumerate(self.slots) if r is not None]

    @property
    def idle(self) -> bool:
        return not self.queue and not self.active_slots

    def submit(self, req: Request) -> None:
        worst = len(req.prompt) + req.max_new_tokens
        pool = self.allocator.num_pages - 1  # page 0 reserved
        if (worst > self.max_context
                or pages_for(worst, self.page_size) > self.pages_per_seq
                or pages_for(worst, self.page_size) > pool):
            # the pool bound matters too: a request needing more pages than
            # EXIST can never admit (queue head-of-line spins forever) and,
            # admitted mid-way, would self-preempt in an infinite
            # recompute loop once it outgrows the pool
            raise ValueError(
                f"request {req.rid}: prompt+max_new={worst} tokens exceeds "
                f"the serving bound (max_context={self.max_context}, "
                f"pages_per_seq={self.pages_per_seq} x page_size="
                f"{self.page_size}, pool={pool} pages) — reject at the "
                f"front door, not mid-decode")
        req.state = RequestState.QUEUED
        if req.t_submit is None:
            req.t_submit = self.clock()
        self.queue.append(req)

    def _release(self, slot: int) -> None:
        self.allocator.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self.tables[slot] = 0
        self.lengths[slot] = 0
        self.next_input[slot] = 0
        self.slots[slot] = None

    def _finish(self, slot: int) -> None:
        req = self.slots[slot]
        req.state = RequestState.FINISHED
        req.t_done = self.clock()
        self.finished.append(req)
        self._release(slot)

    def _preempt(self, slot: int) -> None:
        """Recompute-style preemption: pages freed, generated tokens KEPT;
        re-admission prefills prompt+tokens (greedy decode reproduces the
        exact state, no quality loss — only recomputed FLOPs)."""
        req = self.slots[slot]
        req.preemptions += 1
        req.state = RequestState.QUEUED
        self._release(slot)
        self.queue.appendleft(req)

    # ------------------------------------------------------------ admission
    def _admit(self) -> int:
        # phase 1: claim slots + pages for everything that fits this cycle
        batch = []  # (slot, context tokens)
        for slot in range(self.num_slots):
            if self.slots[slot] is not None or not self.queue:
                continue
            req = self.queue[0]
            ctx = req.context_len
            # +1: the first decode step appends its token's KV at position
            # ctx, which may open a fresh page
            need = pages_for(ctx + 1, self.page_size)
            pages = self.allocator.alloc(need)
            if pages is None:
                break  # head-of-line blocking keeps FIFO order under pressure
            self.queue.popleft()
            self._slot_pages[slot] = pages
            self.tables[slot] = 0
            self.tables[slot, :len(pages)] = pages
            tokens = np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(req.tokens, np.int32)]) if req.tokens else \
                np.asarray(req.prompt, np.int32)
            self.lengths[slot] = ctx
            self.slots[slot] = req
            self._admissions += 1
            self._admit_seq[slot] = self._admissions
            req.state = RequestState.RUNNING
            batch.append((slot, tokens))
        if not batch:
            return 0
        # phase 2: prefill the whole admission cycle — batched when the
        # executor supports it (one [num_slots, chunk] dispatch instead of
        # one per request)
        if hasattr(self.executor, "prefill_many"):
            results = self.executor.prefill_many(
                [(slot, toks, self.tables[slot]) for slot, toks in batch])
        else:
            results = {slot: int(self.executor.prefill(
                slot, toks, self.tables[slot])) for slot, toks in batch}
        for slot, _ in batch:
            req = self.slots[slot]
            first = int(results[slot])
            self.next_input[slot] = first
            # prefill's sample is the next NEW token whether this is a fresh
            # admission (prompt only) or a post-preemption re-prefill
            # (prompt + kept tokens): append it either way
            req.tokens.append(first)
            if req.t_first_token is None:
                req.t_first_token = self.clock()
            if req.done:
                self._finish(slot)
        return len(batch)

    def _ensure_page(self, slot: int, horizon: int = 1) -> bool:
        """Make sure pages exist for write positions ``lengths[slot]`` up to
        ``lengths[slot] + horizon - 1`` (a decode block appends ``horizon``
        tokens between scheduling points)."""
        last_pi = (int(self.lengths[slot]) + horizon - 1) // self.page_size
        if last_pi >= self.pages_per_seq:
            raise RuntimeError(
                f"slot {slot} outgrew pages_per_seq — admission bound broken")
        for pi in range(last_pi + 1):
            if self.tables[slot, pi] != 0:
                continue
            page = self.allocator.alloc(1)
            if page is None:
                return False
            self._slot_pages[slot].append(page[0])
            self.tables[slot, pi] = page[0]
        return True

    # ------------------------------------------------------------ one step
    def _block_size(self) -> int:
        """Steps safely runnable as one compiled block: no slot may finish
        early (wasted work), no eos can fire unseen (eos requests decode
        step-by-step), and page growth for the whole horizon must be
        coverable up front. Rounded down to a power of two so the engine
        compiles at most log2(decode_block)+1 block shapes."""
        if self.decode_block <= 1:
            return 1
        reqs = [self.slots[s] for s in self.active_slots]
        if any(r.eos_token_id is not None for r in reqs):
            return 1
        remaining = min(r.max_new_tokens - len(r.tokens) for r in reqs)
        k = 1
        while k * 2 <= min(remaining, self.decode_block):
            k *= 2
        return k

    def step(self) -> int:
        """Admit what fits, then run one decode step (or one safe decode
        BLOCK) over the slot array. Returns tokens produced."""
        self._admit()
        if not self.active_slots:
            return 0
        block = self._block_size()
        # page growth for the block horizon, preempting newest-first under
        # pool pressure
        for slot in list(self.active_slots):
            if self.slots[slot] is None:
                continue
            while not self._ensure_page(slot, horizon=block):
                # newest-admitted work yields FIRST — including the growing
                # slot itself, so an old request is never evicted by a
                # younger grower (oldest work always completes)
                victim = max(self.active_slots,
                             key=lambda s: self._admit_seq[s])
                self._preempt(victim)
                if victim == slot:
                    break
        active = self.active_slots
        if not active:
            return 0
        block = min(block, self._block_size())  # preemption may shrink it
        mask = np.zeros(self.num_slots, bool)
        mask[active] = True
        out = np.asarray(self.executor.decode(
            self.next_input.copy(), self.tables.copy(),
            self.lengths.copy(), mask, steps=block))
        if out.ndim == 1:  # simple executors may return a flat SINGLE step
            if block != 1:
                raise ValueError(
                    f"executor returned a flat token vector for a "
                    f"{block}-step decode block; multi-step decode must "
                    f"return [steps, num_slots]")
            out = out[None]
        self.steps += 1
        produced = 0
        for k in range(block):
            for slot in active:
                req = self.slots[slot]
                if req is None or req.state is not RequestState.RUNNING:
                    continue
                self.lengths[slot] += 1  # input token's KV now cached
                tok = int(out[k, slot])
                req.tokens.append(tok)
                self.next_input[slot] = tok
                produced += 1
                if req.done:
                    self._finish(slot)
        return produced

    def run_to_completion(self, max_steps: int = 100_000) -> None:
        """Drain queue + slots (closed-loop; the open-loop driver lives in
        ``serving.bench``)."""
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"scheduler did not drain in {max_steps} steps")
