"""Continuous-batching scheduler: per-decode-step admit / evict / preempt.

The unit of scheduling is a **decode slot**: the decode program is compiled
ONCE for a fixed slot count (the admission limit — ``runtime/aot.py``'s
``find_max_decode_batch`` verdict, see :func:`serving_admission_limit`), and
every step runs all slots whether occupied or not. Requests flow:

    submit -> queue -> [admit: alloc pages, chunked prefill] -> slot
           -> one token per scheduler step -> [finish: free pages, evict]

against the static-batch ``InferenceEngine.generate`` baseline this recycles
a slot the moment its request finishes instead of holding it until the whole
batch drains — at equal HBM (same pool, same slot count) the decode steps
spend no work on finished sequences.

Page growth is on demand: a slot crossing a page boundary allocates one page
mid-flight; when the pool is exhausted the most-recently-admitted other slot
is **preempted** (pages freed, request requeued at the FRONT with its
generated tokens kept — re-admission re-prefills prompt+tokens, the
vLLM-style recompute preemption), so the oldest work always completes.

The scheduler is host-pure and device-free: all device work goes through an
*executor* with two methods (implemented by ``serving.engine.ServingEngine``;
tests drive a fake):

- ``prefill(slot, tokens, table_row) -> first_token`` — run the context,
  write its KV into the slot's pages, return the next-token sample
  (optional ``prefill_many(items) -> {slot: first_token}`` batches one
  admission cycle).
- ``decode(tokens, tables, lengths, active, steps=1) -> [steps, num_slots]``
  — ``steps`` fixed-shape decode steps over every slot as one dispatch
  (a flat ``[num_slots]`` return is accepted only for ``steps == 1``).

Production hardening (docs/SERVING.md "Overload & failure"):

- **overload control** — ``submit`` returns a typed
  :class:`AdmissionVerdict`; past ``max_queue`` / ``max_queued_tokens`` the
  configured shed policy rejects the newest request (default) or sheds the
  largest queued one to make room. No unbounded host-RAM queue, no
  accepting work the pool can never serve in time.
- **deadlines** — per-request TTFT and end-to-end deadlines (defaults from
  the scheduler) are checked every step: expired requests are evicted,
  their pages freed, and a ``deadline_miss`` recovery event recorded.
- **dispatch fault recovery** — every executor call is bracketed by the
  resilience watchdog's serving phases and the chaos plan's dispatch
  injectors, retried on the shared ``backoff_delay`` curve, and — when a
  whole episode fails — healed by preempt-and-requeue (kept-token
  semantics) with the offending decode block shape quarantined after K
  failures. Every recovery path ends in a :meth:`audit` pass: page
  conservation is an enforced invariant, not a hope.

Copy-on-write prefix caching (docs/SERVING.md "KV quantization & prefix
caching"): with a :class:`~.paging.PrefixIndex` attached, admission SHAREs
the physical pages of the longest indexed page-aligned prompt prefix
(allocator refcounts) instead of allocating them, the prefill scatter
starts past the borrowed pages, and a successful prefill registers the
request's own full prompt pages for later arrivals. :meth:`audit` then
additionally proves every refcount matches its slot references and that no
shared page can ever be written (it lies wholly below every referencing
slot's write frontier).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import time
from collections import deque
from contextlib import nullcontext
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import numpy as np

from ...resilience.chaos import (sdc_flip_fault, serving_dispatch_fault,
                                 serving_tenant_flood)
from ...resilience.retry import backoff_delay
from .paging import (PageAllocator, PrefixIndex, pages_for,
                     prefix_chain_hashes)
from .speculate import AdaptiveSpecK, spec_k_ladder
from .tenancy import (BROWNOUT_STAGES, DEFAULT_TIER, BrownoutConfig,
                      BrownoutController, StartTimeFairQueue, TenantConfig,
                      TierConfig, TokenBucket, sacrifice_key, tier_rank)


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"   # shed at/after submit (overload or unservable)
    EXPIRED = "expired"     # missed its deadline; evicted, pages freed
    HANDOFF = "handoff"     # prefilled on a prefill-role scheduler; pages
    #                         staged for export to a decode-role replica


class ServingFaultError(RuntimeError):
    """The executor failed ``dispatch_failure_budget`` consecutive dispatch
    episodes (each already retried) — the serving process is sick beyond
    what preempt-and-requeue can heal; the supervisor should recycle it."""


class _DispatchFailure(RuntimeError):
    """Internal: one dispatch episode (all retry attempts) failed."""

    def __init__(self, kind: str, attempts: int, last: BaseException):
        super().__init__(f"{kind} dispatch failed after {attempts} attempts: "
                         f"{last!r}")
        self.kind = kind
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass(frozen=True)
class AdmissionVerdict:
    """The typed result of :meth:`ContinuousBatchingScheduler.submit`.

    ``reason``: ``admitted`` | ``unservable`` (prompt+max_new can never fit
    the serving bound — a caller bug, not load) | ``queue_full`` |
    ``token_backlog`` (the admission queue's token-budget backpressure
    estimate is exhausted) | ``draining`` (the scheduler is in a graceful
    drain — finishing accepted work, admitting nothing new) |
    ``rate_limited`` (the tenant's token bucket is empty — its contracted
    rate, not system load) | ``brownout`` (the degradation ladder has
    closed this tier's admission; docs/SERVING.md "Multi-tenancy & SLO
    tiers"). ``shed_rid``: under the ``reject_largest`` policy, the rid of
    the queued request evicted to make room."""

    admitted: bool
    reason: str = "admitted"
    detail: str = ""
    shed_rid: Optional[int] = None

    def __bool__(self) -> bool:
        return self.admitted


_rid = itertools.count()


@dataclasses.dataclass(eq=False)
class Request:
    """One generation request plus its lifecycle bookkeeping.

    ``eq=False``: requests compare by identity. Field equality was never
    meaningful (the ndarray prompt makes generated ``__eq__`` raise on any
    same-length comparison) and the queue's ``remove()`` must match THE
    request object, not a lookalike."""

    prompt: np.ndarray                  # [T] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_time: float = 0.0           # offset into the workload (open loop)
    # deadlines, seconds from t_submit (None -> the scheduler's defaults):
    # TTFT is enforced while queued (first token lands at admission), the
    # end-to-end deadline for the whole lifetime
    ttft_deadline_s: Optional[float] = None
    deadline_s: Optional[float] = None
    # multi-turn affinity key (inference/fleet): requests sharing a
    # session_id are routed to the same replica so its prefix-cache pages
    # stay hot; a lone scheduler ignores it
    session_id: Optional[str] = None
    # disaggregated prefill/decode: a request arriving WITH a KV payload
    # (an ``export_pages`` product from a prefill-role scheduler) admits by
    # IMPORTING the pages instead of prefilling — cleared after the import,
    # so a later preemption falls back to the normal kept-token re-prefill
    kv_payload: Optional[dict] = None
    # multi-tenancy (docs/SERVING.md "Multi-tenancy & SLO tiers"): plain
    # fields so they ride request_spec / the subprocess protocol verbatim.
    # tier is resolved at submit (request override > tenant config >
    # DEFAULT_TIER) and stamped back here so every downstream event,
    # handoff, and ledger row carries it
    tenant_id: Optional[str] = None
    tier: Optional[str] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_rid))

    # lifecycle (filled by the scheduler)
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    t_submit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    preemptions: int = 0
    reject_reason: Optional[str] = None  # set when REJECTED/EXPIRED
    # per-request speculation ledger (draft positions offered to the
    # verifier / confirmed by it — the request-level accept-rate row)
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def context_len(self) -> int:
        """Tokens whose KV must be live to continue this request."""
        return len(self.prompt) + len(self.tokens)

    @property
    def done(self) -> bool:
        return (len(self.tokens) >= self.max_new_tokens
                or (self.eos_token_id is not None and self.tokens
                    and self.tokens[-1] == self.eos_token_id))

    @property
    def work_tokens(self) -> int:
        """Remaining worst-case token work: what the backpressure estimate
        charges this request against ``max_queued_tokens`` (prompt KV to
        prefill + tokens still to decode)."""
        return len(self.prompt) + self.max_new_tokens - len(self.tokens)


SHED_POLICIES = ("reject_newest", "reject_largest")


class ContinuousBatchingScheduler:
    def __init__(self, executor: Any, num_slots: int, num_pages: int,
                 page_size: int, pages_per_seq: int, decode_block: int = 1,
                 max_context: Optional[int] = None, clock=time.monotonic,
                 max_queue: Optional[int] = None,
                 max_queued_tokens: Optional[int] = None,
                 shed_policy: str = "reject_newest",
                 ttft_deadline_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 dispatch_retries: int = 2,
                 retry_base_delay: float = 0.02,
                 retry_max_delay: float = 0.25,
                 quarantine_after: int = 2,
                 dispatch_failure_budget: int = 8,
                 recovery_log: Any = None, watchdog: Any = None,
                 prefix_cache: Optional[PrefixIndex] = None,
                 drafter: Any = None, spec_k: int = 4,
                 spec_adaptive: bool = True, role: str = "both",
                 tiers: Optional[Dict[str, TierConfig]] = None,
                 tenants: Optional[Dict[str, TenantConfig]] = None,
                 brownout: Optional[BrownoutConfig] = None,
                 latency_preempt_budget: int = 2,
                 page_fingerprints: bool = False,
                 pages_scan_per_step: int = 1):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if shed_policy not in SHED_POLICIES:
            raise ValueError(f"shed_policy {shed_policy!r} not in "
                             f"{SHED_POLICIES}")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, got "
                             f"{role!r}")
        self.executor = executor
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.pages_per_seq = int(pages_per_seq)
        if not (1 <= decode_block <= self.page_size):
            raise ValueError(f"decode_block {decode_block} outside "
                             f"[1, page_size]")
        self.decode_block = int(decode_block)
        # the engine's model-length bound can sit BELOW the page capacity by
        # a partial page — admission must honor the tighter of the two
        self.max_context = int(max_context if max_context is not None
                               else pages_per_seq * page_size)
        self.allocator = PageAllocator(num_pages)
        self.clock = clock
        # overload control / deadlines
        self.max_queue = None if max_queue is None else int(max_queue)
        self.max_queued_tokens = (None if max_queued_tokens is None
                                  else int(max_queued_tokens))
        self.shed_policy = shed_policy
        self.ttft_deadline_s = ttft_deadline_s
        self.deadline_s = deadline_s
        # dispatch fault recovery
        self.dispatch_retries = int(dispatch_retries)
        self.retry_base_delay = float(retry_base_delay)
        self.retry_max_delay = float(retry_max_delay)
        self.quarantine_after = int(quarantine_after)
        self.dispatch_failure_budget = int(dispatch_failure_budget)
        self.recovery_log = recovery_log
        self.watchdog = watchdog
        self._owns_watchdog = False  # set by ServingEngine.make_scheduler
        # shared-prefix page reuse (copy-on-write; None = off): admission
        # looks the prompt's page-aligned prefix up in the index and SHAREs
        # those physical pages instead of allocating fresh ones
        self.prefix_cache = prefix_cache
        # silent-corruption defense for immutable KV (docs/RESILIENCE.md
        # "Data integrity"): pages behind the write frontier are stamped
        # with a content fingerprint when they become shareable (prefix
        # registration, handoff staging) and re-verified at every trust
        # boundary (share-time claim, background scan, recovery audit). A
        # mismatch evicts the page from the prefix index and unwinds
        # borrowers to a clean re-prefill — never a blind retry.
        self.page_fingerprints = bool(page_fingerprints)
        self.pages_scan_per_step = max(0, int(pages_scan_per_step))
        self._page_fp: Dict[int, int] = {}
        self._page_scan_rr = 0  # round-robin cursor over stamped pages
        # cumulative page accounting: logical = pages every admission asked
        # for, physical = pages actually allocated, shared = pages served
        # from the prefix index — physical/logical is the bench row's
        # page-reuse ratio
        self.page_stats: Dict[str, int] = {
            "logical": 0, "physical": 0, "shared": 0}
        # multi-tenancy (docs/SERVING.md "Multi-tenancy & SLO tiers"):
        # tiers=None keeps the scheduler byte-for-byte FIFO; with a tier
        # table armed the queue is ordered by start-time-fair-queueing
        # virtual time (per-tenant flows weighted by tier), admission
        # partitions are per tier, and the brownout ladder degrades batch
        # before standard before interactive under sustained pressure
        self.tiers = dict(tiers) if tiers else None
        self.tenants: Dict[str, TenantConfig] = dict(tenants) if tenants \
            else {}
        self._wfq = StartTimeFairQueue() if self.tiers else None
        self._buckets: Dict[str, TokenBucket] = {}
        self.brownout = (BrownoutController(brownout or BrownoutConfig())
                         if self.tiers else None)
        self.brownout_stage = 0
        # how many times one batch request may be displaced by a queued
        # interactive request before it becomes preemption-immune (0
        # disables latency preemption; pool-pressure preemption is never
        # budgeted — it is a capacity fact, not a policy choice)
        self.latency_preempt_budget = int(latency_preempt_budget)
        if self.tiers is not None:
            total_reserved = sum(t.reserved_slots for t in
                                 self.tiers.values())
            if total_reserved >= self.num_slots:
                raise ValueError(
                    f"tier slot reservations ({total_reserved}) must leave "
                    f"at least one unreserved slot of {self.num_slots}")
        # distinct tenant ids observed at submit (tiered or not) — the
        # evidence the serving/untiered-multi-tenant dslint rule reads
        self.tenants_seen: Set[str] = set()
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * self.num_slots
        self._slot_pages: List[List[int]] = [[] for _ in range(self.num_slots)]
        # leading pages of each slot that are BORROWED (shared prefix) —
        # the audit's no-write-on-shared invariant is anchored here
        self._slot_shared: List[int] = [0] * self.num_slots
        self._admit_seq: List[int] = [0] * self.num_slots  # admission order
        self._admissions = 0
        self.tables = np.zeros((self.num_slots, self.pages_per_seq), np.int32)
        self.lengths = np.zeros(self.num_slots, np.int32)
        self.next_input = np.zeros(self.num_slots, np.int32)
        self.finished: List[Request] = []
        self.shed: List[Request] = []      # REJECTED (submit-time or policy)
        self.expired: List[Request] = []   # EXPIRED (deadline misses)
        self.counters: Dict[str, int] = {}
        self.steps = 0
        self._draining = False
        self._dispatch_count = 0           # chaos injection index
        # disaggregated prefill/decode (docs/SERVING.md "Tensor parallel &
        # disaggregation"): a "prefill" scheduler stops each request after
        # its first token and STAGES the slot for handoff — the pages stay
        # owned (export-before-free) until the decode side acknowledges via
        # complete_handoff(). A "decode" scheduler admits kv_payload
        # requests by importing pages instead of prefilling.
        self.role = role
        self._handoffs: Dict[int, dict] = {}      # rid -> staged entry
        self._handoff_slots: Set[int] = set()
        self.handed_off: List[Request] = []       # completed exports
        # failed dispatch EPISODES in a row, per kind: a healthy prefill
        # path must not mask a dead decode path (or vice versa) — the
        # admit/fail/requeue cycle would spin forever against a shared
        # counter that every successful prefill resets
        self._consecutive_failures: Dict[str, int] = {}
        self._block_failures: Dict[int, int] = {}
        self._quarantined_blocks: Set[int] = set()
        # speculative decoding (docs/SERVING.md "Speculative decoding"):
        # a drafter proposes up to k tokens per slot, ONE verify dispatch
        # scores k+1 positions, longest-prefix greedy acceptance commits
        # only the confirmed prefix — rejected suffixes were never written
        self.drafter = drafter
        self._spec_ctl = (AdaptiveSpecK(spec_k_ladder(spec_k),
                                        adaptive=spec_adaptive)
                          if drafter is not None else None)
        self.spec_stats: Dict[str, Any] = {
            "drafter": getattr(drafter, "kind", None),
            "windows": 0,           # verify dispatches
            "drafted": 0,           # draft positions offered (k x slots)
            "accepted": 0,          # draft positions confirmed
            "committed_tokens": 0,  # tokens produced by verify windows
            "full_accept_windows": 0,   # slot-windows: every real draft hit
            "full_reject_windows": 0,   # slot-windows: real drafts, none hit
            "fallback_steps": 0,    # steps with no drafts -> plain decode
        }

    # ------------------------------------------------------------ bookkeeping
    @property
    def active_slots(self) -> List[int]:
        """Slots actively DECODING — a staged handoff still occupies its
        slot (pages owned until the decode side acks) but never decodes,
        never expires as "running", and is never a preemption victim."""
        return [i for i, r in enumerate(self.slots)
                if r is not None and r.state is RequestState.RUNNING]

    @property
    def idle(self) -> bool:
        return (not self.queue and not self.active_slots
                and not self._handoffs)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        """A drain was requested and every accepted request has since left
        the system (finished, expired, or shed by policy) — the point at
        which ``close()`` loses no work."""
        return self._draining and self.idle

    def drain(self) -> None:
        """Graceful, idempotent drain: stop admitting NEW submissions
        (``submit`` returns a typed ``draining`` rejection) while queued and
        running requests keep stepping to completion. The autoscaler's
        scale-down path is ``drain()`` -> ``step()`` until :attr:`drained`
        -> ``close()`` — accepted work is never dropped, where an abrupt
        ``close()`` would strand every in-flight request."""
        if not self._draining:
            self._draining = True
            self._record("drain_started", queued=len(self.queue),
                         active=len(self.active_slots))

    @property
    def queued_tokens(self) -> int:
        """The admission queue's token-backpressure estimate: worst-case
        tokens of work (prompt KV + remaining generation) the queue already
        holds. What ``max_queued_tokens`` bounds."""
        return sum(r.work_tokens for r in self.queue)

    def _record(self, event: str, value: float = 1.0, **fields: Any) -> None:
        self.counters[event] = self.counters.get(event, 0) + 1
        if self.recovery_log is not None:
            try:
                self.recovery_log.record(event, value=value, step=self.steps,
                                         **fields)
            except Exception:  # event export must never fail serving
                pass

    # ------------------------------------------------------------- tenancy
    def _tenant_fields(self, req: Request) -> Dict[str, Any]:
        """Per-tenant attribution stamped onto recovery events: absent for
        untenanted traffic, so the pre-tier event schema is unchanged."""
        f: Dict[str, Any] = {}
        if req.tenant_id is not None:
            f["tenant_id"] = req.tenant_id
        if req.tier is not None:
            f["tier"] = req.tier
        return f

    def _resolve_tier(self, req: Request) -> Optional[str]:
        """Effective tier of a submission (request override > tenant config
        > DEFAULT_TIER), stamped back onto the request. None when untiered
        (the request's tier field is left as-is for the ledger)."""
        if req.tenant_id:
            self.tenants_seen.add(req.tenant_id)
        if self.tiers is None:
            return None
        tier = req.tier
        if tier is None and req.tenant_id in self.tenants:
            tier = self.tenants[req.tenant_id].tier
        if tier not in self.tiers:
            tier = DEFAULT_TIER if DEFAULT_TIER in self.tiers \
                else min(self.tiers, key=tier_rank)
        req.tier = tier
        return tier

    def _rate_limit_ok(self, req: Request, tcfg: TierConfig) -> bool:
        """Per-tenant token bucket (work tokens/s): tenant override first,
        tier default second, unlimited when neither sets a rate."""
        if req.tenant_id is None:
            return True
        ten = self.tenants.get(req.tenant_id)
        rate = (ten.rate_tokens_per_s if ten is not None
                and ten.rate_tokens_per_s is not None
                else tcfg.rate_tokens_per_s)
        if rate is None:
            return True
        bucket = self._buckets.get(req.tenant_id)
        if bucket is None:
            burst = (ten.rate_burst_tokens if ten is not None
                     and ten.rate_burst_tokens is not None
                     else tcfg.rate_burst_tokens)
            bucket = TokenBucket(rate, burst)
            self._buckets[req.tenant_id] = bucket
        return bucket.try_take(req.work_tokens, self.clock())

    def _victim_key(self, slot: int) -> tuple:
        """Preemption-victim ordering (``max()`` wins): untiered, pure
        newest-first; tiered, batch slots die before interactive ones,
        newest-first within a tier — the growing-slot rule is preserved
        because the grower itself can still win."""
        if self.tiers is None:
            return (0, self._admit_seq[slot])
        return sacrifice_key(self.slots[slot].tier, self._admit_seq[slot])

    def _mark_shed(self, req: Request, reason: str, detail: str = "") -> None:
        req.state = RequestState.REJECTED
        req.reject_reason = reason
        self.shed.append(req)
        if (self.brownout is not None
                and reason in ("queue_full", "token_backlog",
                               "shed_for_smaller")):
            # only ORGANIC pressure feeds the ladder: counting its own
            # brownout sheds (or rate-limit/drain rejections) as pressure
            # would latch the ladder at its deepest stage forever
            self.brownout.observe("shed", self.clock())
        self._record("request_shed", rid=req.rid, reason=reason,
                     work_tokens=req.work_tokens, detail=detail[:200],
                     **self._tenant_fields(req))

    def submit(self, req: Request) -> AdmissionVerdict:
        """Admission control. Returns a typed verdict — the caller sees WHY
        a request was turned away (unservable vs overload) instead of a
        silently growing queue. A rejected request is marked
        ``RequestState.REJECTED`` and never enters the queue."""
        tier = self._resolve_tier(req)
        if self.brownout is not None:
            self.brownout.observe("submit", self.clock())
        if self._draining:
            detail = (f"request {req.rid} rejected: scheduler is draining "
                      f"({len(self.queue)} queued + "
                      f"{len(self.active_slots)} running to finish)")
            self._mark_shed(req, "draining", detail)
            return AdmissionVerdict(False, "draining", detail)
        worst = len(req.prompt) + req.max_new_tokens
        pool = self.allocator.num_pages - 1  # page 0 reserved
        if (worst > self.max_context
                or pages_for(worst, self.page_size) > self.pages_per_seq
                or pages_for(worst, self.page_size) > pool):
            # the pool bound matters too: a request needing more pages than
            # EXIST can never admit (queue head-of-line spins forever) and,
            # admitted mid-way, would self-preempt in an infinite
            # recompute loop once it outgrows the pool
            detail = (
                f"request {req.rid}: prompt+max_new={worst} tokens exceeds "
                f"the serving bound (max_context={self.max_context}, "
                f"pages_per_seq={self.pages_per_seq} x page_size="
                f"{self.page_size}, pool={pool} pages) — reject at the "
                f"front door, not mid-decode")
            self._mark_shed(req, "unservable", detail)
            return AdmissionVerdict(False, "unservable", detail)
        tcfg = self.tiers[tier] if tier is not None else None
        if tcfg is not None:
            # degradation ladder: from shed_batch onward, new batch-tier
            # work is turned away at the front door (reversible — the
            # ladder steps back down when pressure clears)
            if self.brownout_stage >= 1 and tier == "batch":
                detail = (f"request {req.rid} rejected: brownout stage "
                          f"{BROWNOUT_STAGES[self.brownout_stage]!r} sheds "
                          f"batch-tier admissions")
                self._mark_shed(req, "brownout", detail)
                return AdmissionVerdict(False, "brownout", detail)
            if not self._rate_limit_ok(req, tcfg):
                detail = (f"request {req.rid} rejected: tenant "
                          f"{req.tenant_id!r} token bucket empty "
                          f"({req.work_tokens} work tokens requested)")
                self._mark_shed(req, "rate_limited", detail)
                return AdmissionVerdict(False, "rate_limited", detail)
        # overload control: queue-depth cap, then the token-budget estimate
        # (per-tier partitions when a tier table is armed)
        verdict = self._admission_control(req)
        if not verdict.admitted:
            return verdict
        if req.ttft_deadline_s is None:
            req.ttft_deadline_s = (tcfg.ttft_deadline_s
                                   if tcfg is not None
                                   and tcfg.ttft_deadline_s is not None
                                   else self.ttft_deadline_s)
        if req.deadline_s is None:
            req.deadline_s = (tcfg.deadline_s
                              if tcfg is not None
                              and tcfg.deadline_s is not None
                              else self.deadline_s)
        req.state = RequestState.QUEUED
        if req.t_submit is None:
            req.t_submit = self.clock()
        if self._wfq is not None:
            # SFQ virtual-time tags: per-tenant flows, tier-weighted —
            # a tenant's backlog chains behind itself, never behind
            # another tenant's
            req._wfq_start, req._wfq_finish = self._wfq.stamp(
                req.tenant_id or "_anon", tcfg.weight, req.work_tokens)
        self.queue.append(req)
        return verdict

    def _admission_control(self, req: Request) -> AdmissionVerdict:
        # untiered: one global partition (the whole queue, the global
        # knobs). Tiered: the request competes only against its OWN tier's
        # queued work, bounded by the tier's knobs (global fallback) — a
        # batch flood exhausts the batch partition and draws token_backlog
        # verdicts while interactive admission stays open.
        tcfg = (self.tiers.get(req.tier)
                if self.tiers is not None and req.tier is not None else None)
        if tcfg is None:
            pool = list(self.queue)
            max_q, max_t = self.max_queue, self.max_queued_tokens
        else:
            pool = [r for r in self.queue
                    if (r.tier or DEFAULT_TIER) == req.tier]
            max_q = (tcfg.max_queue if tcfg.max_queue is not None
                     else self.max_queue)
            max_t = (tcfg.max_queued_tokens
                     if tcfg.max_queued_tokens is not None
                     else self.max_queued_tokens)

        def over(queued: List[Request]) -> bool:
            depth = max_q is not None and len(queued) >= max_q
            tokens = (max_t is not None
                      and sum(r.work_tokens for r in queued)
                      + req.work_tokens > max_t)
            return depth or tokens

        if not over(pool):
            return AdmissionVerdict(True)
        if self.shed_policy == "reject_largest":
            # plan the shed set FIRST: the largest queued request(s) — the
            # cheapest goodput to sacrifice per freed token — each strictly
            # larger than the incoming one (shedding down trades goodput
            # away). Victims are only actually sacrificed if the incoming
            # request then fits; otherwise nobody dies for a rejection.
            # Tiered, victims come from the request's own partition only.
            sim = list(pool)
            victims: List[Request] = []
            while sim and over(sim):
                v = max(sim, key=lambda r: r.work_tokens)
                if v.work_tokens <= req.work_tokens:
                    break
                sim.remove(v)
                victims.append(v)
            if not over(sim):
                for v in victims:
                    self.queue.remove(v)
                    self._mark_shed(v, "shed_for_smaller",
                                    f"shed for request {req.rid}")
                return AdmissionVerdict(
                    True, shed_rid=victims[-1].rid if victims else None)
        over_depth = max_q is not None and len(pool) >= max_q
        reason = "queue_full" if over_depth else "token_backlog"
        detail = (
            f"request {req.rid} rejected ({reason}): "
            + (f"tier {req.tier!r} " if tcfg is not None else "")
            + f"queue depth {len(pool)}"
            + (f"/{max_q}" if max_q is not None else "")
            + f", queued work {sum(r.work_tokens for r in pool)} tokens"
            + (f"/{max_t}" if max_t is not None else ""))
        self._mark_shed(req, reason, detail)
        return AdmissionVerdict(False, reason, detail)

    def _release(self, slot: int) -> None:
        if self.drafter is not None:
            try:
                self.drafter.release(slot)
            except Exception:  # drafter state is advisory, never fatal
                pass
        released = self.allocator.free(self._slot_pages[slot])
        if self.prefix_cache is not None and released:
            # a page whose LAST reference died is about to be recycled — it
            # must never serve another request's prefix lookup
            self.prefix_cache.forget(released)
        for p in released:
            # recycled page: its old content stamp is meaningless
            self._page_fp.pop(p, None)
        self._slot_pages[slot] = []
        self._slot_shared[slot] = 0
        self.tables[slot] = 0
        self.lengths[slot] = 0
        self.next_input[slot] = 0
        self.slots[slot] = None

    def _finish(self, slot: int) -> None:
        req = self.slots[slot]
        req.state = RequestState.FINISHED
        req.t_done = self.clock()
        self.finished.append(req)
        # the per-tenant goodput row (value = tokens delivered): with the
        # shed/miss/preemption events this completes the Serving/* ledger's
        # by-tenant accounting (docs/SERVING.md "Multi-tenancy & SLO tiers")
        self._record("request_finished", value=float(len(req.tokens)),
                     rid=req.rid, tokens=len(req.tokens),
                     **self._tenant_fields(req))
        self._release(slot)

    def _preempt(self, slot: int, why: str = "pool") -> None:
        """Recompute-style preemption: pages freed, generated tokens KEPT;
        re-admission prefills prompt+tokens (greedy decode reproduces the
        exact state, no quality loss — only recomputed FLOPs).

        ``why="pool"`` is page pressure; ``why="latency"`` is tier-aware
        displacement by a protected request, charged against the victim's
        bounded yield budget (:attr:`latency_preempt_budget`). Either way
        the victim requeues at the front with its original SFQ tags —
        oldest work still completes."""
        req = self.slots[slot]
        req.preemptions += 1
        if why == "latency":
            req._latency_preempts = getattr(req, "_latency_preempts", 0) + 1
        req.state = RequestState.QUEUED
        self._record("preemption", rid=req.rid, why=why,
                     tokens_done=len(req.tokens),
                     **self._tenant_fields(req))
        self._release(slot)
        self.queue.appendleft(req)

    # ------------------------------------------------------------- deadlines
    def _expire(self, req: Request, where: str, now: float) -> None:
        req.state = RequestState.EXPIRED
        req.reject_reason = f"deadline_{where}"
        self.expired.append(req)
        t0 = req.t_submit if req.t_submit is not None else now
        if self.brownout is not None:
            self.brownout.observe("miss", now)
        self._record("deadline_miss", value=now - t0,
                     rid=req.rid, where=where,
                     tokens_done=len(req.tokens),
                     **self._tenant_fields(req))

    def _sweep_deadlines(self) -> int:
        """Evict expired requests (queued: TTFT or e2e deadline already
        blown; running: e2e deadline blown — pages freed). Returns the
        number evicted; any eviction is a recovery action, so the page
        audit runs."""
        now = self.clock()
        evicted = 0
        for req in [r for r in self.queue]:
            t0 = req.t_submit if req.t_submit is not None else now
            # TTFT only applies while the first token is still owed — a
            # preempted request back in the queue has already delivered it
            miss_ttft = (req.ttft_deadline_s is not None
                         and req.t_first_token is None
                         and now - t0 > req.ttft_deadline_s)
            miss_e2e = (req.deadline_s is not None
                        and now - t0 > req.deadline_s)
            if miss_ttft or miss_e2e:
                self.queue.remove(req)
                self._expire(req, "queued", now)
                evicted += 1
        for slot in self.active_slots:
            req = self.slots[slot]
            t0 = req.t_submit if req.t_submit is not None else now
            if req.deadline_s is not None and now - t0 > req.deadline_s:
                self._release(slot)
                self._expire(req, "running", now)
                evicted += 1
        if evicted:
            self._audit_after_recovery("deadline_sweep")
        return evicted

    # ------------------------------------------------------ dispatch bracket
    def _phase(self, kind: str):
        if self.watchdog is None:
            return nullcontext()
        return self.watchdog.phase(f"serving_{kind}")

    def _dispatch(self, kind: str, fn, *args: Any, **kw: Any) -> Any:
        """One dispatch episode: chaos injection + watchdog phase bracket +
        bounded retry on the shared backoff curve. The chaos hook fires
        INSIDE the phase (an injected stall is observed by the deadline
        machinery) and BEFORE the executor call (an injected raise never
        tears device state, so the in-place retry is sound)."""
        attempts = self.dispatch_retries + 1
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            idx = self._dispatch_count
            self._dispatch_count += 1
            try:
                with self._phase(kind):
                    serving_dispatch_fault(kind, idx)
                    out = fn(*args, **kw)
                self._consecutive_failures[kind] = 0
                return out
            except Exception as e:
                last = e
                self._record("dispatch_error", kind=kind, attempt=attempt,
                             error=f"{type(e).__name__}: {e}"[:200])
                if attempt < attempts:
                    time.sleep(backoff_delay(attempt, self.retry_base_delay,
                                             self.retry_max_delay))
        raise _DispatchFailure(kind, attempts, last)

    def _on_dispatch_episode_failed(self, fail: _DispatchFailure,
                                    affected: List[int],
                                    block: Optional[int] = None) -> None:
        """A whole dispatch episode (all retries) failed: quarantine the
        decode block shape after K failures, preempt-and-requeue the
        affected slots (kept-token semantics — greedy re-prefill reproduces
        the exact state), audit the pool, and give up loudly once the
        consecutive-failure budget is spent."""
        if block is not None and block > 1:
            n = self._block_failures.get(block, 0) + 1
            self._block_failures[block] = n
            if (n >= self.quarantine_after
                    and block not in self._quarantined_blocks):
                self._quarantined_blocks.add(block)
                self._record("block_quarantined", value=block, block=block,
                             failures=n)
        # newest-admitted first keeps the requeue order FIFO-consistent:
        # appendleft of newest..oldest leaves the oldest at the queue head
        for slot in sorted(affected, key=lambda s: self._admit_seq[s],
                           reverse=True):
            if self.slots[slot] is not None:
                self._preempt(slot)
        n = self._consecutive_failures.get(fail.kind, 0) + 1
        self._consecutive_failures[fail.kind] = n
        self._record("dispatch_failed", kind=fail.kind,
                     attempts=fail.attempts, consecutive=n,
                     error=f"{type(fail.last).__name__}: {fail.last}"[:200])
        self._audit_after_recovery(f"dispatch_failed[{fail.kind}]")
        if n >= self.dispatch_failure_budget:
            raise ServingFaultError(
                f"{n} consecutive {fail.kind} dispatch episodes failed "
                f"(budget {self.dispatch_failure_budget}); last: "
                f"{fail}") from fail.last

    # ----------------------------------------------------------- page audit
    def audit(self) -> Dict[str, Any]:
        """The allocator conservation invariant plus the scheduler-side
        cross-checks. With copy-on-write sharing, conservation means free +
        Σ(unique allocated) == total with every refcount >= 1 (allocator
        side), each page's refcount equals the number of slot references it
        actually has, and — the write-safety half — NO slot can ever write
        a shared page: every page referenced by more than one slot must lie
        entirely below each referencing slot's write frontier (a full
        prefix page), because the next append lands at ``lengths[slot]``."""
        fp_fn = (getattr(self.executor, "fingerprint_pages", None)
                 if self.page_fingerprints else None)
        rep = (self.allocator.audit(expected_fingerprints=self._page_fp,
                                    fingerprint_fn=fp_fn)
               if fp_fn is not None else self.allocator.audit())
        errors: List[str] = list(rep["errors"])
        refs: Dict[int, int] = {}
        for s_idx, pages in enumerate(self._slot_pages):
            if len(pages) != len(set(pages)):
                errors.append(f"slot {s_idx} lists a page twice")
            for p in pages:
                refs[p] = refs.get(p, 0) + 1
        if set(refs) != self.allocator.allocated_ids:
            leaked = sorted(self.allocator.allocated_ids - set(refs))
            foreign = sorted(set(refs) - self.allocator.allocated_ids)
            if leaked:
                errors.append(f"pages allocated but owned by no slot "
                              f"(leak): {leaked}")
            if foreign:
                errors.append(f"slot-held pages unknown to the allocator: "
                              f"{foreign}")
        for p, n in refs.items():
            have = self.allocator.refcount(p)
            if have != n:
                errors.append(f"page {p}: {n} slot reference(s) vs "
                              f"allocator refcount {have} (leaked refcount)")
        for s_idx, pages in enumerate(self._slot_pages):
            if s_idx in self._handoff_slots:
                # a staged handoff is read-only by construction: its table
                # row is parked on the sink page (lengths 0), so the
                # frontier invariants below do not apply — conservation and
                # refcount checks above still do
                continue
            frontier = int(self.lengths[s_idx])
            # the borrowed-prefix bookkeeping must agree with reality: the
            # slot borrowed its first _slot_shared pages, so the write
            # frontier can never sit inside them
            if self._slot_shared[s_idx] * self.page_size > frontier:
                errors.append(
                    f"slot {s_idx} records {self._slot_shared[s_idx]} "
                    f"borrowed prefix pages but its write frontier "
                    f"{frontier} is inside them")
            for idx, p in enumerate(pages):
                if (self.allocator.refcount(p) > 1
                        and (idx + 1) * self.page_size > frontier):
                    errors.append(
                        f"shared page {p} (table index {idx}) reaches slot "
                        f"{s_idx}'s write frontier {frontier} — a decode "
                        f"append could land on a shared page")
        rep["errors"] = errors
        rep["ok"] = not errors
        rep["page_stats"] = dict(self.page_stats)
        return rep

    def _audit_after_recovery(self, context: str) -> None:
        rep = self.audit()
        if not rep["ok"]:
            self._record("page_audit_failed", context=context,
                         errors="; ".join(rep["errors"])[:400])
            raise RuntimeError(
                f"page conservation broken after {context}: {rep['errors']}")

    # ---------------------------------------------- KV-page data integrity
    def _stamp_pages(self, pages: List[int]) -> None:
        """Fingerprint pages whose content just became IMMUTABLE (full
        prefix pages at registration, staged handoff pages). Stamp-once:
        a page already stamped keeps its first-writer fingerprint — a
        re-stamp would bless whatever bytes are there now, corrupt or not.
        Stamps die with the page in :meth:`_release`."""
        if not self.page_fingerprints:
            return
        fn = getattr(self.executor, "fingerprint_pages", None)
        todo = [p for p in pages if p not in self._page_fp]
        if fn is None or not todo:
            return
        for p, fp in zip(todo, fn(todo)):
            self._page_fp[p] = int(fp)

    def _verify_pages(self, pages: List[int], context: str) -> List[int]:
        """Re-fingerprint stamped pages and return the mismatches (each
        recorded as a typed ``sdc_detected`` event). Unstamped pages are
        skipped — they are still behind an active write frontier."""
        fn = getattr(self.executor, "fingerprint_pages", None)
        check = [p for p in pages if p in self._page_fp]
        if fn is None or not check:
            return []
        bad = [p for p, fp in zip(check, fn(check))
               if int(fp) != self._page_fp[p]]
        for p in bad:
            self._record("sdc_detected", domain="kv_page", page=int(p),
                         context=context,
                         refcount=self.allocator.refcount(p))
        return bad

    def _quarantine_page(self, page: int, context: str) -> None:
        """Containment + healing for a corrupt KV page: forget it in the
        prefix index (no future admission borrows it), void its stamp, and
        preempt every slot referencing it — recompute-style, so each victim
        re-prefills prompt + kept tokens into clean pages and greedy decode
        reproduces the exact stream. Never a blind retry over rotten KV."""
        if self.prefix_cache is not None:
            self.prefix_cache.forget([page])
        self._page_fp.pop(page, None)
        victims = [i for i, pages in enumerate(self._slot_pages)
                   if page in pages and self.slots[i] is not None
                   and i not in self._handoff_slots]
        for i in victims:
            self._preempt(i, why="sdc")
        self._record("sdc_healed", domain="kv_page", page=int(page),
                     context=context, victims=len(victims))
        self._audit_after_recovery(f"sdc_{context}")

    def _integrity_scan(self) -> None:
        """Budgeted background sweep: verify up to ``pages_scan_per_step``
        stamped pages round-robin per scheduler step, quarantining any
        mismatch. Also the serving consumption point for the chaos plan's
        ``flip_bit_at`` (domain ``kv_page``): the flip lands in a real
        stamped page's pool content so detection exercises the same path
        production corruption would take."""
        flip = sdc_flip_fault(self.steps, scope="serving")
        if flip is not None and self._page_fp:
            corrupt = getattr(self.executor, "corrupt_page_bit", None)
            if corrupt is not None:
                # prefer a SHARED page: the worst blast radius (several
                # borrowers) is the one worth rehearsing
                shared = [p for p in sorted(self._page_fp)
                          if self.allocator.refcount(p) > 1]
                target = (shared or sorted(self._page_fp))[0]
                corrupt(target)
                self._record("chaos_injected", kind="sdc_flip",
                             page=int(target))
        stamped = sorted(self._page_fp)
        if not stamped or self.pages_scan_per_step <= 0:
            return
        k = min(self.pages_scan_per_step, len(stamped))
        start = self._page_scan_rr % len(stamped)
        batch = [stamped[(start + j) % len(stamped)] for j in range(k)]
        self._page_scan_rr = (start + k) % len(stamped)
        for p in self._verify_pages(batch, "scan"):
            self._quarantine_page(p, "scan")

    def close(self) -> None:
        """Stop a watchdog the engine created for this scheduler (no-op for
        caller-owned or absent watchdogs)."""
        if self._owns_watchdog and self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None

    # ------------------------------------------------------------ admission
    def _claim_pages(self, req: Request, need: int) -> Optional[tuple]:
        """Pages for one admission: shared prefix pages from the index
        (refcount bumped, copy-on-write) + fresh ones for the rest. Returns
        (pages, shared_count) or None (and claims NOTHING) when the pool
        cannot cover the unshared remainder. The shared count is always <
        ``need``: the append frontier (position ctx, first decode write) is
        past the page-aligned prompt prefix, so the page it lands in is
        always privately owned.

        Hot-path discipline: admission retries EVERY step while the queue
        head is pool-blocked, so the prompt's hash chain is computed once
        and cached on the request, the free-list is probed BEFORE any
        refcount is taken (no share-then-unwind churn per retry), and hit
        statistics count only the admission that proceeds."""
        shared: List[int] = []
        hashes = ()
        if self.prefix_cache is not None:
            hashes = getattr(req, "_prefix_hashes", None)
            if hashes is None:
                hashes = prefix_chain_hashes(np.asarray(req.prompt),
                                             self.page_size)
                req._prefix_hashes = hashes
            shared = self.prefix_cache.lookup_chain(hashes)[:need]
        if shared and self.page_fingerprints:
            # trust boundary: these pages are about to serve ANOTHER
            # request's prefix — re-fingerprint before the refcount bump.
            # A mismatch truncates the borrow at the first corrupt page
            # (its suffix chains through it, so it is unusable too) and
            # quarantines: index eviction + borrower unwind, then this
            # admission proceeds as a partial/complete cache miss.
            bad = self._verify_pages(shared, "share")
            if bad:
                cut = min(shared.index(p) for p in bad)
                for p in bad:
                    self._quarantine_page(p, "share")
                shared = shared[:cut]
        if not self.allocator.can_alloc(need - len(shared)):
            return None
        if shared:
            self.allocator.share(shared)
        own = self.allocator.alloc(need - len(shared))
        if own is None:  # chaos alloc_fail_at fires through the normal path
            if shared:
                self.prefix_cache.forget(self.allocator.free(shared))
            return None
        if self.prefix_cache is not None:
            self.prefix_cache.count(hashes, shared)
        self.page_stats["logical"] += need
        self.page_stats["physical"] += len(own)
        self.page_stats["shared"] += len(shared)
        return shared + own, len(shared)

    def _peek_queued(self, blocked: Set[str]) -> Optional[Request]:
        """Non-mutating admission pick: the request :meth:`_pick_queued`
        would return, WITHOUT advancing SFQ virtual time."""
        if self.tiers is None:
            return self.queue[0] if self.queue else None
        best: Optional[Request] = None
        best_key = None
        for r in self.queue:
            tier = r.tier or DEFAULT_TIER
            if tier in blocked:
                continue
            if self.brownout_stage >= 3 and tier_rank(tier) > 0:
                continue
            key = (getattr(r, "_wfq_start", 0.0),
                   getattr(r, "_wfq_finish", 0.0), r.rid)
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _pick_queued(self, blocked: Set[str]) -> Optional[Request]:
        """The next queued request to admit. Untiered: the FIFO head.
        Tiered: the minimum SFQ virtual-time tag (start, finish, rid) among
        requests whose tier is neither pool-blocked this cycle (``blocked``
        — per-tier head-of-line, so a pool-blocked batch head cannot block
        interactive admission) nor held by the brownout ladder
        (``hold_standard``: only interactive reaches a slot)."""
        best = self._peek_queued(blocked)
        if best is not None and self._wfq is not None:
            self._wfq.on_select(getattr(best, "_wfq_start", 0.0))
        return best

    def _reserve_shortfall(self, tier: str) -> int:
        """Free slots that must be LEFT OPEN when admitting ``tier``: the
        summed ``reserved_slots`` of every more-protected tier. Strict
        headroom — a protected tier's RUNNING requests do not repay its
        reservation (crediting them would let lower tiers fill every other
        slot the moment one interactive request runs, putting the next
        arrival right back behind a standard decode). Protected tiers
        admit past their reservation through the normal queue: the reserve
        is a floor on instant availability, not a cap on use."""
        if self.tiers is None:
            return 0
        rank = tier_rank(tier)
        return sum(tc.reserved_slots for name, tc in self.tiers.items()
                   if tc.reserved_slots and tier_rank(name) < rank)

    def _latency_preempt(self, blocked: Set[str],
                         pending: Set[int]) -> Optional[Tuple[int, Request]]:
        """Tier-aware latency preemption: every slot is busy but the fair-
        queue head is an INTERACTIVE request — sacrifice the newest
        batch-tier slot (kept-token requeue) rather than make the
        protected tier wait out a batch decode. Only interactive
        displaces, and only batch is ever displaced: standard queues like
        everyone else, and an interactive-vs-interactive conflict is real
        contention, not a noisy neighbor. A victim already displaced
        :attr:`latency_preempt_budget` times is IMMUNE — that bound is
        what keeps the WFQ starvation-freedom property: under a sustained
        interactive storm a batch request yields at most budget times,
        then holds its slot to completion. Returns the freed slot and the
        request it was freed FOR (force-admitted by the caller — the
        displaced victim keeps its original minimal SFQ tag, so selection
        alone cannot be trusted to not hand the slot straight back)."""
        if (self.tiers is None or not self.queue
                or self.latency_preempt_budget <= 0):
            return None
        head = self._peek_queued(blocked)
        if head is None or tier_rank(head.tier or DEFAULT_TIER) != 0:
            return None
        # ``pending`` excludes slots claimed earlier in THIS admission
        # cycle: they sit in the phase-2 prefill batch, and evicting one
        # would leave a stale batch entry prefilling into a slot that no
        # longer belongs to its request
        victims = [s for s in self.active_slots
                   if s not in pending
                   and tier_rank(self.slots[s].tier) >= tier_rank("batch")
                   and (getattr(self.slots[s], "_latency_preempts", 0)
                        < self.latency_preempt_budget)]
        if not victims:
            return None
        victim = max(victims, key=self._victim_key)
        self._preempt(victim, why="latency")
        self._wfq.on_select(getattr(head, "_wfq_start", 0.0))
        self._audit_after_recovery("latency_preempt")
        return victim, head

    def _admit(self) -> int:
        # phase 1: claim slots + pages for everything that fits this cycle
        batch = []  # (slot, context tokens, first unshared position)
        free = deque(s for s in range(self.num_slots)
                     if self.slots[s] is None)
        blocked: Set[str] = set()  # tiers pool-blocked this cycle
        forced: Optional[Request] = None  # latency-preempt beneficiary
        while True:
            if not free:
                grab = self._latency_preempt(
                    blocked, {slot for slot, _, _ in batch})
                if grab is None:
                    break
                slot, forced = grab
                free.append(slot)
            slot = free[0]
            req = forced if forced is not None else self._pick_queued(blocked)
            forced = None
            if req is None:
                break
            if len(free) <= self._reserve_shortfall(req.tier or DEFAULT_TIER):
                # admitting would eat a more-protected tier's reserved
                # slot — this tier sits the cycle out, the slot stays open
                blocked.add(req.tier or DEFAULT_TIER)
                continue
            if req.kv_payload is not None:
                # disaggregated handoff arrival: admit by IMPORTING the
                # prefill replica's exported pages — no prefill dispatch
                if self._admit_import(slot, req):
                    free.popleft()
                elif self.tiers is None:
                    break  # pool-blocked (FIFO) or the import failed
                else:
                    blocked.add(req.tier or DEFAULT_TIER)
                continue
            ctx = req.context_len
            # +1: the first decode step appends its token's KV at position
            # ctx, which may open a fresh page
            need = pages_for(ctx + 1, self.page_size)
            claim = self._claim_pages(req, need)
            if claim is None:
                if self.tiers is None:
                    # head-of-line blocking keeps FIFO order under pressure
                    break
                blocked.add(req.tier or DEFAULT_TIER)
                continue
            free.popleft()
            pages, shared = claim
            self.queue.remove(req)
            self._slot_pages[slot] = pages
            self._slot_shared[slot] = shared
            self.tables[slot] = 0
            self.tables[slot, :len(pages)] = pages
            tokens = np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(req.tokens, np.int32)]) if req.tokens else \
                np.asarray(req.prompt, np.int32)
            self.lengths[slot] = ctx
            self.slots[slot] = req
            self._admissions += 1
            self._admit_seq[slot] = self._admissions
            req.state = RequestState.RUNNING
            batch.append((slot, tokens, shared * self.page_size))
        if not batch:
            return 0
        # phase 2: prefill the whole admission cycle — batched when the
        # executor supports it (one [num_slots, chunk] dispatch instead of
        # one per request). A failed episode (retries exhausted) unwinds the
        # WHOLE admission cycle back to the queue: no request has appended a
        # token yet, so requeue-with-kept-tokens is exact. With prefix
        # sharing the executor additionally receives each row's first
        # UNSHARED position — its KV scatter must never touch a borrowed
        # page (the prefill forward still runs the full context).
        try:
            if hasattr(self.executor, "prefill_many"):
                if self.prefix_cache is not None:
                    items = [(slot, toks, self.tables[slot], start)
                             for slot, toks, start in batch]
                else:  # legacy 3-tuple protocol for start-less executors
                    items = [(slot, toks, self.tables[slot])
                             for slot, toks, _ in batch]
                results = self._dispatch(
                    "prefill", self.executor.prefill_many, items)
            else:
                results = {}
                for slot, toks, start in batch:
                    args = (slot, toks, self.tables[slot])
                    if self.prefix_cache is not None:
                        args += (start,)
                    results[slot] = int(self._dispatch(
                        "prefill", self.executor.prefill, *args))
        except _DispatchFailure as fail:
            self._on_dispatch_episode_failed(fail,
                                             [slot for slot, _, _ in batch])
            return 0
        for slot, _, _ in batch:
            req = self.slots[slot]
            first = int(results[slot])
            self.next_input[slot] = first
            # prefill's sample is the next NEW token whether this is a fresh
            # admission (prompt only) or a post-preemption re-prefill
            # (prompt + kept tokens): append it either way
            req.tokens.append(first)
            if req.t_first_token is None:
                req.t_first_token = self.clock()
            if self.prefix_cache is not None:
                # the slot's full prompt pages now hold canonical KV —
                # index them so later arrivals with the same prefix share
                # (first writer wins; entries die with the page)
                self.prefix_cache.register(np.asarray(req.prompt),
                                           self._slot_pages[slot])
                # the registered full-prefix pages are immutable from here
                # (every position written, frontier past them) — stamp them
                # so share/scan/audit can prove the bytes never drift
                n_full = len(np.asarray(req.prompt)) // self.page_size
                self._stamp_pages(self._slot_pages[slot][:n_full])
            if req.done:
                self._finish(slot)
            elif self.role == "prefill":
                self._stage_handoff(slot)
        return len(batch)

    # --------------------------------------------- disaggregated handoff
    def _stage_handoff(self, slot: int) -> None:
        """A prefill-role scheduler just delivered a request's first token:
        stage its pages for export instead of decoding. The slot's table
        row is parked on the sink page so a concurrent decode dispatch for
        OTHER slots can never write into the staged pages (a stray append
        would dirty a quantized page's scale before export); the page order
        is snapshotted in the entry."""
        req = self.slots[slot]
        req.state = RequestState.HANDOFF
        # KV live on this replica: everything prefilled — the freshly
        # sampled first token's KV is NOT written yet (the decode side
        # writes it at its own first decode step)
        live = req.context_len - 1
        n_pages = pages_for(live, self.page_size) if live else 0
        self._handoffs[req.rid] = {
            "rid": req.rid, "slot": slot, "request": req,
            "page_ids": list(self._slot_pages[slot][:n_pages]),
            "context_len": live, "popped": False}
        self._handoff_slots.add(slot)
        self.tables[slot] = 0
        self.lengths[slot] = 0
        self.next_input[slot] = 0
        # staged pages are read-only until the decode side acks — stamp
        # them so the background scan covers the staging window and the
        # export's payload fingerprints attest bytes that were still clean
        self._stamp_pages(self._slot_pages[slot][:n_pages])
        self._record("handoff_staged", rid=req.rid, pages=n_pages,
                     context_len=live)

    @property
    def pending_handoff_rids(self) -> Set[int]:
        """Rids staged (popped or not) whose pages this replica still owns."""
        return set(self._handoffs)

    def pop_handoffs(self) -> List[dict]:
        """Staged handoff entries not yet handed to the transport, WITHOUT
        freeing anything (export-before-free: the pages stay owned and
        refcounted until :meth:`complete_handoff`). Each entry carries the
        request, its page ids in table order, and the live context length;
        the caller serializes the pages (``ServingEngine.export_pages``)
        and ships them to a decode-role replica."""
        out = []
        for e in self._handoffs.values():
            if not e["popped"]:
                e["popped"] = True
                out.append(e)
        return out

    def complete_handoff(self, rid: int, ok: bool = True) -> bool:
        """The decode side acknowledged (``ok=True``) — or the handoff was
        orphaned and the router re-routed the request (``ok=False``) —
        either way THIS replica's ownership ends: free the staged pages,
        recycle the slot, audit. Returns False for an unknown rid (already
        completed; idempotent)."""
        e = self._handoffs.pop(rid, None)
        if e is None:
            return False
        slot = e["slot"]
        req = self.slots[slot]
        self._handoff_slots.discard(slot)
        if req is not None:
            if ok:
                self.handed_off.append(req)
            self._release(slot)
        self._record("handoff_complete" if ok else "handoff_aborted",
                     rid=rid)
        self._audit_after_recovery(
            f"handoff_{'complete' if ok else 'abort'}")
        return True

    def _admit_import(self, slot: int, req: Request) -> bool:
        """Admission of a handoff arrival: claim this replica's own pages,
        install the exported KV into them (``executor.import_pages``), and
        seed the slot mid-stream — lengths at the live context, next input
        the already-delivered first token. Page ids need not match across
        replicas; only the table ORDER is the contract."""
        ctx = req.context_len
        # first decode write lands at position ctx-1 (the handed-off
        # token's KV) — pages must cover it
        need = pages_for(ctx, self.page_size)
        pages = (self.allocator.alloc(need)
                 if self.allocator.can_alloc(need) else None)
        if pages is None:
            return False
        self.queue.remove(req)
        live = ctx - 1
        n_kv = pages_for(live, self.page_size) if live else 0
        try:
            self._dispatch("import_kv", self.executor.import_pages,
                           pages[:n_kv], req.kv_payload)
        except _DispatchFailure as fail:
            # nothing installed durably matters — the claim unwinds whole
            # and the request requeues intact for another import attempt
            self.allocator.free(pages)
            self.queue.appendleft(req)
            self._on_dispatch_episode_failed(fail, [])
            return False
        self.page_stats["logical"] += need
        self.page_stats["physical"] += need
        self._slot_pages[slot] = pages
        self._slot_shared[slot] = 0
        self.tables[slot] = 0
        self.tables[slot, :len(pages)] = pages
        self.lengths[slot] = live
        self.next_input[slot] = int(req.tokens[-1])
        self.slots[slot] = req
        self._admissions += 1
        self._admit_seq[slot] = self._admissions
        req.state = RequestState.RUNNING
        # consumed: a later preemption re-prefills prompt+kept tokens — the
        # payload's KV no longer covers the grown context
        req.kv_payload = None
        if req.t_first_token is None:
            req.t_first_token = self.clock()
        self._record("handoff_import", rid=req.rid, pages=n_kv,
                     context_len=live)
        return True

    def _ensure_page(self, slot: int, horizon: int = 1) -> bool:
        """Make sure pages exist for write positions ``lengths[slot]`` up to
        ``lengths[slot] + horizon - 1`` (a decode block appends ``horizon``
        tokens between scheduling points)."""
        last_pi = (int(self.lengths[slot]) + horizon - 1) // self.page_size
        if last_pi >= self.pages_per_seq:
            raise RuntimeError(
                f"slot {slot} outgrew pages_per_seq — admission bound broken")
        for pi in range(last_pi + 1):
            if self.tables[slot, pi] != 0:
                continue
            page = self.allocator.alloc(1)
            if page is None:
                return False
            self._slot_pages[slot].append(page[0])
            self.tables[slot, pi] = page[0]
            self.page_stats["logical"] += 1
            self.page_stats["physical"] += 1
        return True

    # ------------------------------------------------------------ one step
    def _block_size(self) -> int:
        """Steps safely runnable as one compiled block: no slot may finish
        early (wasted work), no eos can fire unseen (eos requests decode
        step-by-step), and page growth for the whole horizon must be
        coverable up front. Rounded down to a power of two so the engine
        compiles at most log2(decode_block)+1 block shapes."""
        if self.decode_block <= 1:
            return 1
        reqs = [self.slots[s] for s in self.active_slots]
        if any(r.eos_token_id is not None for r in reqs):
            return 1
        remaining = min(r.max_new_tokens - len(r.tokens) for r in reqs)
        k = 1
        while k * 2 <= min(remaining, self.decode_block):
            k *= 2
        while k > 1 and k in self._quarantined_blocks:
            k //= 2  # shapes that keep failing dispatch are off the menu
        return k

    def step(self) -> int:
        """Expire blown deadlines, admit what fits, then run one decode step
        (or one safe decode BLOCK, or — with a drafter armed — one
        speculative verify window) over the slot array. Returns tokens
        produced."""
        self._maybe_tenant_flood()
        if self.brownout is not None:
            self._brownout_tick()
        self._sweep_deadlines()
        if self.page_fingerprints:
            # scan BEFORE admission so a rotted page is quarantined before
            # this step's admissions could borrow it
            self._integrity_scan()
        self._admit()
        if not self.active_slots:
            return 0
        if self.drafter is not None:
            produced = self._spec_step()
            if produced is not None:
                return produced
            # no slot had a draftable history this step: fall back to the
            # plain decode path (speculation must never cost a step)
            self.spec_stats["fallback_steps"] += 1
        return self._decode_step()

    def _brownout_tick(self) -> None:
        """Poll the degradation ladder; on a transition, record the typed
        ``tier_brownout`` event, apply the stage's mechanics, and prove
        page conservation (every ladder transition is a recovery action)."""
        stage = self.brownout.decide(self.clock())
        if stage == self.brownout_stage:
            return
        prev, self.brownout_stage = self.brownout_stage, stage
        if stage >= 2 and prev < 2:
            # clamp_batch: cap the EXISTING batch backlog's generation
            # budget so it drains capacity back faster (new batch work is
            # already shed at stage >= 1). Never below what is already
            # generated — a clamped running request simply finishes now.
            for req in list(self.queue) + [self.slots[s]
                                           for s in self.active_slots]:
                if req is None or req.tier != "batch":
                    continue
                clamp = self.tiers["batch"].brownout_max_new
                if clamp is not None and req.max_new_tokens > clamp:
                    req.max_new_tokens = max(clamp, len(req.tokens), 1)
        self._record("tier_brownout", value=float(stage), stage=stage,
                     stage_name=BROWNOUT_STAGES[stage], prev=prev,
                     direction="enter" if stage > prev else "exit")
        self._audit_after_recovery("tier_brownout")

    def _maybe_tenant_flood(self) -> None:
        """Noisy-neighbor chaos: an armed ``FaultPlan.tenant_flood_at``
        injects a one-shot burst of batch-tier submissions from one tenant
        through the REAL ``submit()`` path at this step."""
        burst = serving_tenant_flood(self.steps)
        if burst is None:
            return
        vocab = max(int(burst["vocab"]), 2)
        p_len = max(int(burst["prompt_tokens"]), 1)
        for i in range(int(burst["requests"])):
            prompt = (np.arange(1, p_len + 1, dtype=np.int32)
                      * (i + 3)) % (vocab - 1) + 1
            self.submit(Request(prompt=prompt.astype(np.int32),
                                max_new_tokens=int(burst["max_new"]),
                                tenant_id=burst["tenant_id"],
                                tier="batch"))
        self._record("tenant_flood", value=float(burst["requests"]),
                     requests=int(burst["requests"]),
                     tenant_id=burst["tenant_id"])

    def _spec_step(self) -> Optional[int]:
        """One speculation window: draft up to k tokens per active slot,
        verify k+1 positions in ONE dispatch (in-program longest-prefix
        greedy acceptance + accepted-prefix KV commit), apply the accepted
        tokens. Returns tokens produced, or None when no slot produced a
        draft (caller falls back to plain decode)."""
        k = self._spec_ctl.k
        W = k + 1
        drafts: Dict[int, np.ndarray] = {}
        for slot in self.active_slots:
            req = self.slots[slot]
            try:
                d = np.asarray(self.drafter.draft(
                    slot, req.rid, np.asarray(req.prompt, np.int32),
                    req.tokens, k), np.int32)[:k]
            except Exception as e:  # a broken drafter must not stop serving
                self._record("drafter_error",
                             error=f"{type(e).__name__}: {e}"[:200])
                d = np.empty(0, np.int32)
            drafts[slot] = d
        if not any(len(d) for d in drafts.values()):
            return None
        # page growth for each slot's commit horizon (never beyond its
        # remaining budget — commits are budget-truncated in-program),
        # preempting newest-first under pool pressure like the block path
        for slot in list(self.active_slots):
            req = self.slots[slot]
            if req is None:
                continue
            horizon = max(min(W, req.max_new_tokens - len(req.tokens)), 1)
            while not self._ensure_page(slot, horizon=horizon):
                victim = max(self.active_slots, key=self._victim_key)
                self._preempt(victim)
                if victim == slot:
                    break
        active = self.active_slots
        if not active:
            return 0
        win = np.zeros((self.num_slots, W), np.int32)
        eos = np.full(self.num_slots, -1, np.int32)
        budget = np.zeros(self.num_slots, np.int32)
        offered: Dict[int, int] = {}
        for slot in active:
            req = self.slots[slot]
            win[slot, 0] = self.next_input[slot]
            d = drafts.get(slot, np.empty(0, np.int32))
            win[slot, 1:1 + len(d)] = d
            offered[slot] = len(d)
            if req.eos_token_id is not None:
                eos[slot] = req.eos_token_id
            budget[slot] = req.max_new_tokens - len(req.tokens)
        mask = np.zeros(self.num_slots, bool)
        mask[active] = True
        try:
            outs, n_acc = self._dispatch(
                "verify", self.executor.verify, win, self.tables.copy(),
                self.lengths.copy(), mask, eos, budget)
        except _DispatchFailure as fail:
            # nothing was committed (the injected raise fires before the
            # executor call): every slot requeues with exactly its tokens,
            # the healed rerun is greedy-identical — mid-window preemption
            # is the same kept-token contract as mid-block
            self._on_dispatch_episode_failed(fail, active)
            return 0
        outs = np.asarray(outs)
        n_acc = np.asarray(n_acc)
        self.steps += 1
        produced = 0
        step_offered = step_accepted = 0
        for slot in active:
            req = self.slots[slot]
            if req is None or req.state is not RequestState.RUNNING:
                continue
            n = int(n_acc[slot])
            self.lengths[slot] += n   # the n accepted inputs' KV is cached
            acc_drafts = max(n - 1, 0)
            dr = offered[slot]
            req.spec_drafted += dr
            req.spec_accepted += min(acc_drafts, dr)
            step_offered += dr
            step_accepted += min(acc_drafts, dr)
            if dr:
                if acc_drafts >= dr:
                    self.spec_stats["full_accept_windows"] += 1
                elif acc_drafts == 0:
                    self.spec_stats["full_reject_windows"] += 1
            for i in range(n):
                req.tokens.append(int(outs[slot, i]))
                produced += 1
            if n:
                self.next_input[slot] = req.tokens[-1]
            if req.done:
                self._finish(slot)
        self.spec_stats["windows"] += 1
        self.spec_stats["drafted"] += step_offered
        self.spec_stats["accepted"] += step_accepted
        self.spec_stats["committed_tokens"] += produced
        self._spec_ctl.observe(step_offered, step_accepted)
        # the per-step ledger row the fleet autoscaler's summarize_events
        # merges: accept_rate + tokens_per_dispatch + drafter kind
        self._record(
            "spec_window", value=float(produced), k=k,
            drafted=step_offered, accepted=step_accepted,
            accept_rate=round(step_accepted / max(step_offered, 1), 4),
            tokens_per_dispatch=produced,
            drafter=self.spec_stats["drafter"])
        return produced

    def _decode_step(self) -> int:
        block = self._block_size()
        # page growth for the block horizon, preempting newest-first under
        # pool pressure
        for slot in list(self.active_slots):
            if self.slots[slot] is None:
                continue
            while not self._ensure_page(slot, horizon=block):
                # newest-admitted work yields FIRST — including the growing
                # slot itself, so an old request is never evicted by a
                # younger grower (oldest work always completes). With tiers
                # armed, batch slots are sacrificed before interactive ones
                # (newest-first within a tier)
                victim = max(self.active_slots, key=self._victim_key)
                self._preempt(victim)
                if victim == slot:
                    break
        active = self.active_slots
        if not active:
            return 0
        block = min(block, self._block_size())  # preemption may shrink it
        mask = np.zeros(self.num_slots, bool)
        mask[active] = True
        try:
            out = np.asarray(self._dispatch(
                "decode", self.executor.decode, self.next_input.copy(),
                self.tables.copy(), self.lengths.copy(), mask, steps=block))
        except _DispatchFailure as fail:
            # no token from this episode was observed: every active slot
            # requeues with exactly the tokens it had, so the healed rerun
            # is greedy-identical to a fault-free one
            self._on_dispatch_episode_failed(fail, active, block=block)
            return 0
        if out.ndim == 1:  # simple executors may return a flat SINGLE step
            if block != 1:
                raise ValueError(
                    f"executor returned a flat token vector for a "
                    f"{block}-step decode block; multi-step decode must "
                    f"return [steps, num_slots]")
            out = out[None]
        self.steps += 1
        produced = 0
        for k in range(block):
            for slot in active:
                req = self.slots[slot]
                if req is None or req.state is not RequestState.RUNNING:
                    continue
                self.lengths[slot] += 1  # input token's KV now cached
                tok = int(out[k, slot])
                req.tokens.append(tok)
                self.next_input[slot] = tok
                produced += 1
                if req.done:
                    self._finish(slot)
        return produced

    def run_to_completion(self, max_steps: int = 100_000) -> None:
        """Drain queue + slots (closed-loop; the open-loop driver lives in
        ``serving.bench``)."""
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"scheduler did not drain in {max_steps} steps")
