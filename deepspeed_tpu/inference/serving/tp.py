"""Tensor-parallel serving programs: one ``ServingEngine`` replica spanning a
2-4 chip ``tp`` mesh.

The serving stack above the engine (scheduler, page allocator, speculation,
chaos machinery, fleet protocol) never sees the mesh: block tables, lengths
and token ids stay replicated host-level values, while the paged KV pools and
the weight stacks are sharded over attention heads / MLP features. This is
the AutoTP shape (reference ``module_inject/auto_tp.py``): column-split QKV +
row-split attention output, column-split MLP up + row-split MLP down, ONE
``psum`` per sublayer — attention and its out-projection partial-sum, MLP up
/act/down partial-sum — so a block costs two reduces (one fused reduce when
``parallel_residual`` folds both deltas into the same residual add).

Sharding layout (head-contiguous, so plain ``PartitionSpec``s do all the
work — the one host-side reshape is ``qkv_w [L,d,3d] -> [L,d,3,d]`` /
``qkv_b [L,3d] -> [L,3,d]`` so the fused QKV projection splits per-head
instead of across the q|k|v concat boundary):

====================  ======================  =========================
array                 shape                   spec
====================  ======================  =========================
qkv_w / qkv_b         [L,d,3,d] / [L,3,d]     P(..., "tp") (head cols)
attn_out_w            [L,d,d]                 P(None, "tp", None) (rows)
mlp_up_w / mlp_up_b   [L,d,f] / [L,f]         P(..., "tp") (cols)
mlp_down_w            [L,f,d]                 P(None, "tp", None) (rows)
k/v_pages             [L,H,P,ps,Dh]           P(None, "tp", ...) (heads)
k/v_scales            [L,H,P]                 P(None, "tp", None)
dense prefill cache   [L,B,H,S,Dh]            P(None, None, "tp", ...)
everything else       (ln/bias/embed/head)    replicated
====================  ======================  =========================

Attention is per-head independent (rope, pool append, paged attention), so
each shard runs the unmodified per-head math from ``models/gpt.py`` on its
local heads — the page-append/commit/scatter writers (`_append_kv_token`,
`commit_window_kv`, `write_prompt_kv_batch`) are reused VERBATIM inside
``shard_map`` (they read every extent from the sliced arrays, never from
``cfg.n_head``). Logits come out replicated (the lm head is replicated and
the final residual stream is post-psum identical on every shard), so argmax
/ acceptance logic needs no collective at all.

Collective-order discipline: every ``psum`` is issued UNCONDITIONALLY in the
block body — never under a ``lax.cond``/``while`` whose predicate could
diverge across shards (the quantized pool append's requantize ``cond`` is
collective-free, which is exactly why it is safe to reuse here). The dslint
rule ``serving/tp-collective-order`` (analysis/rules_collectives.py) checks
captured tp programs for violations of this invariant.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...models import gpt as gpt_mod
from ...utils.jax_compat import shard_map

TP_AXIS = "tp"


# ------------------------------------------------------------------ context
class TPContext:
    """Mesh + sharding bookkeeping for one tensor-parallel serving replica.

    Owns the dedicated 1-axis ``("tp",)`` mesh (the serving replica's chips
    are its whole world — fleet-level placement picks WHICH chips via
    ``replica_env`` pinning), the partition specs for the reshaped weight
    tree and the paged/dense caches, and the captured jaxprs the
    ``serving/tp-collective-order`` dslint rule audits."""

    def __init__(self, cfg, tp: int, devices=None):
        if tp < 2:
            raise ValueError(f"TPContext needs tp >= 2, got {tp}")
        if cfg.n_head % tp:
            raise ValueError(
                f"tp={tp} must divide n_head={cfg.n_head} (head-sharded "
                f"attention)")
        if cfg.ffn_dim % tp:
            raise ValueError(
                f"tp={tp} must divide ffn_dim={cfg.ffn_dim} (col/row-split "
                f"MLP)")
        if cfg.alibi or cfg.local_attention_period > 1:
            raise ValueError("tp serving does not support alibi/local-window "
                             "attention (same bound as paged_decode_step)")
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < tp:
            raise ValueError(f"tp={tp} but only {len(devices)} devices")
        self.cfg = cfg
        self.tp = tp
        self.mesh = Mesh(np.asarray(devices[:tp]), (TP_AXIS,))
        # name -> ClosedJaxpr of the tp programs, populated by
        # capture_programs() (engine warmup) for the dslint audit
        self.captured: Dict[str, Any] = {}

    # ----------------------------------------------------------- param tree
    def reshape_params(self, params):
        """Host-side relayout: split the fused QKV axes so every sharded
        axis is head/feature-contiguous. Idempotent on already-reshaped
        trees."""
        if any(jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(gpt_mod._is_qleaf, params,
                                       is_leaf=gpt_mod._is_qleaf))):
            raise ValueError(
                "tp serving does not support quantized weight stacks yet "
                "(the int8/int4 Pallas matmuls are not head-sharded)")
        blocks = dict(params["blocks"])
        qkv_w = blocks["qkv_w"]
        if qkv_w.ndim == 3:  # [L, d, 3d] -> [L, d, 3, d]
            L, d, _ = qkv_w.shape
            blocks["qkv_w"] = qkv_w.reshape(L, d, 3, d)
            blocks["qkv_b"] = blocks["qkv_b"].reshape(L, 3, d)
        out = dict(params)
        out["blocks"] = blocks
        return out

    def param_specs(self, params) -> Dict[str, Any]:
        """PartitionSpecs for a :meth:`reshape_params` tree (serving tp
        layout — distinct from the training-time ``gpt.partition_specs``,
        which splits the raw QKV concat and vocab-shards the embedding)."""
        return _param_specs_impl(params)

    def cache_specs(self, paged_cache) -> Dict[str, P]:
        """Paged pool specs: heads sharded, everything else replicated."""
        return {k: (P(None, TP_AXIS, None)
                    if k in ("k_scales", "v_scales")
                    else P(None, TP_AXIS, None, None, None))
                for k in paged_cache}

    def dense_cache_specs(self) -> Dict[str, P]:
        return {"k": P(None, None, TP_AXIS, None, None),
                "v": P(None, None, TP_AXIS, None, None),
                "pos": P()}

    def _put(self, tree, specs):
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        return jax.device_put(tree, shardings)

    def shard_params(self, params):
        params = self.reshape_params(params)
        return self._put(params, self.param_specs(params))

    def shard_cache(self, paged_cache):
        return self._put(paged_cache, self.cache_specs(paged_cache))

    def shard_dense_cache(self, dense_cache):
        return self._put(dense_cache, self.dense_cache_specs())

    # ------------------------------------------------------------ dslint IO
    def capture_programs(self, engine) -> Dict[str, Any]:
        """Trace (never execute) the replica's tp decode/verify programs to
        jaxprs for the ``serving/tp-collective-order`` audit. Cheap: pure
        abstract tracing over ShapeDtypeStructs."""
        sds = lambda t: jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a), a.dtype), t)
        s = engine.serving
        B = engine.num_slots
        params, cache = sds(engine.params), sds(engine.paged_cache)
        ids = jax.ShapeDtypeStruct((B, 1), jnp.int32)
        win = jax.ShapeDtypeStruct((B, max(2, int(s.spec_k))), jnp.int32)
        tables = jax.ShapeDtypeStruct((B, s.pages_per_seq), jnp.int32)
        lengths = jax.ShapeDtypeStruct((B,), jnp.int32)
        impl = s.kernel_impl
        self.captured["tp_decode"] = jax.make_jaxpr(
            lambda p, c, i, t, le: tp_paged_decode_step(
                self.cfg, p, i, c, t, le, mesh=self.mesh, impl=impl)
        )(params, cache, ids, tables, lengths)
        self.captured["tp_verify"] = jax.make_jaxpr(
            lambda p, c, i, t, le: tp_paged_verify_step(
                self.cfg, p, i, c, t, le, mesh=self.mesh, impl=impl)
        )(params, cache, win, tables, lengths)
        return self.captured


# ------------------------------------------------- shard-local block bodies
def _local_qkv(cfg, x, w):
    """LN1 + head-sharded fused QKV projection. Returns q/k/v [B,T,H_loc,Dh]
    (bitwise the local-head slice of the unsharded projection: each output
    column contracts the same replicated d-axis)."""
    B, T, _ = x.shape
    Dh = cfg.head_dim
    h = gpt_mod.layer_norm(x, w["ln1_scale"], w["ln1_bias"],
                           cfg.layer_norm_eps)
    qkv = jnp.einsum("btd,dce->btce", h, w["qkv_w"]) + w["qkv_b"]
    H_loc = qkv.shape[-1] // Dh
    q = qkv[:, :, 0].reshape(B, T, H_loc, Dh)
    k_ = qkv[:, :, 1].reshape(B, T, H_loc, Dh)
    v = qkv[:, :, 2].reshape(B, T, H_loc, Dh)
    return h, q, k_, v


def _maybe_rope(cfg, q, k_, positions):
    if cfg.rotary:
        rd = int(cfg.rotary_pct * cfg.head_dim)
        rd -= rd % 2
        q = gpt_mod._rope(q, positions, rd, cfg.rotary_interleaved)
        k_ = gpt_mod._rope(k_, positions, rd, cfg.rotary_interleaved)
    return q, k_


def _softmax_scale(cfg):
    return (cfg.attention_scale if cfg.attention_scale is not None
            else 1.0 / np.sqrt(cfg.head_dim))


def _out_proj_partial(x_dtype, attn, w):
    """Row-split attention output projection: local heads contribute a
    PARTIAL [B,T,D] sum; caller psums and adds the replicated bias."""
    B, T = attn.shape[0], attn.shape[1]
    return jnp.einsum("bte,ed->btd",
                      attn.reshape(B, T, -1).astype(x_dtype),
                      w["attn_out_w"])


def _mlp_partial(cfg, x, w):
    """Col-split up / row-split down MLP: returns the PARTIAL [B,T,D] delta
    (no bias — added post-psum by the caller)."""
    h = gpt_mod.layer_norm(x, w["ln2_scale"], w["ln2_bias"],
                           cfg.layer_norm_eps)
    h = h @ w["mlp_up_w"] + w["mlp_up_b"]
    h = gpt_mod._act(cfg, h)
    return h @ w["mlp_down_w"]


def _attn_paged_local(cfg, x, w, k_pages, v_pages, tables, lengths, impl,
                      k_scales, v_scales):
    """Shard-local single-token paged attention (gpt._paged_attn_sublayer
    over the local head slice): appends into the local pool shard and
    returns the PARTIAL out-projection, not the residual."""
    from ...ops.pallas.decode_attention import paged_decode_attention

    B = x.shape[0]
    Dh = cfg.head_dim
    ps = k_pages.shape[2]
    _, q, k_, v = _local_qkv(cfg, x, w)
    positions = lengths[:, None]
    q, k_ = _maybe_rope(cfg, q, k_, positions)
    page = jnp.take_along_axis(tables, (lengths // ps)[:, None],
                               axis=1)[:, 0]
    off = lengths % ps
    quantized = k_scales is not None
    if not quantized:
        dt = k_pages.dtype
        k_pages = k_pages.at[:, page, off, :].set(
            k_[:, 0].astype(dt).transpose(1, 0, 2))
        v_pages = v_pages.at[:, page, off, :].set(
            v[:, 0].astype(dt).transpose(1, 0, 2))
    else:
        bits = 4 if k_pages.shape[-1] * 2 == Dh else 8
        k_pages, k_scales = gpt_mod._append_kv_token(
            k_pages, k_scales,
            k_[:, 0].transpose(1, 0, 2).astype(jnp.float32), page, off, bits)
        v_pages, v_scales = gpt_mod._append_kv_token(
            v_pages, v_scales,
            v[:, 0].transpose(1, 0, 2).astype(jnp.float32), page, off, bits)
    qdt = x.dtype if quantized else k_pages.dtype
    attn = paged_decode_attention(q.astype(qdt), k_pages, v_pages,
                                  lengths + 1, tables,
                                  softmax_scale=_softmax_scale(cfg),
                                  impl=impl, k_scales=k_scales,
                                  v_scales=v_scales)
    partial = _out_proj_partial(x.dtype, attn, w)
    return partial, k_pages, v_pages, k_scales, v_scales


def _attn_verify_local(cfg, x, w, k_pages, v_pages, tables, lengths, impl,
                       k_scales, v_scales):
    """Shard-local speculation-window attention (gpt._paged_verify_sublayer
    over the local head slice). Pool is read-only; returns the partial
    out-projection plus the local win_k/win_v [B, W, H_loc, Dh]."""
    from ...ops.pallas.decode_attention import paged_verify_attention

    _, q, k_, v = _local_qkv(cfg, x, w)
    W = x.shape[1]
    positions = lengths[:, None] + jnp.arange(W)[None, :]
    q, k_ = _maybe_rope(cfg, q, k_, positions)
    quantized = k_scales is not None
    qdt = x.dtype if quantized else k_pages.dtype
    attn = paged_verify_attention(q.astype(qdt), k_pages, v_pages, lengths,
                                  tables, k_, v,
                                  softmax_scale=_softmax_scale(cfg),
                                  impl=impl, k_scales=k_scales,
                                  v_scales=v_scales)
    return _out_proj_partial(x.dtype, attn, w), k_, v


def _attn_dense_local(cfg, x, w, k_cache, v_cache, pos):
    """Shard-local prefill attention over the dense cache slice
    [B, H_loc, S, Dh] (gpt.attn_with_cache's masked-softmax path — also
    what tp1 prefill compiles to, so per-head values match bitwise)."""
    S = k_cache.shape[2]
    B, T, _ = x.shape
    _, q, k_, v = _local_qkv(cfg, x, w)
    positions = pos + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    q, k_ = _maybe_rope(cfg, q, k_, positions)
    k_cache = lax.dynamic_update_slice(
        k_cache, k_.transpose(0, 2, 1, 3).astype(k_cache.dtype),
        (0, 0, pos, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, v.transpose(0, 2, 1, 3).astype(v_cache.dtype),
        (0, 0, pos, 0))
    logits = jnp.einsum("bthd,bhsd->bhts", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * _softmax_scale(cfg)
    s_idx = jnp.arange(S)[None, :]
    t_idx = positions[:, :, None]
    mask = s_idx <= t_idx  # [B, T, S]
    logits = jnp.where(mask[:, None, :, :], logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("bhts,bhsd->bthd", probs.astype(v_cache.dtype), v_cache)
    return _out_proj_partial(x.dtype, attn, w), k_cache, v_cache


def _residual(cfg, x, attn_partial, mlp_partial_fn, w):
    """Close a block: psum the partial deltas and add replicated biases.

    ``parallel_residual`` (NeoX/GPT-J) reads the MLP off the pre-attention
    stream, so both partials fold into ONE psum; the sequential residual
    needs the attention psum to complete before LN2 reads the combined
    stream (two psums — the Megatron block shape)."""
    if cfg.parallel_residual:
        delta = lax.psum(attn_partial + mlp_partial_fn(x), TP_AXIS)
        return x + delta + w["attn_out_b"] + w["mlp_down_b"]
    y = x + lax.psum(attn_partial, TP_AXIS) + w["attn_out_b"]
    return y + lax.psum(mlp_partial_fn(y), TP_AXIS) + w["mlp_down_b"]


def _embed(cfg, params, ids, positions):
    x = jnp.take(params["wte"], ids, axis=0)
    if not cfg.rotary and not cfg.alibi:
        x = x + jnp.take(params["wpe"], positions + cfg.pos_offset, axis=0)
    if cfg.embed_layernorm:
        x = gpt_mod.layer_norm(x, params["emb_ln_scale"],
                               params["emb_ln_bias"], cfg.layer_norm_eps)
    return x.astype(params["blocks"]["qkv_w"].dtype)


def _head_logits(cfg, params, x):
    x = gpt_mod.layer_norm(x, params["lnf_scale"], params["lnf_bias"],
                           cfg.layer_norm_eps)
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    if cfg.lm_head_bias and not cfg.tie_embeddings:
        logits = logits + params["lm_head_b"].astype(logits.dtype)
    return logits


def _kv_xs(paged_cache):
    kv_q = "k_scales" in paged_cache
    if kv_q:
        return (paged_cache["k_pages"], paged_cache["v_pages"],
                paged_cache["k_scales"], paged_cache["v_scales"]), True
    return (paged_cache["k_pages"], paged_cache["v_pages"]), False


def _kv_dict(new_kv, kv_q):
    out = {"k_pages": new_kv[0], "v_pages": new_kv[1]}
    if kv_q:
        out["k_scales"], out["v_scales"] = new_kv[2], new_kv[3]
    return out


def _tp_specs(paged_cache):
    cache_specs = {k: (P(None, TP_AXIS, None)
                       if k in ("k_scales", "v_scales")
                       else P(None, TP_AXIS, None, None, None))
                   for k in paged_cache}
    win_spec = P(None, None, None, TP_AXIS, None)
    return cache_specs, win_spec


def _param_specs_impl(params):
    """Specs for an already-reshaped tp param tree (module-level twin of
    ``TPContext.param_specs`` so the program builders need no context
    object — only a mesh)."""
    block_specs = {
        "qkv_w": P(None, None, None, TP_AXIS),
        "qkv_b": P(None, None, TP_AXIS),
        "attn_out_w": P(None, TP_AXIS, None),
        "mlp_up_w": P(None, None, TP_AXIS),
        "mlp_up_b": P(None, TP_AXIS),
        "mlp_down_w": P(None, TP_AXIS, None),
    }
    specs = {}
    for key, leaf in params.items():
        if key == "blocks":
            specs["blocks"] = {
                k: block_specs.get(k, P(*([None] * jnp.ndim(leaf[k]))))
                for k in leaf}
        else:
            specs[key] = P(*([None] * jnp.ndim(leaf)))
    return specs


# ----------------------------------------------------------- full programs
def tp_paged_decode_step(cfg, params, input_ids, paged_cache, block_tables,
                         lengths, mesh: Mesh,
                         impl: Optional[str] = None
                         ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """tp-sharded :func:`gpt.paged_decode_step`: logits [B, V] replicated,
    pool shards updated in place on their own chips."""
    ids = jnp.asarray(input_ids)
    if ids.ndim == 1:
        ids = ids[:, None]
    lengths = jnp.asarray(lengths, jnp.int32)
    tables = jnp.asarray(block_tables, jnp.int32)
    cache_specs, _ = _tp_specs(paged_cache)
    pspecs = _param_specs_impl(params)
    kv_q = "k_scales" in paged_cache

    def body(params, paged, ids, tables, lengths):
        x = _embed(cfg, params, ids, lengths[:, None])

        def step(carry, layer_in):
            x, i = carry
            layer_w, kv = layer_in[0], layer_in[1:]
            k_s, v_s = (kv[2], kv[3]) if kv_q else (None, None)
            partial, k_p, v_p, k_s, v_s = _attn_paged_local(
                cfg, x, layer_w, kv[0], kv[1], tables, lengths, impl,
                k_s, v_s)
            y = _residual(cfg, x, partial,
                          lambda h: _mlp_partial(cfg, h, layer_w), layer_w)
            out_kv = (k_p, v_p, k_s, v_s) if kv_q else (k_p, v_p)
            return (y, i + 1), out_kv

        xs, _ = _kv_xs(paged)
        (x, _), new_kv = lax.scan(step, (x, jnp.int32(0)),
                                  (params["blocks"],) + xs)
        logits = _head_logits(cfg, params, x)
        return logits[:, 0, :], _kv_dict(new_kv, kv_q)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(pspecs, cache_specs, P(), P(), P()),
                   out_specs=(P(), cache_specs),
                   check_vma=False)
    return fn(params, paged_cache, ids, tables, lengths)


def tp_paged_verify_step(cfg, params, window_ids, paged_cache, block_tables,
                         lengths, mesh: Mesh,
                         impl: Optional[str] = None
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """tp-sharded :func:`gpt.paged_verify_step`: logits [B, W, V] replicated,
    win_k/win_v [L, B, W, H, Dh] sharded over the head axis (they feed
    straight into :func:`tp_commit_window_kv`, which is sharded the same
    way — the window K/V never leave their chips)."""
    ids = jnp.asarray(window_ids)
    lengths = jnp.asarray(lengths, jnp.int32)
    tables = jnp.asarray(block_tables, jnp.int32)
    cache_specs, win_spec = _tp_specs(paged_cache)
    pspecs = _param_specs_impl(params)
    kv_q = "k_scales" in paged_cache

    def body(params, paged, ids, tables, lengths):
        W = ids.shape[1]
        positions = lengths[:, None] + jnp.arange(W)[None, :]
        x = _embed(cfg, params, ids, positions)

        def step(carry, layer_in):
            x, i = carry
            layer_w, kv = layer_in[0], layer_in[1:]
            k_s, v_s = (kv[2], kv[3]) if kv_q else (None, None)
            partial, wk, wv = _attn_verify_local(
                cfg, x, layer_w, kv[0], kv[1], tables, lengths, impl,
                k_s, v_s)
            y = _residual(cfg, x, partial,
                          lambda h: _mlp_partial(cfg, h, layer_w), layer_w)
            return (y, i + 1), (wk, wv)

        xs, _ = _kv_xs(paged)
        (x, _), (win_k, win_v) = lax.scan(step, (x, jnp.int32(0)),
                                          (params["blocks"],) + xs)
        return _head_logits(cfg, params, x), win_k, win_v

    fn = shard_map(body, mesh=mesh,
                   in_specs=(pspecs, cache_specs, P(), P(), P()),
                   out_specs=(P(), win_spec, win_spec),
                   check_vma=False)
    return fn(params, paged_cache, ids, tables, lengths)


def tp_commit_window_kv(paged_cache, win_k, win_v, block_tables, lengths,
                        n_commit, mesh: Mesh) -> Dict[str, jnp.ndarray]:
    """Head-sharded :func:`gpt.commit_window_kv`: the accepted-prefix
    scatter is per-head independent and collective-free, so the unmodified
    writer runs on each shard's local pool + window slice."""
    cache_specs, win_spec = _tp_specs(paged_cache)
    fn = shard_map(gpt_mod.commit_window_kv, mesh=mesh,
                   in_specs=(cache_specs, win_spec, win_spec, P(), P(), P()),
                   out_specs=cache_specs,
                   check_vma=False)
    return fn(paged_cache, win_k, win_v,
              jnp.asarray(block_tables, jnp.int32),
              jnp.asarray(lengths, jnp.int32),
              jnp.asarray(n_commit, jnp.int32))


def tp_write_prompt_kv_batch(paged_cache, dense_cache, block_tables, lengths,
                             starts, mesh: Mesh) -> Dict[str, jnp.ndarray]:
    """Head-sharded :func:`gpt.write_prompt_kv_batch` (prefill-to-pool
    scatter, including the quantized per-page absmax path — all per-head,
    collective-free)."""
    cache_specs, _ = _tp_specs(paged_cache)
    dspec = {"k": P(None, None, TP_AXIS, None, None),
             "v": P(None, None, TP_AXIS, None, None)}

    def body(paged, dense, tables, lengths, starts):
        return gpt_mod.write_prompt_kv_batch(paged, dense, tables, lengths,
                                             starts=starts)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(cache_specs, dspec, P(), P(), P()),
                   out_specs=cache_specs,
                   check_vma=False)
    dense = {"k": dense_cache["k"], "v": dense_cache["v"]}
    return fn(paged_cache, dense,
              jnp.asarray(block_tables, jnp.int32),
              jnp.asarray(lengths, jnp.int32),
              jnp.asarray(starts, jnp.int32))


def tp_write_prompt_kv(paged_cache, dense_cache, block_table, length, start,
                       mesh: Mesh, row: int = 0) -> Dict[str, jnp.ndarray]:
    """Single-request :func:`tp_write_prompt_kv_batch` over ``dense_cache``
    row ``row`` (mirrors :func:`gpt.write_prompt_kv`)."""
    one = {"k": dense_cache["k"][:, row:row + 1],
           "v": dense_cache["v"][:, row:row + 1]}
    return tp_write_prompt_kv_batch(
        paged_cache, one, jnp.asarray(block_table, jnp.int32)[None],
        jnp.asarray(length, jnp.int32)[None],
        jnp.asarray(start, jnp.int32)[None], mesh)


def tp_forward_with_cache(cfg, params, input_ids, cache, mesh: Mesh
                          ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """tp-sharded :func:`gpt.forward_with_cache` (the prefill program):
    dense cache sharded over heads, logits [B, T, V] replicated."""
    ids = jnp.asarray(input_ids)
    pspecs = _param_specs_impl(params)
    cspec = P(None, None, TP_AXIS, None, None)

    def body(params, ids, k_cache, v_cache, pos):
        B, T = ids.shape
        positions = pos + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = _embed(cfg, params, ids, positions)

        def step(carry, layer_in):
            x, i = carry
            layer_w, k_c, v_c = layer_in
            partial, k_c, v_c = _attn_dense_local(cfg, x, layer_w,
                                                  k_c, v_c, pos)
            y = _residual(cfg, x, partial,
                          lambda h: _mlp_partial(cfg, h, layer_w), layer_w)
            return (y, i + 1), (k_c, v_c)

        (x, _), (new_k, new_v) = lax.scan(
            step, (x, jnp.int32(0)), (params["blocks"], k_cache, v_cache))
        return _head_logits(cfg, params, x), new_k, new_v

    fn = shard_map(body, mesh=mesh,
                   in_specs=(pspecs, P(), cspec, cspec, P()),
                   out_specs=(P(), cspec, cspec),
                   check_vma=False)
    logits, new_k, new_v = fn(params, ids, cache["k"], cache["v"],
                              cache["pos"])
    return logits, {"k": new_k, "v": new_v,
                    "pos": cache["pos"] + ids.shape[1]}
