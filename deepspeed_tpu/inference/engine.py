"""Inference engine.

Capability parity with the reference's ``InferenceEngine`` (``inference/engine.py:33``):
dtype conversion, tensor-parallel sharding, a patched ``generate`` with KV caching,
and CUDA-graph-style replay. TPU-native mapping:

- **kernel injection** (``module_inject/replace_module.py:302``): unnecessary as
  module surgery — models are functional; the "injected" fast path is the jitted
  decode step whose ops XLA/Pallas fuse. The policy/container machinery collapses
  into per-model adapters (:func:`for_gpt` here; HF import adapters live in
  ``models/``).
- **AutoTP** (``module_inject/auto_tp.py:7``): the model's Megatron-style
  ``partition_specs`` shard every Linear over the ``tp`` mesh axis; XLA places the
  two all-reduces per block that AutoTP inserts by hand.
- **CUDA graphs** (``inference/engine.py:467-495``): the decode step is compiled
  once for a fixed [batch, 1] shape and replayed — XLA's compiled executable *is*
  the captured graph.
- **KV cache** (``inference_context.h``): a pytree of [L, B, H, S, Dh] arrays in
  HBM (see ``models/gpt.py::init_cache``), sharded over ``tp`` on the head axis.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import gpt as gpt_mod
from ..runtime.topology import MeshTopology, mesh_context
from ..utils.logging import log_dist
from .config import DeepSpeedInferenceConfig


class InferenceEngine:
    """Fixed-shape, AOT-compiled autoregressive inference over a TP mesh.

    ``model`` is an adapter object exposing:
      - ``params``: parameter pytree (any dtype; converted per config)
      - ``prefill(params, input_ids, cache) -> (logits, cache)``
      - ``init_cache(batch, max_len, dtype) -> cache``
      - ``partition_specs(param_shapes)`` (optional, for TP)
    Use :func:`for_gpt` to wrap a GPT config + params.
    """

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 topology: Optional[MeshTopology] = None):
        self.config = config or DeepSpeedInferenceConfig()
        tp = self.config.tensor_parallel.tp_size
        ep = self.config.moe.ep_size  # expert-parallel decode (moe{ep_size})
        self.topo = topology or MeshTopology.create(tp=tp, ep=ep)
        self.mesh = self.topo.mesh
        self.model = model
        self.dtype = self.config.jax_dtype()
        self._decode_fns: Dict[Tuple, Callable] = {}
        self._profile_model_time = False
        self._model_times = []
        # compiled-program cache misses, in order (the evidence stream the
        # serving/unbucketed-decode-shape dslint rule audits)
        self.compile_log = []
        self.monitor = None

        # dtype conversion + TP placement (parity: engine init flow :38-150).
        # Quantized {"q"/"q4","s"} leaves pass through whole: the int8/int4
        # payload must not be float-cast and the scales stay fp32.
        from ..models.gpt import _is_qleaf

        def _cast(x):
            if _is_qleaf(x):
                return x
            return (x.astype(self.dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x)

        params = jax.tree_util.tree_map(_cast, model.params,
                                        is_leaf=_is_qleaf)
        # params may arrive ALREADY quantized (the host-streamed big-model
        # init — models/gpt.init_quantized_decode_params): treat exactly like
        # the per-layer quant path, never re-quantize
        pre_quantized = any(
            isinstance(leaf, dict) and _is_qleaf(leaf)
            for leaf in jax.tree_util.tree_leaves(params, is_leaf=_is_qleaf))

        # int8 weight-only quantization (parity: GroupQuantizer,
        # module_inject/replace_module.py:144). Preferred path: the model
        # quantizes its own layer stack ({"q","s"} leaves) and dequantizes ONE
        # layer inside the decode scan — peak HBM holds int8 weights + a single
        # layer's compute-dtype copy. Fallback (models without quantize_params):
        # whole-tree dequant inside the compiled fn (storage-only savings).
        self._quant_scales = None
        self._per_layer_quant = False
        if pre_quantized:
            self._per_layer_quant = True
            log_dist("inference engine: pre-quantized layer-stack weights "
                     "(host-streamed init), in-scan per-layer dequant")
        elif self.config.quant.enabled and hasattr(model, "quantize_params"):
            params = model.quantize_params(
                params, bits=self.config.quant.bits,
                group_size=self.config.quant.group_size)
            self._per_layer_quant = True
            log_dist(f"inference engine: int{self.config.quant.bits} layer-stack "
                     "weights, in-scan per-layer dequant")
        elif self.config.quant.enabled:
            from ..compression import quantize_params_for_inference

            params, scales, meta = quantize_params_for_inference(
                params, bits=self.config.quant.bits,
                group_size=self.config.quant.group_size)
            self._quant_scales = scales
            log_dist(f"inference engine: int{self.config.quant.bits} weights for "
                     f"{len(meta['quantized'])} tensors")

        shapes = jax.eval_shape(lambda: params)
        specs = model.partition_specs(shapes) if hasattr(model, "partition_specs") else None
        if specs is None and tp > 1:
            if self._per_layer_quant:
                # AutoTP's shape heuristics don't understand {"q","s"} leaves;
                # replicating silently would waste tp x HBM — fail loudly
                raise ValueError(
                    "per-layer int8 quantization with tp>1 requires the model "
                    "to provide partition_specs (AutoTP cannot infer sharding "
                    "for quantized {'q','s'} leaves)")
            # AutoTP: infer Megatron-style specs for unknown trees
            # (parity: module_inject/auto_tp.py:7)
            from ..module_inject import auto_tp_specs

            specs = auto_tp_specs(params, tp_size=tp)
            log_dist("inference engine: AutoTP-inferred tensor-parallel sharding")
        if specs is not None:
            if self._per_layer_quant:
                from ..models.gpt import quantized_partition_specs

                specs = quantized_partition_specs(params, specs)
            specs = self._sanitize_specs(params, specs)

        if specs is not None:
            self.params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)), params, specs)
        else:
            self.params = jax.device_put(params, NamedSharding(self.mesh, P()))
        log_dist(f"inference engine: dtype {self.dtype}, tp={tp}, "
                 f"max_out_tokens={self.config.max_out_tokens}")

    def _sanitize_specs(self, params, specs):
        """Drop mesh axes from dims they don't divide (e.g. an odd vocab over
        tp=2) — the same indivisibility guard AutoTP applies to inferred specs,
        extended to model-provided ones so imported checkpoints with unfriendly
        shapes still place (replicating just the offending dims)."""

        def fix(x, spec):
            out = []
            for dim, names in enumerate(tuple(spec)):
                if names is None:
                    out.append(None)
                    continue
                tup = names if isinstance(names, tuple) else (names,)
                extent = int(np.prod([self.mesh.shape[n] for n in tup]))
                if dim < x.ndim and extent and x.shape[dim] % extent == 0:
                    out.append(names)
                else:
                    out.append(None)
            return P(*out)

        return jax.tree_util.tree_map(
            fix, params, specs, is_leaf=lambda s: isinstance(s, P))

    def _materialize(self, params):
        """Inside-jit dequantization of int8 leaves back to compute dtype."""
        if self._quant_scales is None:
            return params
        from ..ops.quantizer import dequantize

        flat, treedef = jax.tree_util.tree_flatten(params)
        out = [leaf if s is None else dequantize(leaf, s, dtype=self.dtype)
               for leaf, s in zip(flat, self._quant_scales)]
        return jax.tree_util.tree_unflatten(treedef, out)

    def set_monitor(self, monitor) -> None:
        """Attach a ``MonitorMaster``-like sink for compile events."""
        self.monitor = monitor

    def _log_compile(self, kind: str, shape: Tuple[int, ...]) -> None:
        if not self.config.log_compile_events:
            return
        from .serving.buckets import record_compile

        record_compile(self.compile_log, self.monitor,
                       "Inference/compile_events", kind, shape,
                       hint="repeated shape misses on a hot path? consider "
                            "decode_buckets")

    def _bucket_max_new(self, max_new: int) -> int:
        """Round max_new up to the configured decode bucket (serving shape
        buckets) so repeat shapes hit the compiled-fn cache; callers slice
        generated output back to the requested length."""
        if not self.config.decode_buckets:
            return max_new
        from .serving.buckets import bucket_for

        return bucket_for(max_new, self.config.decode_buckets)

    def profile_model_time(self, use_cuda_events: bool = False) -> None:
        """Parity: ``inference/engine.py:151``."""
        self._profile_model_time = True

    def model_times(self):
        times, self._model_times = self._model_times, []
        return times

    # ------------------------------------------------------------------ forward
    def forward(self, input_ids) -> jnp.ndarray:
        """One full forward (prefill shapes); returns logits."""
        input_ids = jnp.asarray(input_ids)
        t0 = time.perf_counter()
        logits = self._get_prefill_fn(input_ids.shape)(self.params, input_ids)
        if self._profile_model_time:
            jax.block_until_ready(logits)
            self._model_times.append(time.perf_counter() - t0)
        return logits

    __call__ = forward

    def _get_prefill_fn(self, shape):
        key = ("prefill", shape)
        if key not in self._decode_fns:
            self._log_compile("prefill", shape)

            def fn(params, ids):
                params = self._materialize(params)
                cache = self.model.init_cache(shape[0], shape[1], self.dtype)
                logits, _ = self.model.prefill(params, ids, cache)
                return logits

            self._decode_fns[key] = jax.jit(fn)
        return self._decode_fns[key]

    # ------------------------------------------------------------------ generate
    def generate(self, input_ids, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
                 num_beams: int = 1, repetition_penalty: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0) -> np.ndarray:
        """Autoregressive generation with KV cache; greedy when temperature==0,
        else categorical with optional top-k and/or nucleus (top-p) filtering;
        ``num_beams > 1`` runs deterministic beam search (the HF-generate
        capability the reference reaches by patching modules under HF's loop).
        Parity: the patched ``generate`` + per-token decode hot loop
        (``inference/engine.py:537``)."""
        input_ids = jnp.asarray(input_ids)
        B, T = input_ids.shape
        if self.config.max_batch_size and B > self.config.max_batch_size:
            raise ValueError(
                f"batch {B} exceeds max_batch_size "
                f"{self.config.max_batch_size} (the workspace bound the "
                f"engine was configured for)")
        max_new = (self.config.max_out_tokens if max_new_tokens is None
                   else max_new_tokens)
        if max_new < self.config.min_out_tokens:
            raise ValueError(
                f"max_new_tokens {max_new} < min_out_tokens "
                f"{self.config.min_out_tokens}")
        requested = max_new
        max_new = self._bucket_max_new(max_new)
        key = jax.random.PRNGKey(seed)
        eos = -1 if eos_token_id is None else eos_token_id
        if num_beams > 1:
            if temperature != 0.0 or top_k or top_p or repetition_penalty != 1.0:
                raise ValueError("beam search is deterministic; sampling "
                                 "knobs cannot combine with num_beams > 1")
            gen_key = (B, T, max_new, "beam", num_beams, eos)
            if gen_key not in self._decode_fns:
                self._log_compile("generate_beam", (B, T, max_new))
                self._decode_fns[gen_key] = self._build_beam_fn(
                    B, T, max_new, num_beams, eos)
        else:
            gen_key = (B, T, max_new, temperature, top_k, top_p,
                       repetition_penalty, eos)
            if gen_key not in self._decode_fns:
                self._log_compile("generate", (B, T, max_new))
                self._decode_fns[gen_key] = self._build_generate_fn(*gen_key)
        fn = self._decode_fns[gen_key]
        t0 = time.perf_counter()
        with mesh_context(self.mesh):
            out = fn(self.params, input_ids, key)
        out = np.asarray(jax.device_get(out))
        if max_new != requested:  # bucket padding: slice back
            out = out[:, :T + requested]
        if self._profile_model_time:
            self._model_times.append(time.perf_counter() - t0)
        return out

    def _build_generate_fn(self, B: int, T: int, max_new: int, temperature: float,
                           top_k: int, top_p: float,
                           repetition_penalty: float, eos: int):
        model = self.model
        dtype = self.dtype
        # cache sequence axis padded to a 128-multiple so the Pallas decode
        # kernel's (block_k, Dh) tiles stay sublane-aligned; the validity mask
        # makes the padding inert
        total = -(-(T + max_new) // 128) * 128

        def penalize(logits, seen):
            # CTRL-style repetition penalty: seen tokens' logits shrink
            # toward improbability (divide if positive, multiply if negative)
            if repetition_penalty == 1.0:
                return logits
            p = repetition_penalty
            pen = jnp.where(logits > 0, logits / p, logits * p)
            return jnp.where(seen, pen, logits)

        def sample(logits, key):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1)
            logits = logits / temperature
            if top_k > 0:
                kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if 0.0 < top_p < 1.0:
                # nucleus: keep the smallest prefix of the sorted distribution
                # whose mass reaches top_p (the kept set always includes the
                # top token)
                desc = jnp.sort(logits, axis=-1)[..., ::-1]
                probs = jax.nn.softmax(desc, axis=-1)
                exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
                kept = jnp.where(exclusive_cum >= top_p, jnp.inf, desc)
                thr = jnp.min(kept, axis=-1, keepdims=True)
                logits = jnp.where(logits < thr, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1)

        def fn(params, input_ids, key):
            params = self._materialize(params)
            cache = model.init_cache(B, total, dtype)
            logits, cache = model.prefill(params, input_ids, cache)
            V = logits.shape[-1]
            seen = jnp.zeros((B, V), bool)
            if repetition_penalty != 1.0:
                seen = seen.at[jnp.arange(B)[:, None], input_ids].set(True)
            next_tok = sample(penalize(logits[:, -1, :], seen), key)
            seen = seen.at[jnp.arange(B), next_tok].set(True)
            done = (next_tok == eos)

            def body(carry, step_key):
                cache, tok, done, seen = carry
                logits, cache = model.prefill(params, tok[:, None], cache)
                nxt = sample(penalize(logits[:, -1, :], seen), step_key)
                nxt = jnp.where(done, tok, nxt)  # freeze finished rows
                seen = seen.at[jnp.arange(B), nxt].set(True)
                done = done | (nxt == eos)
                return (cache, nxt, done, seen), nxt

            if max_new > 1:
                keys = jax.random.split(key, max_new - 1)
                (_, _, _, _), toks = jax.lax.scan(
                    body, (cache, next_tok, done, seen), keys)
                gen = jnp.concatenate([next_tok[:, None], toks.T], axis=1)
            else:
                gen = next_tok[:, None]
            return jnp.concatenate([input_ids, gen], axis=1)

        if self.config.enable_cuda_graph:
            return jax.jit(fn)  # compiled executable == captured graph
        return fn

    def _build_beam_fn(self, B: int, T: int, max_new: int, K: int, eos: int):
        """Deterministic beam search as one compiled scan: K beams per row
        share one [B*K]-row KV cache, reordered along the batch axis by a
        gather at every step; finished beams continue on a zero-cost eos
        lane. Returns the highest-scoring beam per row, same [B, T+max_new]
        contract as the sampling path."""
        model = self.model
        dtype = self.dtype
        total = -(-(T + max_new) // 128) * 128

        def fn(params, input_ids, key):
            del key  # beam search is deterministic
            params = self._materialize(params)
            ids_rep = jnp.repeat(input_ids, K, axis=0)  # [B*K, T]
            cache = model.init_cache(B * K, total, dtype)
            logits, cache = model.prefill(params, ids_rep, cache)
            V = logits.shape[-1]
            logp = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32)).reshape(B, K, V)
            # beams are identical after prefill: diversify on the FIRST step
            # by taking the row's top-K tokens
            scores, toks = jax.lax.top_k(logp[:, 0, :], K)  # [B, K]
            done = toks == eos
            out = jnp.zeros((B, K, max_new), jnp.int32).at[:, :, 0].set(toks)
            eos_lane = jnp.full((V,), -jnp.inf,
                                jnp.float32).at[eos].set(0.0)

            def body(carry, t):
                cache, scores, toks, done, out = carry
                logits, cache = model.prefill(params, toks.reshape(B * K, 1),
                                              cache)
                logp = jax.nn.log_softmax(
                    logits[:, -1, :].astype(jnp.float32)).reshape(B, K, V)
                logp = jnp.where(done[:, :, None], eos_lane[None, None, :],
                                 logp)
                flat = (scores[:, :, None] + logp).reshape(B, K * V)
                new_scores, idx = jax.lax.top_k(flat, K)
                src = idx // V   # which beam each winner extends
                tok = idx % V
                rows = (jnp.arange(B)[:, None] * K + src).reshape(-1)
                cache = jax.tree_util.tree_map(
                    lambda a: (jnp.take(a, rows, axis=1)
                               if a.ndim >= 2 and a.shape[1] == B * K else a),
                    cache)
                out = jnp.take_along_axis(out, src[:, :, None], axis=1)
                out = out.at[:, :, t].set(tok)
                done = jnp.take_along_axis(done, src, axis=1) | (tok == eos)
                return (cache, new_scores, tok, done, out), None

            if max_new > 1:
                (cache, scores, toks, done, out), _ = jax.lax.scan(
                    body, (cache, scores, toks, done, out),
                    jnp.arange(1, max_new))
            best = jnp.argmax(scores, axis=1)
            seq = jnp.take_along_axis(out, best[:, None, None], axis=1)[:, 0]
            return jnp.concatenate([input_ids, seq], axis=1)

        if self.config.enable_cuda_graph:
            return jax.jit(fn)
        return fn


class _GPTInferenceAdapter:
    def __init__(self, cfg: gpt_mod.GPTConfig, params):
        self.cfg = cfg
        self.params = params

    def init_cache(self, batch: int, max_len: int, dtype):
        return gpt_mod.init_cache(self.cfg, batch, max_len, dtype)

    def prefill(self, params, input_ids, cache):
        return gpt_mod.forward_with_cache(self.cfg, params, input_ids, cache)

    def partition_specs(self, shapes):
        return gpt_mod.partition_specs(self.cfg, shapes)

    def quantize_params(self, params, bits: int, group_size: int):
        return gpt_mod.quantize_for_inference(self.cfg, params, bits=bits,
                                              group_size=group_size)


def for_gpt(cfg: gpt_mod.GPTConfig, params) -> _GPTInferenceAdapter:
    """Adapter: GPT config + trained params -> InferenceEngine model."""
    return _GPTInferenceAdapter(cfg, params)


class _GPTMoEInferenceAdapter:
    """Expert-parallel generate: the MoE cached forward dispatches tokens over
    the ``ep`` mesh axis inside every decode step (parity: the reference's MoE
    inference layer, ``ops/transformer/inference/moe_inference.py``)."""

    def __init__(self, cfg, params):
        self.cfg = cfg
        self.params = params

    def init_cache(self, batch: int, max_len: int, dtype):
        from ..models import gpt_moe

        return gpt_moe.init_cache(self.cfg, batch, max_len, dtype)

    def prefill(self, params, input_ids, cache):
        from ..models import gpt_moe

        return gpt_moe.forward_with_cache(self.cfg, params, input_ids, cache)

    def partition_specs(self, shapes):
        from ..models import gpt_moe

        return gpt_moe.partition_specs(self.cfg, shapes)


def for_gpt_moe(cfg, params) -> _GPTMoEInferenceAdapter:
    """Adapter: GPT-MoE config + trained params -> InferenceEngine model."""
    return _GPTMoEInferenceAdapter(cfg, params)
