"""Fleet bench driver: the open-loop workload clock over a ReplicaRouter.

Mirror of :func:`~..serving.bench.run_continuous`, sharing its report
schema (``_report``) so a fleet run and a single-replica run score against
the same SLO with identical accounting — the fleet overload bench row
(``bench.py`` kind ``serving_fleet``) is an honest A/B.

``on_step(router, produced_total)`` is the chaos hook: the replica-kill
bench variant uses it to SIGKILL/kill one replica mid-stream at a
deterministic point in the token trajectory.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

from ..serving.bench import _report
from ..serving.scheduler import Request
from .autoscale import FleetAutoscaler
from .router import ReplicaRouter


def run_fleet(router: ReplicaRouter, workload: Sequence[Request],
              max_wall_s: float = 600.0, slo_s: Optional[float] = None,
              on_step: Optional[Callable[[ReplicaRouter, int], None]] = None,
              autoscaler: Optional[FleetAutoscaler] = None) -> Dict:
    """Drive the router under the workload's arrival clock; fleet-level
    rejections are terminal (scored as shed). Returns the shared report
    schema plus fleet extras (replica counts, re-routes, survivor audit)."""
    pending = sorted(workload, key=lambda r: r.arrival_time)
    t0 = time.monotonic()
    i = 0
    produced_total = 0
    try:
        while i < len(pending) or not router.idle:
            now = time.monotonic() - t0
            if now > max_wall_s:
                break
            while i < len(pending) and pending[i].arrival_time <= now:
                router.submit(pending[i])
                i += 1
            if router.idle:
                if i < len(pending):
                    time.sleep(min(max(pending[i].arrival_time - now, 0.0),
                                   0.25))
                continue
            produced_total += router.step()
            if on_step is not None:
                on_step(router, produced_total)
            if autoscaler is not None:
                autoscaler.tick()
    finally:
        audit = router.audit_survivors()
    t_end = time.monotonic()
    return _report(workload, t0, t_end, "fleet", slo_s=slo_s, extra={
        "replicas_live": len(router.live_replicas),
        "replicas_dead": len(router.dead),
        "replicas_retired": len(router.retired),
        "reroutes": router.counters.get("request_rerouted", 0),
        "fleet_rejects": router.counters.get("fleet_reject", 0),
        "fleet_counters": dict(router.counters),
        "fleet_audit_ok": bool(audit["ok"]),
    })


__all__ = ["run_fleet"]
