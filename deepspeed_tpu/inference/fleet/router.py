"""Replica router: fleet-level placement, backpressure, and failover.

One scheduler+engine pair is one replica; this router fronts N of them
(docs/SERVING.md "Fleet"). Responsibilities, in the order they matter:

- **placement** — least-loaded scoring over each replica's ``load()``
  snapshot (queued + running work tokens, refreshed on every pump), with
  **session affinity**: requests sharing a ``session_id`` stick to the
  replica that served the session last, so its copy-on-write prefix pages
  (PR 8) stay hot. Affinity *spills on pressure*: a sticky replica
  answering ``queue_full``/``token_backlog`` loses the request (and the
  session re-sticks wherever it lands) instead of queueing behind its own
  backlog.
- **backpressure shed-to-sibling** — a replica's typed
  :class:`~..serving.scheduler.AdmissionVerdict` is a live load signal,
  not a terminal answer: the router walks siblings in load order and only
  returns a fleet-level rejection when EVERY placement-eligible replica
  refused (``unservable`` is the exception — the request can never fit any
  same-shaped replica, so it rejects immediately).
- **failure-driven re-routing** — a replica that raises from a dispatch
  (``ServingFaultError`` after the scheduler's failure budget), whose
  process dies (:class:`~.replica.ReplicaDeadError`), or whose heartbeat
  age exceeds ``heartbeat_deadline_s`` is removed from the fleet and its
  assigned requests re-submitted to survivors with their absorbed tokens
  KEPT (greedy re-prefill reproduces the exact continuation). Each request
  carries a ``reroute_budget``; exhausting it is a loud typed rejection,
  not a silent loop. Every failure handling pass ends with a survivor-wide
  page-conservation audit.
- **drain-then-retire** — ``retire()`` drains a replica
  (:meth:`~..serving.scheduler.ContinuousBatchingScheduler.drain`), keeps
  pumping it until its accepted work finished, then closes and removes it:
  the autoscaler's scale-down path drops capacity without dropping work.

The router is host-pure and replica-agnostic: everything it knows about a
replica arrives through the :mod:`.replica` protocol dicts, so in-process
and subprocess replicas mix freely. Fleet events (``replica_dead``,
``request_rerouted``, ``fleet_reject``, ...) go to the router's own
replica-stamped :class:`~...resilience.events.RecoveryLog` and to an
in-memory window (:attr:`ReplicaRouter.events`) that
:class:`~.autoscale.AutoscalePolicy` consumes merged with the per-replica
counter deltas mirrored off every pump.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..serving.scheduler import AdmissionVerdict, Request, RequestState
from ..serving.tenancy import tier_rank
from .replica import ReplicaDeadError, request_spec

#: Replica-level events mirrored into the router's merged in-memory window
#: (for autoscaling trends) off each pump's counter deltas. Kept small on
#: purpose: these are the capacity/SLO signals, not the whole recovery
#: vocabulary.
MIRRORED_COUNTERS = ("deadline_miss", "request_shed", "preemption",
                     "dispatch_failed")


@dataclasses.dataclass
class FleetConfig:
    """Fleet-level knobs. The failover pair — ``heartbeat_deadline_s`` and
    ``reroute_budget`` — is what the ``serving/fleet-without-failover``
    dslint rule checks: a multi-replica fleet with neither armed silently
    loses every in-flight request of the first replica that dies."""

    #: seconds of heartbeat silence before a replica is declared hung and
    #: failed over (None = never — the rule-flagged default)
    heartbeat_deadline_s: Optional[float] = None
    #: how many times one request may be re-routed off a failed replica
    #: before the fleet gives up on it (0 = never re-route)
    reroute_budget: int = 2
    #: same-session requests stick to their last replica (prefix-cache
    #: locality); spill-on-pressure still applies
    session_affinity: bool = True
    #: walk siblings on queue_full/token_backlog before rejecting
    spill: bool = True
    #: scheduler steps per replica per router step
    pump_steps: int = 1
    #: in-memory fleet event window (entries, for autoscale trends)
    event_window: int = 4096
    #: disaggregated prefill/decode (docs/SERVING.md "Tensor parallel &
    #: disaggregation"): the role assumed for replica handles that don't
    #: declare one. Handles built from a role-configured ServingConfig
    #: carry their own ``role`` attribute; placement is role-aware —
    #: fresh requests go to prefill-capable replicas ("prefill"/"both"),
    #: handoff forwards to decode-capable ones ("decode"/"both"), with
    #: fall-back to ANY live replica when a role pool is empty (failover:
    #: every program family stays lazily compilable on every replica)
    role: str = "both"

    @property
    def failover_armed(self) -> bool:
        return self.heartbeat_deadline_s is not None or self.reroute_budget >= 1


class ReplicaRouter:
    """Front N replica handles with placement, backpressure, and failover
    (module docstring). ``replicas``: handles implementing the
    :mod:`.replica` protocol. ``recovery_log``: fleet-level event sink
    (optional; an in-memory window is always kept)."""

    def __init__(self, replicas, config: Optional[FleetConfig] = None,
                 recovery_log=None, clock=time.monotonic):
        self.replicas = list(replicas)   # placement-eligible or draining
        self.dead: List[Any] = []
        self.retired: List[Any] = []
        self.config = config or FleetConfig()
        self.recovery_log = recovery_log
        self.clock = clock
        self.counters: Dict[str, int] = {}
        self.events: Deque[Dict[str, Any]] = deque(
            maxlen=self.config.event_window)
        self._requests: Dict[int, Request] = {}
        self._assignment: Dict[int, str] = {}   # rid -> replica_id
        self._reroutes: Dict[int, int] = {}
        self._affinity: Dict[str, str] = {}     # session_id -> replica_id
        self._last_load: Dict[str, Dict[str, Any]] = {}
        self._last_counters: Dict[str, Dict[str, int]] = {}
        # bounded: a long-lived router must not grow with total requests
        # served (terminal requests are dropped from the ledgers above the
        # moment they finalize; callers keep their own Request objects)
        self.finished: Deque[Request] = deque(
            maxlen=self.config.event_window)

    # ------------------------------------------------------------- events
    def _record(self, event: str, persist: bool = True,
                **fields: Any) -> None:
        self.counters[event] = self.counters.get(event, 0) + 1
        entry = {"unix_time": time.time(), "event": event, **fields}
        self.events.append(entry)
        if persist and self.recovery_log is not None:
            try:
                self.recovery_log.record(event, **fields)
            except Exception:  # event export must never fail routing
                pass

    @staticmethod
    def _tenant_fields(req: Request) -> Dict[str, Any]:
        """Tenant/tier stamps for fleet events — {} for untenanted
        requests, so the pre-tier event schema is unchanged."""
        fields: Dict[str, Any] = {}
        if getattr(req, "tenant_id", None) is not None:
            fields["tenant_id"] = req.tenant_id
        if getattr(req, "tier", None) is not None:
            fields["tier"] = req.tier
        return fields

    def _mirror_counters(self, replica_id: str,
                         counters: Dict[str, int]) -> None:
        """Turn per-replica counter deltas into window events so autoscale
        trend math sees the MERGED fleet stream without double-writing the
        replicas' own recovery logs."""
        prev = self._last_counters.get(replica_id, {})
        for name in MIRRORED_COUNTERS:
            for _ in range(counters.get(name, 0) - prev.get(name, 0)):
                self._record(name, persist=False, replica_id=replica_id)
        self._last_counters[replica_id] = dict(counters)

    # ---------------------------------------------------------- placement
    @property
    def live_replicas(self) -> List[Any]:
        """Placement-eligible replicas (alive and not draining)."""
        return [r for r in self.replicas if r.alive and not r.draining]

    def replica(self, replica_id: str):
        for r in self.replicas:
            if r.replica_id == replica_id:
                return r
        return None

    def _load_score(self, rep) -> int:
        load = self._last_load.get(rep.replica_id)
        if load is None:
            try:
                load = rep.load()
            except ReplicaDeadError:
                return 1 << 30
            self._last_load[rep.replica_id] = load
        return int(load.get("work_tokens", 0))

    def _replica_role(self, rep) -> str:
        return getattr(rep, "role", None) or self.config.role

    def _placement_order(self, req: Request,
                         need: str = "prefill") -> List[Any]:
        """Live replicas in least-loaded order, filtered by role capability:
        ``need="prefill"`` wants a replica that runs prefill programs
        ("prefill"/"both"), ``need="decode"`` one that accepts handoff
        imports and decodes ("decode"/"both"). An empty capability pool
        falls back to EVERY live replica — a decode specialist re-prefills
        an orphaned request rather than the fleet dropping it (it just pays
        a lazy compile)."""
        live = self.live_replicas
        capable = [r for r in live
                   if self._replica_role(r) in (need, "both")]
        order = sorted(capable or live,
                       key=lambda r: (self._load_score(r), r.replica_id))
        if self.config.session_affinity and req.session_id is not None:
            sticky = self._affinity.get(req.session_id)
            for i, r in enumerate(order):
                if r.replica_id == sticky and i > 0:
                    order.insert(0, order.pop(i))
                    break
        return order

    def _place(self, req: Request, pending: List[Request]
               ) -> AdmissionVerdict:
        """Try every eligible replica in placement order. ``pending``
        collects requests orphaned by replicas that die DURING placement
        (the caller keeps re-routing them — no recursion)."""
        now = self.clock()
        age = 0.0 if req.t_submit is None else now - req.t_submit
        last: Optional[Dict[str, Any]] = None
        tried = 0
        for rep in self._placement_order(req):
            tried += 1
            try:
                verdict = rep.submit(request_spec(req, age_s=age))
            except ReplicaDeadError as e:
                pending.extend(self._fail_replica(rep, e))
                continue
            if verdict["admitted"]:
                self._requests[req.rid] = req
                self._assignment[req.rid] = rep.replica_id
                load = self._last_load.get(rep.replica_id)
                if load is not None:  # keep the score fresh between pumps
                    load["work_tokens"] = (load.get("work_tokens", 0)
                                           + req.work_tokens)
                if req.session_id is not None and self.config.session_affinity:
                    prev = self._affinity.get(req.session_id)
                    if prev is not None and prev != rep.replica_id:
                        self._record("session_spilled", persist=False,
                                     session_id=req.session_id,
                                     from_replica=prev,
                                     replica_id=rep.replica_id)
                    self._affinity[req.session_id] = rep.replica_id
                self._record("request_routed", persist=False, rid=req.rid,
                             replica_id=rep.replica_id)
                return AdmissionVerdict(
                    True, detail=f"replica {rep.replica_id}",
                    shed_rid=verdict.get("shed_rid"))
            last = verdict
            if verdict["reason"] == "unservable":
                # the bound is structural (prompt+max_new vs the serving
                # shape) — no same-shaped sibling can do better
                break
            if not self.config.spill:
                break
        reason = last["reason"] if last else "no_replicas"
        detail = (f"{tried} replica(s) refused; last: "
                  f"{last['detail'] if last else 'no live replicas'}")
        req.state = RequestState.REJECTED
        req.reject_reason = reason
        self._record("fleet_reject", rid=req.rid, reason=reason,
                     **self._tenant_fields(req))
        self._forget(req.rid)
        return AdmissionVerdict(False, reason, detail)

    def submit(self, req: Request) -> AdmissionVerdict:
        """Fleet admission: place on the sticky/least-loaded replica,
        spilling across siblings on backpressure; a rejection here means
        the whole fleet refused."""
        if req.t_submit is None:
            req.t_submit = self.clock()
        pending: List[Request] = []
        verdict = self._place(req, pending)
        self._drain_pending(pending)
        return verdict

    # ------------------------------------------------------------ failover
    def _fail_replica(self, rep, err: BaseException) -> List[Request]:
        """Remove a dead/hung replica and return the requests it held."""
        if rep in self.dead:
            return []
        if rep in self.replicas:
            self.replicas.remove(rep)
        self.dead.append(rep)
        try:
            rep.kill()
        except Exception:
            pass
        self._last_load.pop(rep.replica_id, None)
        # a supervisor-restarted replacement may reuse the replica_id: its
        # counter deltas must not be diffed against the dead one's totals
        self._last_counters.pop(rep.replica_id, None)
        for session, target in list(self._affinity.items()):
            if target == rep.replica_id:
                del self._affinity[session]
        victims = [self._requests[rid]
                   for rid, owner in list(self._assignment.items())
                   if owner == rep.replica_id]
        for req in victims:
            del self._assignment[req.rid]
        self._record("replica_dead", replica_id=rep.replica_id,
                     error=f"{type(err).__name__}: {err}"[:200],
                     in_flight=len(victims))
        return victims

    def _drain_pending(self, pending: List[Request]) -> None:
        """Re-route every orphaned request (kept tokens preserved) until
        the list is empty; replicas dying mid-re-route just extend it."""
        audited = False
        while pending:
            req = pending.pop(0)
            if req.state in (RequestState.FINISHED, RequestState.REJECTED,
                             RequestState.EXPIRED):
                continue
            n = self._reroutes.get(req.rid, 0)
            if n >= self.config.reroute_budget:
                req.state = RequestState.REJECTED
                req.reject_reason = "reroute_budget"
                self._record("reroute_budget_exhausted", rid=req.rid,
                             reroutes=n)
                self._forget(req.rid)
                continue
            self._reroutes[req.rid] = n + 1
            self._record("request_rerouted", rid=req.rid,
                         kept_tokens=len(req.tokens), attempt=n + 1,
                         **self._tenant_fields(req))
            self._place(req, pending)
            audited = True
        if audited:
            self.audit_survivors(raise_on_error=True)

    def _handle_failure(self, rep, err: BaseException) -> None:
        self._drain_pending(self._fail_replica(rep, err))
        self.audit_survivors(raise_on_error=True)

    def _check_heartbeats(self) -> None:
        deadline = self.config.heartbeat_deadline_s
        if deadline is None:
            return
        for rep in list(self.replicas):
            if not rep.alive:
                continue
            try:
                age = rep.heartbeat_age()
            except Exception:
                age = float("inf")
            if age > deadline:
                self._record("replica_hung", replica_id=rep.replica_id,
                             age_s=round(age, 3), deadline_s=deadline)
                self._handle_failure(rep, TimeoutError(
                    f"heartbeat age {age:.2f}s > deadline {deadline}s"))

    def audit_survivors(self, raise_on_error: bool = False
                        ) -> Dict[str, Any]:
        """Run the page-conservation audit on every live replica. Fleet
        recovery must never leak pages on a SURVIVOR — a dead replica's
        pool died with its process; the ones still serving must balance."""
        reports: Dict[str, Any] = {}
        ok = True
        for rep in self.replicas:
            if not rep.alive:
                continue
            try:
                r = rep.audit()
            except ReplicaDeadError:
                continue
            reports[rep.replica_id] = r
            ok = ok and bool(r["ok"])
        if not ok:
            self._record("fleet_audit_failed", detail=str({
                k: v["errors"] for k, v in reports.items()
                if not v["ok"]})[:400])
            if raise_on_error:
                raise RuntimeError(
                    f"fleet recovery broke page conservation on a "
                    f"survivor: {reports}")
        return {"ok": ok, "replicas": reports}

    # ------------------------------------------------------------- stepping
    def step(self) -> int:
        """Pump every live replica once (``pump_steps`` scheduler steps
        each), absorb their progress, check heartbeats, and retire drained
        replicas. Returns tokens produced fleet-wide.

        Handles exposing the two-phase ``pump_begin``/``pump_end`` pair
        (subprocess replicas) are all STARTED before any response is
        collected, so replicas that own their own compute run their steps
        concurrently — one replica's prefill no longer stalls another's
        decode, which is the wall-clock point of a fleet. Failures are
        collected and handled only after every pending response is read:
        re-routing mid-collection would interleave a ``submit`` into a
        stream still owing a pump response."""
        failures: List[tuple] = []
        begun: List[Any] = []
        ready: List[tuple] = []
        for rep in list(self.replicas):
            if not rep.alive:
                # a handle that reports dead while still in the placement
                # set (e.g. an in-process kill between pumps) must fail
                # over NOW — skipping it would strand its assigned work
                failures.append((rep, ReplicaDeadError(
                    f"replica {rep.replica_id} reports dead")))
                continue
            begin = getattr(rep, "pump_begin", None)
            if begin is None:
                try:
                    ready.append((rep, rep.pump(self.config.pump_steps)))
                except Exception as e:
                    failures.append((rep, e))
                continue
            try:
                begin(self.config.pump_steps)
                begun.append(rep)
            except Exception as e:
                failures.append((rep, e))
        for rep in begun:
            try:
                ready.append((rep, rep.pump_end()))
            except Exception as e:
                failures.append((rep, e))
        produced = 0
        for rep, out in ready:
            produced += self._absorb(rep, out)
        if failures:
            # remove EVERY failed replica from the placement set before
            # re-routing any victim: handling serially would re-place the
            # first failure's requests onto a sibling that is already
            # known-sick, burning reroute budget while healthy survivors
            # exist
            pending: List[Request] = []
            for rep, err in failures:
                pending.extend(self._fail_replica(rep, err))
            self._drain_pending(pending)
            self.audit_survivors(raise_on_error=True)
        self._check_heartbeats()
        self._retire_drained()
        return produced

    def _absorb(self, rep, out: Dict[str, Any]) -> int:
        now = self.clock()
        self._last_load[rep.replica_id] = out.get("load") or {}
        self._mirror_counters(rep.replica_id, out.get("counters") or {})
        reroute: List[Request] = []
        for rid, toks in (out.get("tokens") or {}).items():
            rid = int(rid)  # JSON object keys arrive as strings
            req = self._requests.get(rid)
            if req is None or self._assignment.get(rid) != rep.replica_id:
                continue  # stale stream from before a re-route
            if len(toks) > len(req.tokens):
                req.tokens = [int(t) for t in toks]
                if req.t_first_token is None:
                    req.t_first_token = now
        for rid in out.get("finished") or ():
            req = self._finalize(int(rid), rep.replica_id)
            if req is not None:
                req.state = RequestState.FINISHED
                req.t_done = now
                self.finished.append(req)
                self._forget(req.rid)
        for rid in out.get("expired") or ():
            req = self._finalize(int(rid), rep.replica_id)
            if req is not None:
                req.state = RequestState.EXPIRED
                if req.reject_reason is None:
                    req.reject_reason = "deadline"
                self._forget(req.rid)
        for rid in out.get("shed") or ():
            # the replica shed an ACCEPTED request post-admission
            # (reject_largest victim / drain) — backpressure, so give the
            # siblings a chance before the fleet gives up on it
            req = self._finalize(int(rid), rep.replica_id)
            if req is not None:
                req.state = RequestState.QUEUED
                reroute.append(req)
        handoffs = list(out.get("handoffs") or ())
        if len(handoffs) > 1:
            # tier-ordered forwarding: interactive handoffs reach decode
            # specialists ahead of batch work staged in the same pump
            # (stable sort — same-tier handoffs keep their staging order;
            # untiered specs rank as "standard" so ordering is unchanged)
            handoffs.sort(
                key=lambda h: tier_rank((h.get("spec") or {}).get("tier")))
        for h in handoffs:
            # disaggregated prefill→decode: the prefill replica finished
            # the prompt and exported the filled KV pages; forward them to
            # a decode-capable sibling. The source OWNS the pages until we
            # answer handoff_complete — success frees them, failure frees
            # them too and the request falls back to kept-token re-prefill.
            rid = int(h["rid"])
            req = self._requests.get(rid)
            if req is None or self._assignment.get(rid) != rep.replica_id:
                # stale stream from before a re-route: the fleet already
                # re-placed this request elsewhere; just release the pages
                try:
                    rep.handoff_complete(rid, False)
                except ReplicaDeadError as e:
                    reroute.extend(self._fail_replica(rep, e))
                continue
            self._place_handoff(req, h, rep, reroute)
        self._drain_pending(reroute)
        return int(out.get("produced", 0))

    def _place_handoff(self, req: Request, h: Dict[str, Any], src,
                       pending: List[Request]) -> None:
        """Forward one staged handoff to a decode-capable replica (wire
        payload rides the normal ``submit`` spec as ``kv_payload``). Any
        refusal or death along the way degrades to the proven recovery
        contract: tell the source to free the staged pages and re-place
        the request with its kept tokens (greedy re-prefill reproduces the
        exact continuation)."""
        spec = dict(h["spec"])
        spec["kv_payload"] = h["payload"]
        for dest in self._placement_order(req, need="decode"):
            if dest.replica_id == src.replica_id:
                continue  # a handoff back to its own exporter is a no-op
            try:
                verdict = dest.submit(spec)
            except ReplicaDeadError as e:
                pending.extend(self._fail_replica(dest, e))
                continue
            if verdict["admitted"]:
                self._assignment[req.rid] = dest.replica_id
                load = self._last_load.get(dest.replica_id)
                if load is not None:
                    load["work_tokens"] = (load.get("work_tokens", 0)
                                           + req.work_tokens)
                if req.session_id is not None and self.config.session_affinity:
                    self._affinity[req.session_id] = dest.replica_id
                self._record("handoff_forwarded", persist=False,
                             rid=req.rid, from_replica=src.replica_id,
                             replica_id=dest.replica_id,
                             context_len=int(h.get("context_len", 0)))
                try:
                    src.handoff_complete(req.rid, True)
                except ReplicaDeadError as e:
                    pending.extend(self._fail_replica(src, e))
                return
        # every decode-capable sibling refused (or none exists): free the
        # staged pages and fall back to normal placement with kept tokens
        self._record("handoff_fallback", rid=req.rid,
                     from_replica=src.replica_id)
        try:
            src.handoff_complete(req.rid, False)
        except ReplicaDeadError as e:
            pending.extend(self._fail_replica(src, e))
        if self._assignment.get(req.rid) == src.replica_id:
            del self._assignment[req.rid]
        req.state = RequestState.QUEUED
        self._place(req, pending)

    def _finalize(self, rid: int, replica_id: str) -> Optional[Request]:
        if self._assignment.get(rid) != replica_id:
            return None
        del self._assignment[rid]
        return self._requests.get(rid)

    def _forget(self, rid: int) -> None:
        """Drop a TERMINAL request from the router's ledgers (the caller's
        Request object is the canonical record; keeping every served
        request would grow memory with total traffic)."""
        self._requests.pop(rid, None)
        self._reroutes.pop(rid, None)

    # -------------------------------------------------- add/retire capacity
    def add_replica(self, rep) -> None:
        self.replicas.append(rep)
        self._record("replica_added", replica_id=rep.replica_id)

    def retire(self, replica_id: str) -> bool:
        """Begin drain-then-retire on one replica: it stops admitting,
        keeps being pumped until its accepted work finished, then is
        closed and removed (see :meth:`_retire_drained`)."""
        rep = self.replica(replica_id)
        if rep is None or not rep.alive:
            return False
        try:
            rep.drain()
        except ReplicaDeadError as e:
            self._handle_failure(rep, e)
            return False
        self._record("replica_draining", replica_id=replica_id)
        return True

    def _retire_drained(self) -> None:
        for rep in list(self.replicas):
            try:
                done = rep.alive and rep.drained
            except ReplicaDeadError:
                continue
            if done:
                rep.close()
                self.replicas.remove(rep)
                self.retired.append(rep)
                self._last_load.pop(rep.replica_id, None)
                self._last_counters.pop(rep.replica_id, None)
                for session, target in list(self._affinity.items()):
                    if target == rep.replica_id:
                        del self._affinity[session]
                self._record("replica_retired", replica_id=rep.replica_id)

    # ------------------------------------------------------------- queries
    @property
    def idle(self) -> bool:
        """No accepted request is still assigned to a replica."""
        return not self._assignment

    def occupancy(self) -> float:
        """Fraction of the fleet's decode slots currently running work —
        the autoscaler's scale-down signal."""
        active = total = 0
        for rep in self.replicas:
            load = self._last_load.get(rep.replica_id)
            if load is None:
                try:
                    load = rep.load()
                except ReplicaDeadError:
                    continue
                self._last_load[rep.replica_id] = load
            active += int(load.get("active", 0)) + int(
                load.get("queue_depth", 0))
            total += int(load.get("num_slots", 0))
        return active / total if total else 0.0

    def run_to_completion(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"fleet did not drain in {max_steps} steps")

    def close(self) -> None:
        for rep in self.replicas:
            try:
                rep.close()
            except Exception:
                pass


__all__ = ["FleetConfig", "ReplicaRouter", "MIRRORED_COUNTERS"]
