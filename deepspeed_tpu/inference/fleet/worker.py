"""Subprocess replica: the fleet protocol over stdin/stdout JSON lines.

``python -m deepspeed_tpu.inference.fleet.worker`` runs ONE replica — a
real ``ServingEngine`` (own jax runtime, own page pool, own watchdog)
wrapped in :class:`~.replica.LocalReplica` — and answers the protocol ops
as one JSON object per line:

    {"op": "init", "replica_id": ..., "model": {...GPTConfig kwargs...},
     "serving": {...ServingConfig kwargs...}, "seed": 0}
    {"op": "submit", "spec": {...}} | {"op": "pump", "steps": K}
    {"op": "load"} | {"op": "drain"} | {"op": "audit"} | {"op": "close"}
    {"op": "handoff_complete", "rid": N, "success": true}

:class:`SubprocessReplica` is the parent-side handle: it spawns the
worker, speaks the same dicts :class:`~.replica.LocalReplica` speaks
in-process, and — the point of the exercise — turns a SIGKILL'd or
wedged worker into :class:`~.replica.ReplicaDeadError` (pipe EOF, or no
response within ``call_timeout_s``), which the router answers with
re-route-to-survivors. ``scripts/serving_smoke.py --fleet`` SIGKILLs one
of two real-engine replicas mid-stream and proves the fleet heals.

Every response is read with a hard deadline (``select`` on the pipe fd):
a replica that stops answering is indistinguishable from a dead one on
purpose — that IS the failure model.
"""

from __future__ import annotations

import json
import os
import select
import signal
import subprocess
import sys
import time
from typing import Any, Dict, Optional

from .replica import ReplicaDeadError

#: generous init deadline: the worker imports jax and warms every serving
#: program shape before answering
DEFAULT_INIT_TIMEOUT_S = 300.0
DEFAULT_CALL_TIMEOUT_S = 60.0


class SubprocessReplica:
    """Parent-side handle for one worker process (module docstring)."""

    def __init__(self, replica_id: str, model: Dict[str, Any],
                 serving: Dict[str, Any], seed: int = 0,
                 call_timeout_s: float = DEFAULT_CALL_TIMEOUT_S,
                 init_timeout_s: float = DEFAULT_INIT_TIMEOUT_S,
                 env: Optional[Dict[str, str]] = None):
        self.replica_id = str(replica_id)
        penv = dict(os.environ)
        penv.setdefault("JAX_PLATFORMS", "cpu")
        if env:
            penv.update(env)
        # -c instead of -m: the package __init__ already imports this
        # module, and runpy warns when re-executing an imported module
        self.proc = subprocess.Popen(
            [sys.executable, "-c",
             "from deepspeed_tpu.inference.fleet.worker import main; "
             "import sys; sys.exit(main())"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=None,
            env=penv, cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))))
        self.call_timeout_s = float(call_timeout_s)
        # disaggregated role, read by the router's role-aware placement
        # (the worker's scheduler enforces the same role internally)
        self.role = str(serving.get("role", "both") or "both")
        self._alive = True
        self._buf = b""
        self._last_beat = time.monotonic()
        self._draining = False
        self._drained = False
        self._pending: Optional[str] = None  # op awaiting its response
        out = self._call({"op": "init", "replica_id": self.replica_id,
                          "model": model, "serving": serving,
                          "seed": int(seed)}, timeout=float(init_timeout_s))
        self.num_slots = int(out.get("num_slots", 0))

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def alive(self) -> bool:
        return self._alive

    @property
    def draining(self) -> bool:
        return self._alive and self._draining

    @property
    def drained(self) -> bool:
        return self._alive and self._drained

    def heartbeat_age(self) -> float:
        return time.monotonic() - self._last_beat

    # ------------------------------------------------------------- transport
    def _reap(self) -> None:
        """Reap the (already-signalled) child and close its pipes — a
        router that fails over replicas for a living must not accumulate
        zombies and leaked pipe fds."""
        try:
            self.proc.wait(timeout=5.0)
        except Exception:
            pass
        for pipe in (self.proc.stdin, self.proc.stdout):
            try:
                pipe.close()
            except Exception:
                pass

    def _mark_dead(self, why: str) -> None:
        self._alive = False
        try:
            self.proc.kill()
        except Exception:
            pass
        self._reap()
        raise ReplicaDeadError(f"replica {self.replica_id}: {why}")

    def _read_line(self, deadline: float) -> bytes:
        fd = self.proc.stdout.fileno()
        while b"\n" not in self._buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._mark_dead(
                    f"no response within {self.call_timeout_s}s "
                    f"(hung or wedged worker)")
            ready, _, _ = select.select([fd], [], [], min(remaining, 0.5))
            if not ready:
                if self.proc.poll() is not None:
                    self._mark_dead(
                        f"worker exited rc={self.proc.returncode}")
                continue
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                self._mark_dead("worker pipe closed (killed or crashed)")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line

    def _send(self, obj: Dict[str, Any]) -> None:
        if not self._alive:
            raise ReplicaDeadError(f"replica {self.replica_id} is dead")
        if self._pending is not None:
            raise RuntimeError(
                f"replica {self.replica_id}: request while a "
                f"{self._pending!r} response is pending")
        try:
            self.proc.stdin.write((json.dumps(obj) + "\n").encode())
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            self._mark_dead("worker pipe broken on write")

    def _recv(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        timeout = self.call_timeout_s if timeout is None else timeout
        line = self._read_line(time.monotonic() + timeout)
        try:
            out = json.loads(line)
        except ValueError:
            self._mark_dead(f"unparseable response: {line[:120]!r}")
        if out.get("error"):
            # a protocol-level error is a sick replica, not a router bug
            self._mark_dead(f"worker error: {out['error']}")
        self._last_beat = time.monotonic()
        return out

    def _call(self, obj: Dict[str, Any],
              timeout: Optional[float] = None) -> Dict[str, Any]:
        self._send(obj)
        return self._recv(timeout)

    # -------------------------------------------------------------- protocol
    def submit(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        return self._call({"op": "submit", "spec": spec})

    def pump(self, max_steps: int = 1) -> Dict[str, Any]:
        self.pump_begin(max_steps)
        return self.pump_end()

    # two-phase pump: the router begins a pump on EVERY replica before
    # collecting any response, so N worker processes decode their steps
    # genuinely concurrently — the wall-clock fleet win of replicas owning
    # their own compute (separate chips; here, separate processes)
    def pump_begin(self, max_steps: int = 1) -> None:
        self._send({"op": "pump", "steps": int(max_steps)})
        self._pending = "pump"

    def pump_end(self) -> Dict[str, Any]:
        if self._pending != "pump":
            raise RuntimeError(f"replica {self.replica_id}: pump_end "
                               f"without pump_begin")
        try:
            out = self._recv()
        finally:
            self._pending = None
        self._draining = bool(out.get("draining"))
        self._drained = bool(out.get("drained"))
        return out

    def load(self) -> Dict[str, Any]:
        return self._call({"op": "load"})

    def handoff_complete(self, rid: int, success: bool = True) -> bool:
        out = self._call({"op": "handoff_complete", "rid": int(rid),
                          "success": bool(success)})
        return bool(out.get("ok"))

    def drain(self) -> None:
        out = self._call({"op": "drain"})
        self._draining = True
        self._drained = bool(out.get("drained"))

    def audit(self) -> Dict[str, Any]:
        return self._call({"op": "audit"})

    def close(self) -> None:
        if not self._alive:
            return
        try:
            self._call({"op": "close"}, timeout=10.0)
        except ReplicaDeadError:
            pass
        self._alive = False
        try:
            self.proc.wait(timeout=10.0)
        except Exception:
            self.proc.kill()
        self._reap()

    def kill(self) -> None:
        """The hard stop: SIGKILL, no goodbyes — what a preempted host or
        an OOM-killed container looks like from the router's side."""
        self._alive = False
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass
        self._reap()


# ------------------------------------------------------------- worker main
def _build_replica(msg: Dict[str, Any]):
    """Import jax lazily (the parent handle must stay importable without
    acquiring a runtime) and assemble engine + LocalReplica."""
    import jax

    from ...models import gpt as gpt_mod
    from ..serving import ServingConfig, ServingEngine
    from .replica import LocalReplica

    cfg = gpt_mod.GPTConfig(**msg["model"])
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(
        int(msg.get("seed", 0))))
    eng = ServingEngine(cfg, params, ServingConfig(**msg["serving"]))
    eng.warmup()
    return LocalReplica(str(msg.get("replica_id", "worker")), engine=eng)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the protocol owns fd 1: keep a private dup for responses and point
    # everything else (library prints, loggers bound to sys.stdout) at
    # stderr, so stray output can never tear the JSON framing
    out = os.fdopen(os.dup(1), "w")
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    replica = None
    for raw in sys.stdin:
        raw = raw.strip()
        if not raw:
            continue
        try:
            msg = json.loads(raw)
            op = msg.get("op")
            if op == "init":
                replica = _build_replica(msg)
                resp = {"ok": True, "replica_id": replica.replica_id,
                        "num_slots": replica.sched.num_slots,
                        "pid": os.getpid()}
            elif replica is None:
                resp = {"error": f"op {op!r} before init"}
            elif op == "submit":
                resp = replica.submit(msg["spec"])
            elif op == "pump":
                resp = replica.pump(int(msg.get("steps", 1)))
            elif op == "load":
                resp = replica.load()
            elif op == "handoff_complete":
                resp = {"ok": replica.handoff_complete(
                    int(msg["rid"]), bool(msg.get("success", True)))}
            elif op == "drain":
                replica.drain()
                resp = {"ok": True, "drained": replica.drained}
            elif op == "audit":
                resp = replica.audit()
            elif op == "close":
                replica.close()
                print(json.dumps({"ok": True}), file=out, flush=True)
                return 0
            else:
                resp = {"error": f"unknown op {op!r}"}
        except Exception as e:  # report, let the parent decide
            resp = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(resp), file=out, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
