"""Replica handles: one serving replica behind a process-boundary-shaped
protocol.

A *replica* is one ``ServingEngine`` + ``ContinuousBatchingScheduler`` pair
(one copy of the weights, one page pool, one admission limit). The fleet
router (:mod:`.router`) never touches those objects directly — every
interaction goes through a handle whose inputs and outputs are
JSON-serializable dicts, so the same router code drives an in-process
:class:`LocalReplica` and a :class:`~.worker.SubprocessReplica` living in
another process (and, later, on another host). The protocol:

- ``submit(spec) -> verdict dict`` — admission-control one request
  (``spec`` from :func:`request_spec`: prompt, max_new, deadlines, KEPT
  tokens from a previous replica, and the request's age so deadline clocks
  survive a re-route). The dict mirrors
  :class:`~..serving.scheduler.AdmissionVerdict`.
- ``pump(max_steps) -> snapshot dict`` — run up to ``max_steps`` scheduler
  steps and report progress: per-request token streams (FULL lists — the
  router's kept-token ledger is exactly what it has absorbed, which is what
  re-routing preserves when this replica dies mid-block), newly
  finished/expired/shed rids, scheduler counters, and a load snapshot.
- ``load() -> dict`` — placement signals (queue depth, queued work tokens,
  active slots, total slots, free pages).
- ``heartbeat_age() -> float`` — seconds since the replica last proved
  liveness; the router's hung-replica deadline reads this.
- ``drain() / drained / draining`` — graceful scale-down
  (``ContinuousBatchingScheduler.drain``: admit nothing new, finish
  accepted work).
- ``audit() -> dict`` — the page-conservation audit, run by the router
  after every fleet recovery action.
- ``close()`` (graceful) / ``kill()`` (hard stop). A dead handle raises
  :class:`ReplicaDeadError` from every call — the router's signal to
  re-route the replica's assigned requests to survivors.

Token-stream discipline: a replica only reports a token AFTER the decode
step that produced it completed, and the router only trusts what it
absorbed. A replica killed mid-decode-block therefore leaves the router
holding a *prefix* of the true greedy sequence — re-prefilling
prompt+kept-tokens on a survivor recomputes the identical continuation
(greedy decode is deterministic and every replica serves the same weights),
which is the whole re-route correctness story.
"""

from __future__ import annotations

import base64
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..serving.scheduler import Request, RequestState


class ReplicaDeadError(RuntimeError):
    """The replica behind this handle is gone (killed, crashed, or its
    process stopped answering) — callers must re-route its work."""


def request_spec(req: Request, age_s: float = 0.0,
                 kv_payload: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """The JSON-safe wire form of one request, kept tokens included.
    ``kv_payload`` (an :func:`encode_kv_payload` product) rides along for
    disaggregated prefill->decode forwarding — the receiving replica admits
    by importing the pages instead of prefilling."""
    spec = {
        "rid": int(req.rid),
        "prompt": [int(t) for t in np.asarray(req.prompt).tolist()],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "tokens": [int(t) for t in req.tokens],
        "ttft_deadline_s": req.ttft_deadline_s,
        "deadline_s": req.deadline_s,
        "session_id": req.session_id,
        "age_s": float(max(age_s, 0.0)),
        # tier metadata rides the wire as plain fields — a re-routed or
        # handed-off request keeps its SLO class on the receiving replica
        "tenant_id": req.tenant_id,
        "tier": req.tier,
    }
    if kv_payload is not None:
        spec["kv_payload"] = kv_payload
    return spec


def encode_kv_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe form of an ``export_pages`` payload: raw buffers become
    base64 text. Quantized pools keep their wire advantage — the int8/int4
    payload plus fp32 per-page scales is what gets encoded, 2-4x smaller
    than fp32 pages before base64's constant 4/3."""
    wire = {
        "page_ids": [int(p) for p in payload["page_ids"]],
        "tensors": {
            k: {"dtype": str(t["dtype"]),
                "shape": [int(x) for x in t["shape"]],
                "data": base64.b64encode(t["data"]).decode("ascii")}
            for k, t in payload["tensors"].items()},
    }
    if "fingerprints" in payload:
        # integrity stamp (algo + per-pool ints) is already JSON-safe; it
        # must survive the wire so the importer can refuse a torn transfer
        wire["fingerprints"] = payload["fingerprints"]
    return wire


def decode_kv_payload(wire: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`encode_kv_payload` (idempotent on raw bytes)."""
    tensors = {}
    for k, t in wire["tensors"].items():
        data = t["data"]
        if isinstance(data, str):
            data = base64.b64decode(data)
        tensors[k] = {"dtype": t["dtype"],
                      "shape": [int(x) for x in t["shape"]], "data": data}
    out = {"page_ids": [int(p) for p in wire["page_ids"]],
           "tensors": tensors}
    if "fingerprints" in wire:
        out["fingerprints"] = wire["fingerprints"]
    return out


def _verdict_dict(v) -> Dict[str, Any]:
    return {"admitted": bool(v.admitted), "reason": v.reason,
            "detail": v.detail,
            "shed_rid": None if v.shed_rid is None else int(v.shed_rid)}


class LocalReplica:
    """In-process replica: the protocol above over a real scheduler.

    Build from a :class:`~..serving.engine.ServingEngine` (the scheduler is
    assembled via ``make_scheduler`` with a replica-stamped
    :class:`~...resilience.events.RecoveryLog`) or hand a prebuilt
    scheduler in directly (device-free tests drive a fake executor).
    """

    def __init__(self, replica_id: str, engine=None, scheduler=None,
                 recovery_log=None, clock=time.monotonic):
        if (engine is None) == (scheduler is None):
            raise ValueError("pass exactly one of engine= or scheduler=")
        self.replica_id = str(replica_id)
        self.engine = engine
        self.clock = clock
        if recovery_log is None:
            from ...resilience.events import RecoveryLog

            recovery_log = RecoveryLog(role="serving", prefix="Serving",
                                       replica_id=self.replica_id)
        self.recovery_log = recovery_log
        if scheduler is None:
            scheduler = engine.make_scheduler(clock=clock,
                                              recovery_log=recovery_log)
        elif scheduler.recovery_log is None:
            scheduler.recovery_log = recovery_log
        self.sched = scheduler
        # disaggregated role (docs/SERVING.md): the router's role-aware
        # placement reads this — "prefill" replicas take fresh admissions
        # and stage handoffs, "decode" replicas take handoff arrivals
        self.role = getattr(scheduler, "role", "both") or "both"
        self._alive = True
        self._reqs: Dict[int, Request] = {}
        self._reported_len: Dict[int, int] = {}
        self._last_beat = clock()

    # ----------------------------------------------------------- liveness
    @property
    def alive(self) -> bool:
        return self._alive

    def _check_alive(self) -> None:
        if not self._alive:
            raise ReplicaDeadError(f"replica {self.replica_id} is dead")

    def heartbeat_age(self) -> float:
        """Seconds since the last completed pump (a pump that returns —
        even with zero tokens — proves the replica is making scheduling
        progress)."""
        return self.clock() - self._last_beat

    # ----------------------------------------------------------- protocol
    def submit(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        self._check_alive()
        req = Request(
            prompt=np.asarray(spec["prompt"], np.int32),
            max_new_tokens=int(spec["max_new_tokens"]),
            eos_token_id=spec.get("eos_token_id"),
            ttft_deadline_s=spec.get("ttft_deadline_s"),
            deadline_s=spec.get("deadline_s"),
            session_id=spec.get("session_id"),
            tenant_id=spec.get("tenant_id"),
            tier=spec.get("tier"),
            rid=int(spec["rid"]),
        )
        req.tokens = [int(t) for t in spec.get("tokens", ())]
        if spec.get("kv_payload") is not None:
            # disaggregated arrival: the scheduler admits this by importing
            # the exported pages instead of prefilling
            req.kv_payload = decode_kv_payload(spec["kv_payload"])
        # deadline clocks measure the request's LIFETIME: a re-routed
        # request arrives pre-aged, not freshly submitted
        req.t_submit = self.clock() - float(spec.get("age_s", 0.0))
        if req.tokens:
            # the first token was already delivered (by a previous replica
            # or before a preemption) — TTFT must not re-arm
            req.t_first_token = req.t_submit
        verdict = self.sched.submit(req)
        if verdict.admitted:
            self._reqs[req.rid] = req
            self._reported_len[req.rid] = len(req.tokens)
        return _verdict_dict(verdict)

    def pump(self, max_steps: int = 1) -> Dict[str, Any]:
        """Run up to ``max_steps`` scheduler steps. Exceptions from the
        scheduler (``ServingFaultError``, a failed page audit) propagate —
        the router treats any raising pump as a replica failure."""
        self._check_alive()
        produced = 0
        for _ in range(int(max_steps)):
            if self.sched.idle:
                break
            produced += self.sched.step()
        return self._snapshot(produced)

    def _snapshot(self, produced: int) -> Dict[str, Any]:
        tokens: Dict[int, List[int]] = {}
        finished: List[int] = []
        expired: List[int] = []
        shed: List[int] = []
        pending_handoffs = self.sched.pending_handoff_rids
        for rid, req in list(self._reqs.items()):
            if len(req.tokens) > self._reported_len.get(rid, 0):
                tokens[rid] = [int(t) for t in req.tokens]
                self._reported_len[rid] = len(req.tokens)
            if req.state is RequestState.FINISHED:
                finished.append(rid)
            elif req.state is RequestState.EXPIRED:
                expired.append(rid)
            elif req.state is RequestState.REJECTED:
                # post-admission policy shed (reject_largest victim, or a
                # drain rejecting re-queued work) — the router may re-place
                shed.append(rid)
            elif (req.state is RequestState.HANDOFF
                  and rid not in pending_handoffs):
                # handoff completed (or aborted) in an earlier cycle: the
                # request's lifecycle now belongs to the decode side
                self._reqs.pop(rid, None)
                self._reported_len.pop(rid, None)
        for rid in finished + expired + shed:
            self._reqs.pop(rid, None)
            self._reported_len.pop(rid, None)
        # stage the wire form of every newly staged handoff: pages exported
        # THROUGH the executor (quantized pools ship int8 + scales), pages
        # still owned here until the router acks via handoff_complete
        handoffs: List[Dict[str, Any]] = []
        now = self.clock()
        for e in self.sched.pop_handoffs():
            req = e["request"]
            age = 0.0 if req.t_submit is None else now - req.t_submit
            payload = self.sched.executor.export_pages(e["page_ids"])
            handoffs.append({
                "rid": int(e["rid"]),
                "context_len": int(e["context_len"]),
                "spec": request_spec(req, age_s=age),
                "payload": encode_kv_payload(payload),
            })
        self._last_beat = self.clock()
        return {
            "replica_id": self.replica_id,
            "produced": int(produced),
            "tokens": tokens,
            "finished": finished,
            "expired": expired,
            "shed": shed,
            "handoffs": handoffs,
            "counters": dict(self.sched.counters),
            "load": self.load(),
            "draining": self.sched.draining,
            "drained": self.sched.drained,
        }

    def handoff_complete(self, rid: int, success: bool = True) -> bool:
        """Ownership-transfer ack from the router: the decode side admitted
        (``success``) or the handoff was abandoned — free the staged pages
        either way (idempotent on unknown rids)."""
        self._check_alive()
        return self.sched.complete_handoff(int(rid), ok=bool(success))

    def load(self) -> Dict[str, Any]:
        self._check_alive()
        s = self.sched
        running = [s.slots[i] for i in s.active_slots]
        work = s.queued_tokens + sum(
            r.max_new_tokens - len(r.tokens) for r in running)
        return {
            "replica_id": self.replica_id,
            "queue_depth": len(s.queue),
            "queued_tokens": int(s.queued_tokens),
            "active": len(running),
            "num_slots": int(s.num_slots),
            "free_pages": int(s.allocator.free_pages),
            "work_tokens": int(work),
            "draining": s.draining,
        }

    @property
    def draining(self) -> bool:
        return self._alive and self.sched.draining

    @property
    def drained(self) -> bool:
        return self._alive and self.sched.drained

    def drain(self) -> None:
        self._check_alive()
        self.sched.drain()

    def audit(self) -> Dict[str, Any]:
        self._check_alive()
        rep = self.sched.audit()
        return {"ok": bool(rep["ok"]), "errors": list(rep["errors"]),
                "free": int(rep["free"]), "allocated": int(rep["allocated"]),
                "total": int(rep["total"]),
                "page_stats": dict(rep.get("page_stats", {}))}

    def close(self) -> None:
        """Graceful stop (the caller drained first, or accepts the loss)."""
        if self._alive:
            self._alive = False
            self.sched.close()

    def kill(self) -> None:
        """Hard stop — the SIGKILL analog. The scheduler's watchdog thread
        is still stopped (it is OUR process), but no draining happens and
        every subsequent call raises :class:`ReplicaDeadError`."""
        self.close()


__all__ = ["LocalReplica", "ReplicaDeadError", "request_spec",
           "encode_kv_payload", "decode_kv_payload"]
