"""Fleet-scale serving: a replica router over N scheduler+engine pairs.

See ``docs/SERVING.md`` "Fleet". Layering:

- :mod:`.replica` — the process-boundary-shaped replica protocol and the
  in-process :class:`LocalReplica` handle.
- :mod:`.worker` — the same replica behind stdin/stdout JSON lines
  (:class:`SubprocessReplica` + the ``python -m ...fleet.worker`` main);
  a SIGKILL'd worker surfaces as :class:`ReplicaDeadError`.
- :mod:`.router` — placement (least-loaded + session affinity with
  spill), backpressure shed-to-sibling over typed admission verdicts,
  heartbeat/failure-budget death detection, re-route with kept tokens,
  drain-then-retire.
- :mod:`.autoscale` — :class:`AutoscalePolicy` over the merged
  ``Serving/*`` event stream; replica sizing stays with the AOT fit
  ladder (``runtime/aot.serving_admission_limit`` /
  ``fleet_replica_plan``).
- :mod:`.bench` — the open-loop fleet driver sharing the serving bench
  report schema.
"""

from .autoscale import AutoscalePolicy, FleetAutoscaler, summarize_events
from .bench import run_fleet
from .replica import LocalReplica, ReplicaDeadError, request_spec
from .router import FleetConfig, ReplicaRouter
from .worker import SubprocessReplica

__all__ = [
    "AutoscalePolicy", "FleetAutoscaler", "summarize_events",
    "run_fleet",
    "LocalReplica", "ReplicaDeadError", "request_spec",
    "FleetConfig", "ReplicaRouter",
    "SubprocessReplica",
]
