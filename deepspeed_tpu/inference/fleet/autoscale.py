"""Admission-driven autoscaling: capacity decisions from the merged
``Serving/*`` event stream.

The fleet's load truth is already flowing: every replica's scheduler emits
typed admission verdicts, shed/deadline-miss/preemption events, and the
router adds fleet-level rejections and re-routes. :class:`AutoscalePolicy`
is the pure decision function over a trailing window of that merged stream
(plus the router's slot-occupancy snapshot):

- **scale up** when the fleet is refusing work it was asked to do — the
  fleet-level rejection rate crosses ``shed_rate_up``, or deadline misses
  are both present and TRENDING up across the window (the leading edge of
  the Gemma-paper capacity-vs-SLO degradation curve a single replica
  cannot flatten);
- **scale down** when the window shows no rejections and no misses AND the
  fleet's remaining work would fit the surviving replicas with headroom
  (occupancy below ``down_occupancy`` of the post-retire fleet) — executed
  as drain-then-retire, never an abrupt close;
- **hold** otherwise, and always inside ``cooldown_s`` of the last action
  (capacity changes must observe their own effect before the next one).

Replica SIZING is not decided here: a new replica's slot count comes from
the AOT fit ladder (``runtime/aot.serving_admission_limit`` /
``fleet_replica_plan`` — compile-time verdicts, ``ServingConfig(
num_slots="auto")``); the policy only decides HOW MANY such replicas run.

:class:`FleetAutoscaler` binds a policy to a router and a
``replica_factory``; drivers call :meth:`FleetAutoscaler.tick` at their own
cadence (the event window, not the tick rate, sets the reaction speed).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from .router import ReplicaRouter


def summarize_events(events: Iterable[Dict[str, Any]], now: float,
                     window_s: float) -> Dict[str, Any]:
    """Reduce a merged fleet event stream to the window aggregates the
    policy consumes. ``events`` are dicts with ``unix_time``/``event``
    (the router's in-memory window, or :func:`~...resilience.events.
    read_events` over per-replica logs). ``miss_trend`` is late-half minus
    early-half deadline misses — positive means the SLO is degrading
    *within* the window, not just loaded."""
    lo = now - float(window_s)
    mid = now - float(window_s) / 2.0
    routed = rejected = misses_early = misses_late = reroutes = 0
    spec_windows = spec_drafted = spec_accepted = 0
    spec_tokens = 0.0
    imiss_early = imiss_late = 0
    by_tenant: Dict[str, Dict[str, float]] = {}
    by_tier: Dict[str, Dict[str, float]] = {}

    def _bump(e, key, amount=1.0):
        # per-tenant/per-tier attribution: any event stamped with
        # tenant_id/tier (scheduler ledger rows, fleet rejections) lands in
        # a merged row — the billing/brownout signal, fleet-wide
        for table, ident in ((by_tenant, e.get("tenant_id")),
                             (by_tier, e.get("tier"))):
            if ident is None:
                continue
            row = table.setdefault(str(ident), {
                "finished": 0, "goodput_tokens": 0.0, "shed": 0,
                "deadline_misses": 0, "preemptions": 0})
            row[key] += amount

    for e in events:
        t = float(e.get("unix_time", 0.0))
        if t < lo or t > now:
            continue
        ev = e.get("event")
        if ev == "request_routed":
            routed += 1
        elif ev == "fleet_reject":
            rejected += 1
            _bump(e, "shed")
        elif ev == "request_shed":
            _bump(e, "shed")
        elif ev == "request_finished":
            _bump(e, "finished")
            _bump(e, "goodput_tokens", float(e.get("tokens", 0)))
        elif ev == "preemption":
            _bump(e, "preemptions")
        elif ev == "deadline_miss":
            _bump(e, "deadline_misses")
            if e.get("tier") == "interactive":
                if t >= mid:
                    imiss_late += 1
                else:
                    imiss_early += 1
            if t >= mid:
                misses_late += 1
            else:
                misses_early += 1
        elif ev == "request_rerouted":
            reroutes += 1
        elif ev == "spec_window":
            # per-step speculation ledger rows from every replica's
            # scheduler — merged here so the fleet-level accept rate and
            # multi-token multiplier are autoscaler inputs like shed rate
            spec_windows += 1
            spec_drafted += int(e.get("drafted", 0))
            spec_accepted += int(e.get("accepted", 0))
            spec_tokens += float(e.get("value", 0.0))
    submitted = routed + rejected
    misses = misses_early + misses_late
    out = {
        "window_s": float(window_s),
        "submitted": submitted,
        "routed": routed,
        "rejected": rejected,
        "shed_rate": rejected / submitted if submitted else 0.0,
        "deadline_misses": misses,
        "miss_trend": misses_late - misses_early,
        "reroutes": reroutes,
    }
    if spec_windows:
        out["spec_windows"] = spec_windows
        out["spec_accept_rate"] = spec_accepted / max(spec_drafted, 1)
        out["spec_tokens_per_dispatch"] = spec_tokens / spec_windows
    if by_tenant or by_tier:
        # tiered keys appear only when tenant-stamped events exist — the
        # untiered summary schema is unchanged
        out["by_tenant"] = by_tenant
        out["by_tier"] = by_tier
        out["interactive_misses"] = imiss_early + imiss_late
        out["interactive_miss_trend"] = imiss_late - imiss_early
    return out


@dataclasses.dataclass
class AutoscalePolicy:
    """Pure scale decision over one window summary (module docstring)."""

    min_replicas: int = 1
    max_replicas: int = 8
    window_s: float = 10.0
    cooldown_s: float = 10.0
    #: fleet-level rejection rate that demands more capacity
    shed_rate_up: float = 0.05
    #: deadline misses below this floor never trigger a scale-up (tiny
    #: absolute counts trend noisily)
    miss_floor: int = 2
    #: scale down only when current occupancy would still fit the
    #: POST-RETIRE fleet below this utilization
    down_occupancy: float = 0.7

    def decide(self, summary: Dict[str, Any], num_replicas: int,
               occupancy: float, now: float,
               last_action_t: Optional[float] = None) -> str:
        """-> ``"scale_up"`` | ``"scale_down"`` | ``"hold"``."""
        if last_action_t is not None and now - last_action_t < self.cooldown_s:
            return "hold"
        overloaded = (
            summary.get("shed_rate", 0.0) > self.shed_rate_up
            or (summary.get("deadline_misses", 0) >= self.miss_floor
                and summary.get("miss_trend", 0) > 0)
            # interactive-tier misses trending up demand capacity even when
            # the fleet-wide trend is flat (batch absorbing the pain must
            # not mask an interactive SLO breach)
            or (summary.get("interactive_misses", 0) >= self.miss_floor
                and summary.get("interactive_miss_trend", 0) > 0))
        if overloaded and num_replicas < self.max_replicas:
            return "scale_up"
        quiet = (summary.get("rejected", 0) == 0
                 and summary.get("deadline_misses", 0) == 0)
        if quiet and num_replicas > self.min_replicas:
            # would the work fit n-1 replicas with headroom?
            projected = occupancy * num_replicas / max(num_replicas - 1, 1)
            if projected < self.down_occupancy:
                return "scale_down"
        return "hold"


class FleetAutoscaler:
    """Apply :class:`AutoscalePolicy` decisions to a router.

    ``replica_factory(replica_id) -> handle`` builds a new replica (the
    factory owns sizing — typically ``ServingConfig(num_slots="auto")``,
    which resolves through ``runtime/aot.serving_admission_limit``).
    Scale-down picks the least-loaded live replica and drains it; the
    router retires it once its accepted work finishes.
    """

    def __init__(self, router: ReplicaRouter, policy: AutoscalePolicy,
                 replica_factory: Callable[[str], Any],
                 clock=time.time):
        self.router = router
        self.policy = policy
        self.replica_factory = replica_factory
        self.clock = clock
        self._last_action_t: Optional[float] = None
        self._spawned = 0
        self.decisions: List[Dict[str, Any]] = []

    def tick(self, now: Optional[float] = None) -> str:
        now = self.clock() if now is None else now
        summary = summarize_events(self.router.events, now,
                                   self.policy.window_s)
        live = self.router.live_replicas
        decision = self.policy.decide(summary, len(live),
                                      self.router.occupancy(), now,
                                      self._last_action_t)
        if decision == "scale_up":
            self._spawned += 1
            rep = self.replica_factory(f"scale{self._spawned}")
            self.router.add_replica(rep)
            self._last_action_t = now
        elif decision == "scale_down":
            victim = min(live, key=lambda r:
                         (self.router._load_score(r), r.replica_id))
            self.router.retire(victim.replica_id)
            self._last_action_t = now
        self.decisions.append({"t": now, "decision": decision,
                               "summary": summary,
                               "replicas": len(self.router.live_replicas)})
        if decision != "hold":
            self.router._record("autoscale_decision", decision=decision,
                                replicas=len(self.router.live_replicas))
        return decision


__all__ = ["AutoscalePolicy", "FleetAutoscaler", "summarize_events"]
