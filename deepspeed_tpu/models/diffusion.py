"""Diffusion (Stable-Diffusion-family) inference models, TPU-native.

Capability parity with the reference's diffusers inference surface — the
CLIP/UNet/VAE injection policies (``model_implementations/diffusers/unet.py``,
``vae.py``, ``module_inject/containers/{clip,unet,vae}.py``) and the spatial
kernels (``csrc/spatial/csrc/opt_bias_add.cu``) — rebuilt as functional JAX:

- a conditional **UNet2D** (timestep sinusoidal embedding + MLP, residual conv
  blocks with GroupNorm/SiLU, self- and cross-attention at low resolution,
  skip connections) in NHWC layout so XLA tiles convs onto the MXU directly;
- a **VAE decoder** (conv + nearest-upsample stacks) mapping latents to images;
- a **DDIM sampler** with classifier-free guidance, expressed as ``lax.scan``
  over a precomputed timestep/alpha schedule — the whole sampling loop is ONE
  compiled program (the reference gets loop fusion from CUDA graphs; here the
  compiled scan IS the captured graph).

The fused bias-add/GroupNorm/attention ops the reference hand-writes in CUDA
are left to XLA fusion (NHWC elementwise chains fuse into the convolutions).
Weights import from HF diffusers checkpoints via the standard policy route;
this module owns architecture + sampling.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


# ----------------------------------------------------------------- primitives
def conv2d(x: jnp.ndarray, w: jnp.ndarray, b: Optional[jnp.ndarray] = None,
           stride: int = 1, padding: str = "SAME") -> jnp.ndarray:
    """NHWC conv; w: [kh, kw, cin, cout]."""
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        y = y + b
    return y


def group_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               groups: int = 8, eps: float = 1e-5) -> jnp.ndarray:
    """GroupNorm over NHWC channels (fp32 statistics)."""
    n, h, w, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    x32 = x.astype(jnp.float32).reshape(n, h, w, g, c // g)
    mu = jnp.mean(x32, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(x32, axis=(1, 2, 4), keepdims=True)
    y = ((x32 - mu) * jax.lax.rsqrt(var + eps)).reshape(n, h, w, c)
    return (y * scale + bias).astype(x.dtype)


def _silu(x):
    return x * jax.nn.sigmoid(x)


def timestep_embedding(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Sinusoidal timestep features [B, dim] (standard DDPM embedding)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


# ----------------------------------------------------------------- config
@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4              # latent channels
    out_channels: int = 4
    base_channels: int = 64
    channel_mults: Tuple[int, ...] = (1, 2)
    text_dim: int = 64                # cross-attention context width
    n_head: int = 4
    time_dim: int = 128
    groups: int = 8


@dataclasses.dataclass(frozen=True)
class VAEDecoderConfig:
    latent_channels: int = 4
    base_channels: int = 32
    out_channels: int = 3
    upsamples: int = 2                # latent 8x8 -> image 32x32 at 2
    scaling_factor: float = 0.18215   # SD latent scaling


# ----------------------------------------------------------------- init
def _conv_init(key, kh, kw, cin, cout, scale=1.0):
    fan = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (
        scale / np.sqrt(fan))


def _dense_init(key, cin, cout, scale=1.0):
    return jax.random.normal(key, (cin, cout), jnp.float32) * (scale / np.sqrt(cin))


def _res_block_init(key, cin, cout, time_dim):
    k = jax.random.split(key, 4)
    p = {
        "gn1_s": jnp.ones((cin,)), "gn1_b": jnp.zeros((cin,)),
        "conv1_w": _conv_init(k[0], 3, 3, cin, cout), "conv1_b": jnp.zeros((cout,)),
        "time_w": _dense_init(k[1], time_dim, cout), "time_b": jnp.zeros((cout,)),
        "gn2_s": jnp.ones((cout,)), "gn2_b": jnp.zeros((cout,)),
        "conv2_w": _conv_init(k[2], 3, 3, cout, cout, scale=0.1),
        "conv2_b": jnp.zeros((cout,)),
    }
    if cin != cout:
        p["skip_w"] = _conv_init(k[3], 1, 1, cin, cout)
    return p


def _attn_init(key, c, ctx_dim, n_head):
    k = jax.random.split(key, 5)
    return {
        "gn_s": jnp.ones((c,)), "gn_b": jnp.zeros((c,)),
        "q_w": _dense_init(k[0], c, c),
        "k_w": _dense_init(k[1], ctx_dim, c),
        "v_w": _dense_init(k[2], ctx_dim, c),
        "o_w": _dense_init(k[3], c, c, scale=0.1),
        "o_b": jnp.zeros((c,)),
    }


def init_unet(cfg: UNetConfig, rng: jax.Array) -> Dict[str, Any]:
    keys = iter(jax.random.split(rng, 64))
    ch = [cfg.base_channels * m for m in cfg.channel_mults]
    td = cfg.time_dim
    p: Dict[str, Any] = {
        "time_w1": _dense_init(next(keys), td, td), "time_b1": jnp.zeros((td,)),
        "time_w2": _dense_init(next(keys), td, td), "time_b2": jnp.zeros((td,)),
        "in_w": _conv_init(next(keys), 3, 3, cfg.in_channels, ch[0]),
        "in_b": jnp.zeros((ch[0],)),
        "down": [], "up": [],
    }
    cur = ch[0]
    for c in ch:
        p["down"].append({
            "res": _res_block_init(next(keys), cur, c, td),
            "down_w": _conv_init(next(keys), 3, 3, c, c),
            "down_b": jnp.zeros((c,)),
        })
        cur = c
    p["mid_res1"] = _res_block_init(next(keys), cur, cur, td)
    p["mid_self"] = _attn_init(next(keys), cur, cur, cfg.n_head)
    p["mid_cross"] = _attn_init(next(keys), cur, cfg.text_dim, cfg.n_head)
    p["mid_res2"] = _res_block_init(next(keys), cur, cur, td)
    for c in reversed(ch):
        p["up"].append({
            # upsample conv maps the previous level's channels -> this level's;
            # the residual block consumes [conv out (c) || skip (c)] = 2c
            "res": _res_block_init(next(keys), 2 * c, c, td),
            "up_w": _conv_init(next(keys), 3, 3, cur, c),
            "up_b": jnp.zeros((c,)),
        })
        cur = c
    p["out_gn_s"] = jnp.ones((cur,))
    p["out_gn_b"] = jnp.zeros((cur,))
    p["out_w"] = _conv_init(next(keys), 3, 3, cur, cfg.out_channels, scale=0.1)
    p["out_b"] = jnp.zeros((cfg.out_channels,))
    return p


def init_vae_decoder(cfg: VAEDecoderConfig, rng: jax.Array) -> Dict[str, Any]:
    keys = iter(jax.random.split(rng, 16))
    c = cfg.base_channels
    p: Dict[str, Any] = {
        "in_w": _conv_init(next(keys), 3, 3, cfg.latent_channels, c),
        "in_b": jnp.zeros((c,)),
        "blocks": [],
    }
    for _ in range(cfg.upsamples):
        p["blocks"].append({
            "gn_s": jnp.ones((c,)), "gn_b": jnp.zeros((c,)),
            "conv_w": _conv_init(next(keys), 3, 3, c, c),
            "conv_b": jnp.zeros((c,)),
        })
    p["out_gn_s"] = jnp.ones((c,))
    p["out_gn_b"] = jnp.zeros((c,))
    p["out_w"] = _conv_init(next(keys), 3, 3, c, cfg.out_channels, scale=0.1)
    p["out_b"] = jnp.zeros((cfg.out_channels,))
    return p


# ----------------------------------------------------------------- apply
def _res_block(cfg: UNetConfig, p, x, temb):
    h = group_norm(x, p["gn1_s"], p["gn1_b"], cfg.groups)
    h = conv2d(_silu(h), p["conv1_w"], p["conv1_b"])
    h = h + (_silu(temb) @ p["time_w"] + p["time_b"])[:, None, None, :]
    h = group_norm(h, p["gn2_s"], p["gn2_b"], cfg.groups)
    h = conv2d(_silu(h), p["conv2_w"], p["conv2_b"])
    skip = conv2d(x, p["skip_w"]) if "skip_w" in p else x
    return h + skip


def _attention(cfg: UNetConfig, p, x, context=None):
    """Spatial (self or cross) attention at [B, H, W, C]."""
    B, H, W, C = x.shape
    h = group_norm(x, p["gn_s"], p["gn_b"], cfg.groups)
    q = h.reshape(B, H * W, C) @ p["q_w"]
    ctx = h.reshape(B, H * W, C) if context is None else context
    k = ctx @ p["k_w"]
    v = ctx @ p["v_w"]
    nh = cfg.n_head
    dh = C // nh

    def split(t):
        return t.reshape(B, -1, nh, dh).transpose(0, 2, 1, 3)

    s = jnp.einsum("bhqd,bhkd->bhqk", split(q), split(k)) / np.sqrt(dh)
    a = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", a, split(v))
    o = o.transpose(0, 2, 1, 3).reshape(B, H * W, C) @ p["o_w"] + p["o_b"]
    return x + o.reshape(B, H, W, C)


def apply_unet(cfg: UNetConfig, params, latents: jnp.ndarray, t: jnp.ndarray,
               text_emb: jnp.ndarray) -> jnp.ndarray:
    """Predict noise. latents [B,H,W,Cin]; t [B]; text_emb [B,S,text_dim]."""
    temb = timestep_embedding(t, cfg.time_dim).astype(latents.dtype)
    temb = _silu(temb @ params["time_w1"] + params["time_b1"])
    temb = temb @ params["time_w2"] + params["time_b2"]

    x = conv2d(latents, params["in_w"], params["in_b"])
    skips = []
    for blk in params["down"]:
        x = _res_block(cfg, blk["res"], x, temb)
        skips.append(x)
        x = conv2d(x, blk["down_w"], blk["down_b"], stride=2)
    x = _res_block(cfg, params["mid_res1"], x, temb)
    x = _attention(cfg, params["mid_self"], x)
    x = _attention(cfg, params["mid_cross"], x, context=text_emb)
    x = _res_block(cfg, params["mid_res2"], x, temb)
    for blk in params["up"]:
        # nearest-neighbor upsample then conv (SD's Upsample2D)
        B, H, W, C = x.shape
        x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
        x = conv2d(x, blk["up_w"], blk["up_b"])
        x = jnp.concatenate([x, skips.pop()], axis=-1)
        x = _res_block(cfg, blk["res"], x, temb)
    x = group_norm(x, params["out_gn_s"], params["out_gn_b"], cfg.groups)
    return conv2d(_silu(x), params["out_w"], params["out_b"])


def apply_vae_decoder(cfg: VAEDecoderConfig, params, latents: jnp.ndarray
                      ) -> jnp.ndarray:
    """Latents [B,h,w,Cl] -> images [B, h*2^U, w*2^U, 3] in [-1, 1]."""
    x = conv2d(latents / cfg.scaling_factor, params["in_w"], params["in_b"])
    for blk in params["blocks"]:
        B, H, W, C = x.shape
        x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
        x = group_norm(x, blk["gn_s"], blk["gn_b"])
        x = conv2d(_silu(x), blk["conv_w"], blk["conv_b"])
    x = group_norm(x, params["out_gn_s"], params["out_gn_b"])
    return jnp.tanh(conv2d(_silu(x), params["out_w"], params["out_b"]))


# ----------------------------------------------------------------- sampler
def ddim_schedule(num_steps: int, num_train_timesteps: int = 1000,
                  beta_start: float = 8.5e-4, beta_end: float = 1.2e-2):
    """Precomputed (timesteps [S], alpha_bar [S+1]) for DDIM (scaled-linear
    betas, the SD schedule)."""
    betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5,
                        num_train_timesteps) ** 2
    alpha_bar = np.cumprod(1.0 - betas)
    step = num_train_timesteps // num_steps
    ts = np.arange(num_train_timesteps - 1, -1, -step)[:num_steps]
    abar = alpha_bar[ts]
    abar_prev = np.concatenate([alpha_bar[ts[1:]], [1.0]])
    return (jnp.asarray(ts, jnp.int32), jnp.asarray(abar, jnp.float32),
            jnp.asarray(abar_prev, jnp.float32))


def ddim_sample(cfg: UNetConfig, params, latents: jnp.ndarray,
                text_emb: jnp.ndarray, uncond_emb: jnp.ndarray,
                num_steps: int = 20, guidance_scale: float = 7.5,
                apply_fn=None) -> jnp.ndarray:
    """Deterministic DDIM (eta=0) with classifier-free guidance, as one scan.

    Parity: the reference's patched SD pipeline loop under CUDA graphs
    (``model_implementations/diffusers/unet.py`` forward + graph replay).
    ``apply_fn(cfg, params, latents, t, ctx)`` selects the denoiser —
    defaults to the lightweight :func:`apply_unet`; pass
    ``models.sd_unet.apply_sd_unet`` to drive the faithful SD-1.x UNet.
    """
    ts, abar, abar_prev = ddim_schedule(num_steps)
    B = latents.shape[0]
    fn = apply_fn or apply_unet
    ctx = jnp.concatenate([text_emb, uncond_emb], axis=0)  # one batched UNet call

    def step(x, sched):
        t, ab, ab_prev = sched
        tb = jnp.full((2 * B,), t, jnp.int32)
        eps_both = fn(cfg, params, jnp.concatenate([x, x], axis=0), tb, ctx)
        eps_c, eps_u = eps_both[:B], eps_both[B:]
        eps = eps_u + guidance_scale * (eps_c - eps_u)
        x0 = (x - jnp.sqrt(1.0 - ab) * eps) / jnp.sqrt(ab)
        x = jnp.sqrt(ab_prev) * x0 + jnp.sqrt(1.0 - ab_prev) * eps
        return x, None

    latents, _ = jax.lax.scan(step, latents, (ts, abar, abar_prev))
    return latents


def clip_text_embeddings(cfg, params, input_ids) -> jnp.ndarray:
    """Text conditioning from an imported CLIP text tower
    (``module_inject``'s ``CLIPTextModel`` policy): the final-LN hidden states
    [B, S, D] fed to the UNet's cross-attention."""
    from . import gpt as G

    return G.forward(cfg, params, input_ids, train=False, return_hidden=True)


# ----------------------------------------------------------------- pipeline
@dataclasses.dataclass
class StableDiffusionPipeline:
    """Latent-diffusion text-to-image inference. Parity surface: the engine's
    diffusers path (``init_inference`` on an SD pipeline; CLIP text encoding is
    supplied by the caller as embeddings — any encoder works)."""

    unet_cfg: UNetConfig
    vae_cfg: VAEDecoderConfig
    unet_params: Any
    vae_params: Any
    latent_size: int = 8

    @classmethod
    def init_random(cls, rng: jax.Array, unet_cfg: Optional[UNetConfig] = None,
                    vae_cfg: Optional[VAEDecoderConfig] = None,
                    latent_size: int = 8) -> "StableDiffusionPipeline":
        unet_cfg = unet_cfg or UNetConfig()
        vae_cfg = vae_cfg or VAEDecoderConfig()
        k1, k2 = jax.random.split(rng)
        return cls(unet_cfg, vae_cfg, init_unet(unet_cfg, k1),
                   init_vae_decoder(vae_cfg, k2), latent_size)

    @functools.cached_property
    def _jitted(self):
        def fn(unet_params, vae_params, text_emb, uncond_emb, noise,
               guidance_scale, num_steps):
            lat = ddim_sample(self.unet_cfg, unet_params, noise, text_emb,
                              uncond_emb, num_steps=num_steps,
                              guidance_scale=guidance_scale)
            return apply_vae_decoder(self.vae_cfg, vae_params, lat)

        return jax.jit(fn, static_argnames=("num_steps",))

    def __call__(self, text_emb: jnp.ndarray, uncond_emb: jnp.ndarray,
                 num_steps: int = 20, guidance_scale: float = 7.5,
                 seed: int = 0) -> np.ndarray:
        B = text_emb.shape[0]
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (B, self.latent_size, self.latent_size, self.unet_cfg.in_channels))
        img = self._jitted(self.unet_params, self.vae_params, text_emb,
                           uncond_emb, noise, jnp.float32(guidance_scale),
                           num_steps)
        return np.asarray(img)
