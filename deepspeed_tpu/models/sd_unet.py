"""Faithful Stable-Diffusion-1.x UNet + VAE decoder (diffusers layout).

Capability parity with the reference's diffusers integration
(``model_implementations/diffusers/unet.py``/``vae.py`` wrap the real
UNet2DConditionModel/AutoencoderKL for kernel-injected inference;
``module_inject/containers/unet.py``/``vae.py``): this module implements the
actual SD-1.x architecture — CrossAttnDownBlock2D / mid / CrossAttnUpBlock2D
with ResnetBlock2D and Transformer2DModel (self-attn, cross-attn, GEGLU) —
natively in JAX, NHWC for TPU convs.

Parameters are a FLAT dict keyed exactly like the diffusers state dict
("down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q.weight", ...),
stored TPU-side as HWIO convs and ``[in, out]`` linears, so importing a real
checkpoint (:func:`import_sd_unet_state`) is a pure layout transform with no
renaming table to maintain.

``models/diffusion.py`` keeps the lightweight skeleton + DDIM sampler; this
module provides the production architecture. The DDIM/CFG scan works with
either via the ``apply_fn`` seam.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------- config
@dataclasses.dataclass(frozen=True)
class SDUNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    n_head: int = 8
    norm_groups: int = 32
    # which down blocks carry cross-attention transformers (SD-1.x: all but
    # the last); up blocks mirror this
    cross_attn: Tuple[bool, ...] = (True, True, True, False)

    @property
    def time_dim(self) -> int:
        return self.block_out_channels[0] * 4


@dataclasses.dataclass(frozen=True)
class SDVAEDecoderConfig:
    latent_channels: int = 4
    out_channels: int = 3
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_groups: int = 32
    scaling_factor: float = 0.18215


# tiny CI-friendly variants (same topology, small widths)
TINY_UNET = SDUNetConfig(block_out_channels=(32, 64), cross_attn=(True, False),
                         cross_attention_dim=32, n_head=2, norm_groups=8)
TINY_VAE = SDVAEDecoderConfig(block_out_channels=(16, 32), norm_groups=8)


# ------------------------------------------------------------------- builders
class _Shapes:
    """Walks the architecture once to enumerate every parameter's shape —
    init and import both validate against this single source of truth."""

    def __init__(self):
        self.shapes: Dict[str, Tuple[int, ...]] = {}

    def conv(self, name, cin, cout, k=3):
        self.shapes[f"{name}.weight"] = (k, k, cin, cout)
        self.shapes[f"{name}.bias"] = (cout,)

    def linear(self, name, cin, cout, bias=True):
        self.shapes[f"{name}.weight"] = (cin, cout)
        if bias:
            self.shapes[f"{name}.bias"] = (cout,)

    def norm(self, name, c):
        self.shapes[f"{name}.weight"] = (c,)
        self.shapes[f"{name}.bias"] = (c,)

    def resnet(self, name, cin, cout, time_dim=None):
        self.norm(f"{name}.norm1", cin)
        self.conv(f"{name}.conv1", cin, cout)
        if time_dim:
            self.linear(f"{name}.time_emb_proj", time_dim, cout)
        self.norm(f"{name}.norm2", cout)
        self.conv(f"{name}.conv2", cout, cout)
        if cin != cout:
            self.conv(f"{name}.conv_shortcut", cin, cout, k=1)

    def transformer(self, name, c, ctx, n_head):
        self.norm(f"{name}.norm", c)
        self.conv(f"{name}.proj_in", c, c, k=1)
        tb = f"{name}.transformer_blocks.0"
        for ln in ("norm1", "norm2", "norm3"):
            self.norm(f"{tb}.{ln}", c)
        for qkv in ("to_q", "to_k", "to_v"):
            self.linear(f"{tb}.attn1.{qkv}", c, c, bias=False)
        self.linear(f"{tb}.attn1.to_out.0", c, c)
        self.linear(f"{tb}.attn2.to_q", c, c, bias=False)
        self.linear(f"{tb}.attn2.to_k", ctx, c, bias=False)
        self.linear(f"{tb}.attn2.to_v", ctx, c, bias=False)
        self.linear(f"{tb}.attn2.to_out.0", c, c)
        self.linear(f"{tb}.ff.net.0.proj", c, 8 * c)
        self.linear(f"{tb}.ff.net.2", 4 * c, c)
        self.conv(f"{name}.proj_out", c, c, k=1)

    def attn_single(self, name, c):
        """VAE mid-block single-head self-attention (diffusers AttnBlock)."""
        self.norm(f"{name}.group_norm", c)
        for qkv in ("to_q", "to_k", "to_v"):
            self.linear(f"{name}.{qkv}", c, c)
        self.linear(f"{name}.to_out.0", c, c)


def unet_param_shapes(cfg: SDUNetConfig) -> Dict[str, Tuple[int, ...]]:
    s = _Shapes()
    chans = cfg.block_out_channels
    td = cfg.time_dim
    s.linear("time_embedding.linear_1", chans[0], td)
    s.linear("time_embedding.linear_2", td, td)
    s.conv("conv_in", cfg.in_channels, chans[0])
    cin = chans[0]
    for bi, cout in enumerate(chans):
        for li in range(cfg.layers_per_block):
            s.resnet(f"down_blocks.{bi}.resnets.{li}",
                     cin if li == 0 else cout, cout, td)
            if cfg.cross_attn[bi]:
                s.transformer(f"down_blocks.{bi}.attentions.{li}", cout,
                              cfg.cross_attention_dim, cfg.n_head)
        if bi < len(chans) - 1:
            s.conv(f"down_blocks.{bi}.downsamplers.0.conv", cout, cout)
        cin = cout
    c_mid = chans[-1]
    s.resnet("mid_block.resnets.0", c_mid, c_mid, td)
    s.transformer("mid_block.attentions.0", c_mid, cfg.cross_attention_dim,
                  cfg.n_head)
    s.resnet("mid_block.resnets.1", c_mid, c_mid, td)
    rev = list(reversed(chans))
    rev_cross = list(reversed(cfg.cross_attn))
    prev = c_mid
    for bi, cout in enumerate(rev):
        for li in range(cfg.layers_per_block + 1):
            skip = rev[min(bi + 1, len(rev) - 1)] \
                if li == cfg.layers_per_block else rev[bi]
            # skip channels follow the down path: the LAST resnet of the up
            # block consumes the earliest (widest-to-narrowest) skip
            s.resnet(f"up_blocks.{bi}.resnets.{li}", prev + skip, cout, td)
            prev = cout
            if rev_cross[bi]:
                s.transformer(f"up_blocks.{bi}.attentions.{li}", cout,
                              cfg.cross_attention_dim, cfg.n_head)
        if bi < len(rev) - 1:
            s.conv(f"up_blocks.{bi}.upsamplers.0.conv", cout, cout)
    s.norm("conv_norm_out", chans[0])
    s.conv("conv_out", chans[0], cfg.out_channels)
    return s.shapes


def vae_decoder_param_shapes(cfg: SDVAEDecoderConfig) -> Dict[str, Tuple[int, ...]]:
    s = _Shapes()
    chans = cfg.block_out_channels
    c_top = chans[-1]
    s.conv("post_quant_conv", cfg.latent_channels, cfg.latent_channels, k=1)
    s.conv("decoder.conv_in", cfg.latent_channels, c_top)
    s.resnet("decoder.mid_block.resnets.0", c_top, c_top)
    s.attn_single("decoder.mid_block.attentions.0", c_top)
    s.resnet("decoder.mid_block.resnets.1", c_top, c_top)
    rev = list(reversed(chans))
    prev = c_top
    for bi, cout in enumerate(rev):
        for li in range(cfg.layers_per_block + 1):
            s.resnet(f"decoder.up_blocks.{bi}.resnets.{li}",
                     prev if li == 0 else cout, cout)
            prev = cout
        if bi < len(rev) - 1:
            s.conv(f"decoder.up_blocks.{bi}.upsamplers.0.conv", cout, cout)
    s.norm("decoder.conv_norm_out", chans[0])
    s.conv("decoder.conv_out", chans[0], cfg.out_channels)
    return s.shapes


def _init_from_shapes(shapes: Dict[str, Tuple[int, ...]], rng: jax.Array,
                      std: float = 0.02) -> Dict[str, jnp.ndarray]:
    params = {}
    keys = jax.random.split(rng, len(shapes))
    for k, (name, shape) in zip(keys, sorted(shapes.items())):
        if name.endswith(".bias") or (len(shape) == 1
                                      and ".norm" in name.lower()):
            params[name] = (jnp.ones(shape) if name.endswith("weight")
                            else jnp.zeros(shape))
        elif len(shape) == 1:
            params[name] = jnp.zeros(shape)
        else:
            params[name] = jax.random.normal(k, shape, jnp.float32) * std
    # norm scales are ones
    for name in shapes:
        if name.endswith(".weight") and len(shapes[name]) == 1:
            params[name] = jnp.ones(shapes[name])
    return params


def init_sd_unet(cfg: SDUNetConfig, rng: jax.Array) -> Dict[str, jnp.ndarray]:
    return _init_from_shapes(unet_param_shapes(cfg), rng)


def init_sd_vae_decoder(cfg: SDVAEDecoderConfig,
                        rng: jax.Array) -> Dict[str, jnp.ndarray]:
    return _init_from_shapes(vae_decoder_param_shapes(cfg), rng)


# ------------------------------------------------------------------- forward
def _conv(p, name, x, stride=1):
    w = p[f"{name}.weight"]
    pad = "SAME" if w.shape[0] > 1 else "VALID"
    y = lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + p[f"{name}.bias"].astype(x.dtype)


def _linear(p, name, x):
    y = x @ p[f"{name}.weight"].astype(x.dtype)
    b = p.get(f"{name}.bias")
    return y if b is None else y + b.astype(x.dtype)


def _group_norm(p, name, x, groups):
    B, H, W, C = x.shape
    g = x.reshape(B, H, W, groups, C // groups).astype(jnp.float32)
    mu = g.mean(axis=(1, 2, 4), keepdims=True)
    var = g.var(axis=(1, 2, 4), keepdims=True)
    g = (g - mu) * lax.rsqrt(var + 1e-6)
    out = g.reshape(B, H, W, C)
    return (out * p[f"{name}.weight"] + p[f"{name}.bias"]).astype(x.dtype)


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _resnet(p, name, x, temb, groups):
    h = _conv(p, f"{name}.conv1", _silu(_group_norm(p, f"{name}.norm1", x,
                                                    groups)))
    if temb is not None and f"{name}.time_emb_proj.weight" in p:
        h = h + _linear(p, f"{name}.time_emb_proj", _silu(temb))[:, None, None, :]
    h = _conv(p, f"{name}.conv2", _silu(_group_norm(p, f"{name}.norm2", h,
                                                    groups)))
    if f"{name}.conv_shortcut.weight" in p:
        x = _conv(p, f"{name}.conv_shortcut", x)
    return x + h


def _mha(q, k, v, n_head):
    B, Tq, C = q.shape
    Dh = C // n_head
    q = q.reshape(B, Tq, n_head, Dh).transpose(0, 2, 1, 3)
    k = k.reshape(B, -1, n_head, Dh).transpose(0, 2, 1, 3)
    v = v.reshape(B, -1, n_head, Dh).transpose(0, 2, 1, 3)
    a = jax.nn.softmax(
        (q @ k.transpose(0, 1, 3, 2)).astype(jnp.float32) / np.sqrt(Dh),
        axis=-1).astype(q.dtype)
    return (a @ v).transpose(0, 2, 1, 3).reshape(B, Tq, C)


def _layer_norm(p, name, x):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    h = (x - mu) * lax.rsqrt(var + 1e-5)
    return h * p[f"{name}.weight"] + p[f"{name}.bias"]


def _transformer(p, name, x, context, n_head, groups):
    B, H, W, C = x.shape
    res = x
    h = _group_norm(p, f"{name}.norm", x, groups)
    h = _conv(p, f"{name}.proj_in", h).reshape(B, H * W, C)
    tb = f"{name}.transformer_blocks.0"
    # self-attention
    hn = _layer_norm(p, f"{tb}.norm1", h)
    h = h + _linear(p, f"{tb}.attn1.to_out.0", _mha(
        _linear(p, f"{tb}.attn1.to_q", hn),
        _linear(p, f"{tb}.attn1.to_k", hn),
        _linear(p, f"{tb}.attn1.to_v", hn), n_head))
    # cross-attention over the text context
    hn = _layer_norm(p, f"{tb}.norm2", h)
    h = h + _linear(p, f"{tb}.attn2.to_out.0", _mha(
        _linear(p, f"{tb}.attn2.to_q", hn),
        _linear(p, f"{tb}.attn2.to_k", context.astype(hn.dtype)),
        _linear(p, f"{tb}.attn2.to_v", context.astype(hn.dtype)), n_head))
    # GEGLU feed-forward
    hn = _layer_norm(p, f"{tb}.norm3", h)
    up = _linear(p, f"{tb}.ff.net.0.proj", hn)
    a, b = jnp.split(up, 2, axis=-1)
    h = h + _linear(p, f"{tb}.ff.net.2", a * jax.nn.gelu(b))
    h = h.reshape(B, H, W, C)
    return res + _conv(p, f"{name}.proj_out", h)


def _timestep_embedding(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def apply_sd_unet(cfg: SDUNetConfig, p: Dict[str, jnp.ndarray],
                  latents: jnp.ndarray, t: jnp.ndarray,
                  context: jnp.ndarray) -> jnp.ndarray:
    """UNet2DConditionModel forward: ``latents`` [B, H, W, C_in] (NHWC),
    ``t`` [B] timesteps, ``context`` [B, S, ctx_dim] text embeddings."""
    G = cfg.norm_groups
    temb = _timestep_embedding(t, cfg.block_out_channels[0])
    temb = _linear(p, "time_embedding.linear_2",
                   _silu(_linear(p, "time_embedding.linear_1", temb)))
    x = _conv(p, "conv_in", latents)
    skips: List[jnp.ndarray] = [x]
    chans = cfg.block_out_channels
    for bi in range(len(chans)):
        for li in range(cfg.layers_per_block):
            x = _resnet(p, f"down_blocks.{bi}.resnets.{li}", x, temb, G)
            if cfg.cross_attn[bi]:
                x = _transformer(p, f"down_blocks.{bi}.attentions.{li}", x,
                                 context, cfg.n_head, G)
            skips.append(x)
        if bi < len(chans) - 1:
            x = _conv(p, f"down_blocks.{bi}.downsamplers.0.conv", x, stride=2)
            skips.append(x)
    x = _resnet(p, "mid_block.resnets.0", x, temb, G)
    x = _transformer(p, "mid_block.attentions.0", x, context, cfg.n_head, G)
    x = _resnet(p, "mid_block.resnets.1", x, temb, G)
    rev_cross = list(reversed(cfg.cross_attn))
    for bi in range(len(chans)):
        for li in range(cfg.layers_per_block + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = _resnet(p, f"up_blocks.{bi}.resnets.{li}", x, temb, G)
            if rev_cross[bi]:
                x = _transformer(p, f"up_blocks.{bi}.attentions.{li}", x,
                                 context, cfg.n_head, G)
        if bi < len(chans) - 1:
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
            x = _conv(p, f"up_blocks.{bi}.upsamplers.0.conv", x)
    x = _silu(_group_norm(p, "conv_norm_out", x, G))
    return _conv(p, "conv_out", x)


def apply_sd_vae_decoder(cfg: SDVAEDecoderConfig, p: Dict[str, jnp.ndarray],
                         latents: jnp.ndarray) -> jnp.ndarray:
    """AutoencoderKL.decode: latents [B, h, w, 4] -> images [B, 8h, 8w, 3]
    (for the SD-1.x 4-scale decoder) in [-1, 1]."""
    G = cfg.norm_groups
    x = _conv(p, "post_quant_conv", latents / cfg.scaling_factor)
    x = _conv(p, "decoder.conv_in", x)
    x = _resnet(p, "decoder.mid_block.resnets.0", x, None, G)
    # single-head attention block
    B, H, W, C = x.shape
    h = _group_norm(p, "decoder.mid_block.attentions.0.group_norm", x, G)
    h = h.reshape(B, H * W, C)
    base = "decoder.mid_block.attentions.0"
    h = _linear(p, f"{base}.to_out.0", _mha(
        _linear(p, f"{base}.to_q", h), _linear(p, f"{base}.to_k", h),
        _linear(p, f"{base}.to_v", h), 1))
    x = x + h.reshape(B, H, W, C)
    x = _resnet(p, "decoder.mid_block.resnets.1", x, None, G)
    chans = cfg.block_out_channels
    for bi in range(len(chans)):
        for li in range(cfg.layers_per_block + 1):
            x = _resnet(p, f"decoder.up_blocks.{bi}.resnets.{li}", x, None, G)
        if bi < len(chans) - 1:
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
            x = _conv(p, f"decoder.up_blocks.{bi}.upsamplers.0.conv", x)
    x = _silu(_group_norm(p, "decoder.conv_norm_out", x, G))
    return _conv(p, "decoder.conv_out", x)


# -------------------------------------------------------------------- import
def import_sd_unet_state(sd: Dict[str, Any],
                         cfg: Optional[SDUNetConfig] = None,
                         n_head: int = 8, norm_groups: int = 32
                         ) -> Tuple[SDUNetConfig, Dict[str, jnp.ndarray]]:
    """Convert a diffusers UNet state dict (torch layout) to this module's
    params: conv ``[out, in, kh, kw] -> [kh, kw, in, out]``, linear
    ``[out, in] -> [in, out]``, keys unchanged. ``cfg`` is inferred from the
    shapes when omitted — except ``n_head``/``norm_groups``, which leave no
    shape trace (defaults are SD-1.x's 8 heads / 32 groups; pass the model's
    real values for other families)."""
    if cfg is None:
        chans = []
        bi = 0
        while f"down_blocks.{bi}.resnets.0.conv1.weight" in sd:
            chans.append(sd[f"down_blocks.{bi}.resnets.0.conv1.weight"].shape[0])
            bi += 1
        cross = tuple(f"down_blocks.{b}.attentions.0.norm.weight" in sd
                      for b in range(bi))
        ctx_key = next((k for k in sd if k.endswith("attn2.to_k.weight")), None)
        ctx = sd[ctx_key].shape[1] if ctx_key is not None else 768
        cfg = SDUNetConfig(
            in_channels=sd["conv_in.weight"].shape[1],
            out_channels=sd["conv_out.weight"].shape[0],
            block_out_channels=tuple(chans), cross_attn=cross,
            cross_attention_dim=int(ctx), n_head=n_head,
            norm_groups=norm_groups)
    params = _convert_torch_state(sd)
    _validate(params, unet_param_shapes(cfg), "UNet")
    return cfg, params


def import_sd_vae_decoder_state(sd: Dict[str, Any],
                                cfg: Optional[SDVAEDecoderConfig] = None,
                                norm_groups: int = 32
                                ) -> Tuple[SDVAEDecoderConfig,
                                           Dict[str, jnp.ndarray]]:
    """Same conversion for the AutoencoderKL decoder subtree (keys
    ``decoder.*`` and ``post_quant_conv.*``; encoder keys are ignored)."""
    sd = {k: v for k, v in sd.items()
          if k.startswith(("decoder.", "post_quant_conv."))}
    if cfg is None:
        chans = []
        bi = 0
        while f"decoder.up_blocks.{bi}.resnets.0.conv1.weight" in sd:
            chans.append(
                sd[f"decoder.up_blocks.{bi}.resnets.0.conv1.weight"].shape[0])
            bi += 1
        cfg = SDVAEDecoderConfig(
            latent_channels=sd["post_quant_conv.weight"].shape[0],
            out_channels=sd["decoder.conv_out.weight"].shape[0],
            block_out_channels=tuple(reversed(chans)),
            norm_groups=norm_groups)
    params = _convert_torch_state(sd)
    _validate(params, vae_decoder_param_shapes(cfg), "VAE decoder")
    return cfg, params


def _read_component_state(root: str, name: str) -> Dict[str, Any]:
    """Read ``<root>/<name>/diffusion_pytorch_model.{safetensors,bin}`` — the
    diffusers on-disk layout the reference's SD path consumes."""
    import os

    base = os.path.join(root, name, "diffusion_pytorch_model")
    if os.path.exists(base + ".safetensors"):
        from safetensors import safe_open

        sd = {}
        with safe_open(base + ".safetensors", framework="np") as f:
            for k in f.keys():
                sd[k] = f.get_tensor(k)
        return sd
    if os.path.exists(base + ".bin"):
        import torch

        return torch.load(base + ".bin", map_location="cpu",
                          weights_only=False)
    raise FileNotFoundError(f"{base}.safetensors|.bin not found")


@dataclasses.dataclass
class SDPipeline:
    """Text-to-image inference on the FAITHFUL SD-1.x architecture: DDIM +
    classifier-free guidance + VAE decode as one compiled program (the
    sampling scan and schedule live in ``models/diffusion.py``)."""

    unet_cfg: SDUNetConfig
    vae_cfg: SDVAEDecoderConfig
    unet_params: Dict[str, jnp.ndarray]
    vae_params: Dict[str, jnp.ndarray]
    latent_size: int = 64

    @classmethod
    def from_diffusers_dir(cls, root: str, n_head: int = 8,
                           norm_groups: int = 32,
                           latent_size: int = 64) -> "SDPipeline":
        """Load ``unet/`` and ``vae/`` component weights from a local
        Stable-Diffusion checkpoint directory (diffusers layout)."""
        ucfg, up = import_sd_unet_state(
            _read_component_state(root, "unet"), n_head=n_head,
            norm_groups=norm_groups)
        vcfg, vp = import_sd_vae_decoder_state(
            _read_component_state(root, "vae"), norm_groups=norm_groups)
        return cls(ucfg, vcfg, up, vp, latent_size)

    def _jitted(self, num_steps: int):
        """One compiled program per num_steps, cached for the pipeline's
        lifetime (a per-call jit would recompile the full DDIM+UNet+VAE
        program for every image)."""
        cache = self.__dict__.setdefault("_jit_cache", {})
        if num_steps not in cache:
            from .diffusion import ddim_sample

            def fn(unet_params, vae_params, text, uncond, x, gs):
                lat = ddim_sample(self.unet_cfg, unet_params, x, text, uncond,
                                  num_steps=num_steps, guidance_scale=gs,
                                  apply_fn=apply_sd_unet)
                return apply_sd_vae_decoder(self.vae_cfg, vae_params, lat)

            cache[num_steps] = jax.jit(fn)
        return cache[num_steps]

    def __call__(self, text_emb: jnp.ndarray, uncond_emb: jnp.ndarray,
                 num_steps: int = 20, guidance_scale: float = 7.5,
                 seed: int = 0) -> np.ndarray:
        B = text_emb.shape[0]
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (B, self.latent_size, self.latent_size,
             self.unet_cfg.in_channels))
        img = self._jitted(num_steps)(
            self.unet_params, self.vae_params, text_emb, uncond_emb, noise,
            jnp.float32(guidance_scale))
        return np.asarray(img)


def _np32(t) -> np.ndarray:
    try:
        import torch

        if isinstance(t, torch.Tensor):
            return t.detach().to(torch.float32).numpy()
    except ImportError:
        pass
    return np.asarray(t, np.float32)


def _convert_torch_state(sd) -> Dict[str, jnp.ndarray]:
    out = {}
    for k, v in sd.items():
        a = _np32(v)
        if a.ndim == 4:  # conv [out, in, kh, kw] -> HWIO
            a = a.transpose(2, 3, 1, 0)
        elif a.ndim == 2:  # linear [out, in] -> [in, out]
            a = a.T
        out[k] = jnp.asarray(a)
    return out


def _validate(params, shapes, what: str) -> None:
    missing = sorted(set(shapes) - set(params))
    extra = sorted(set(params) - set(shapes))
    if missing or extra:
        raise ValueError(
            f"{what} state dict mismatch: missing={missing[:5]} "
            f"(+{max(len(missing) - 5, 0)}), unexpected={extra[:5]} "
            f"(+{max(len(extra) - 5, 0)})")
    for k, want in shapes.items():
        got = tuple(params[k].shape)
        if got != tuple(want):
            raise ValueError(f"{what} {k}: shape {got} != expected {want}")
