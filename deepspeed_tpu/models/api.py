"""The module contract between models and the engine.

The reference wraps ``torch.nn.Module``; the TPU-native contract is functional — a
model is (init, apply, partition rules):

- ``init(rng) -> params``: build the parameter pytree (fp32).
- ``apply(params, batch, rngs, train) -> (loss, aux)``: pure forward + loss.
- ``partition_specs(param_shapes) -> pytree of PartitionSpec``: the *model-parallel*
  (tp/sp) placement of each leaf. ZeRO sharding is layered on top by the engine's
  :class:`~deepspeed_tpu.runtime.zero.policy.ZeroShardingPolicy`; models never think
  about data parallelism.

``Module`` is a tiny carrier for those three functions so user code can also pass
plain callables.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from ..utils.jax_compat import manual_axis_names

Params = Any
Batch = Any


def _spec_axes(spec) -> set:
    axes = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        axes.update(entry if isinstance(entry, (tuple, list)) else (entry,))
    return axes


def maybe_shard(x, spec: P):
    """``with_sharding_constraint`` that no-ops when no mesh is bound, so model code
    runs identically inside the engine (mesh context) and standalone (tests, single
    device). Also no-ops inside a ``shard_map`` body over any of the spec's axes:
    there the data is already device-local and older jax rejects the constraint at
    lowering time (newer jax silently ignores it)."""
    if _spec_axes(spec) & manual_axis_names():
        return x
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (RuntimeError, ValueError):
        return x


def replicated_specs(param_shapes) -> Any:
    """Default partitioning: every leaf replicated (pure data parallelism)."""
    return jax.tree_util.tree_map(lambda _: P(), param_shapes)


@dataclasses.dataclass(frozen=True)
class Module:
    """A trainable model: functional (init, apply, partition_specs).

    ``to_pipeline(num_stages, num_micro) -> Module`` (optional) rebuilds this
    model as its pipeline-parallel variant — layer stack sharded over the ``pp``
    mesh axis, micro-batches streamed by collective-permute pipelining. The
    engine calls it from ``initialize()`` when the mesh requests ``pp > 1``
    (parity: ``deepspeed.initialize`` returning a ``PipelineEngine`` for a
    ``PipelineModule``, ``deepspeed/__init__.py:124-148``)."""

    init: Callable[[jax.Array], Params]
    apply: Callable[..., Tuple[jax.Array, Dict[str, Any]]]
    partition_specs: Optional[Callable[[Any], Any]] = None
    to_pipeline: Optional[Callable[[int, int], "Module"]] = None
    pipelined: bool = False  # True: apply() already pipelines over the pp axis
    # optional random-LTD rebuild: (keep, layer_ids) -> Module whose listed
    # layers train on `keep`-token subsets (the engine calls it when the
    # data_efficiency random_ltd schedule moves to a new compile bucket)
    with_ltd_keep: Optional[Callable[[int, Tuple[int, ...]], "Module"]] = None
    # the GPTConfig this module was built from, when it is a build_gpt model —
    # checkpoint exporters need it (checkpoint/reference_export.py)
    gpt_config: Optional[Any] = None
    # params subtree (top-level key) whose layer stack runs through
    # zero3_layer_scan — the engine's quantized-gradient program buckets that
    # subtree's dp reduce-scatter per layer INSIDE the backward scan
    # (runtime/zero/gather.py grad_bucket_window) instead of folding it into
    # the monolithic post-backward exchange
    grad_bucket_key: Optional[str] = None
    # optional ZeRO-Infinity decomposition: () -> StreamSpec (models/gpt.py
    # make_stream). Exposes the model as embed / repeated-layer / head units so
    # the param-stream runner (runtime/zero/infinity.py) can keep master
    # weights on host and stream one unit at a time through HBM — the
    # offload_param capability (reference: deepspeed/runtime/zero/
    # partition_parameters.py remote-device "cpu"/"nvme")
    stream: Optional[Callable[[], Any]] = None

    def specs(self, param_shapes) -> Any:
        if self.partition_specs is None:
            return replicated_specs(param_shapes)
        return self.partition_specs(param_shapes)
