"""GPT with Mixture-of-Experts MLPs (DeepSpeed-MoE capability).

Parity target: the reference's MoE training path — ``deepspeed/moe/layer.py`` wired
into a Megatron-style GPT where every ``moe_freq``-th MLP is a gated expert bank
(BASELINE.json config #4: "DeepSpeed-MoE GShard 350M x 64-expert"). PR-MoE's
residual experts (``moe/layer.py:34``) are available via ``use_residual``.

TPU-first structure: like :mod:`.gpt`, per-layer weights are stacked and scanned —
here over *super-blocks* of (``moe_freq - 1`` dense blocks, 1 MoE block), so one
compiled body serves any depth. The MoE load-balance aux loss is accumulated in the
scan carry and surfaced through the loss.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..moe.layer import MoEConfig, apply_moe, init_moe, moe_specs
from .api import Module, maybe_shard
from .gpt import (GPTConfig, _block, _dropout, attention_sublayer, layer_norm,
                  next_token_loss)
from .gpt import init_params as gpt_init_params
from .gpt import partition_specs as gpt_partition_specs

BATCH = ("dp", "ep")


@dataclasses.dataclass(frozen=True)
class GPTMoEConfig:
    base: GPTConfig = dataclasses.field(default_factory=GPTConfig)
    num_experts: int = 8
    moe_freq: int = 2           # every moe_freq-th layer is MoE (1 = all layers)
    k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    use_residual: bool = False  # PR-MoE
    aux_loss_coef: float = 0.01
    num_groups: int = 1         # gating groups; set ~ dp*ep for rank-local gating

    def __post_init__(self):
        assert self.base.n_layer % self.moe_freq == 0, (
            f"n_layer {self.base.n_layer} must divide by moe_freq {self.moe_freq}")

    @property
    def n_super(self) -> int:
        return self.base.n_layer // self.moe_freq

    def moe_config(self) -> MoEConfig:
        return MoEConfig(
            d_model=self.base.d_model, d_ff=self.base.ffn_dim,
            num_experts=self.num_experts, k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            drop_tokens=self.drop_tokens, use_rts=self.use_rts,
            use_residual=self.use_residual, num_groups=self.num_groups)


PRESETS: Dict[str, GPTMoEConfig] = {
    # BASELINE.json config #4 flagship
    "moe-350m-64e": GPTMoEConfig(
        base=GPTConfig(n_layer=24, n_head=16, d_model=1024), num_experts=64),
    "moe-125m-8e": GPTMoEConfig(
        base=GPTConfig(n_layer=12, n_head=12, d_model=768), num_experts=8),
    "tiny-moe": GPTMoEConfig(
        base=GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                       max_seq_len=128),
        num_experts=4, moe_freq=2, capacity_factor=2.0),
}


def _stack_init(rng: jax.Array, n: int, init_one):
    """Stack n independently-initialized param trees on a leading axis."""
    keys = jax.random.split(rng, n)
    trees = [init_one(k) for k in keys]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def init_params(cfg: GPTMoEConfig, rng: jax.Array) -> Dict[str, Any]:
    b = cfg.base
    k_base, k_moe = jax.random.split(rng)
    # dense skeleton: embeddings/lns from gpt init at the DENSE layer count
    dense_layers = b.n_layer - cfg.n_super  # layers keeping a dense MLP
    base_cfg = dataclasses.replace(b, n_layer=max(dense_layers, 1))
    # total_depth: residual-out init scales with the FULL depth, not the dense count
    params = gpt_init_params(base_cfg, k_base, total_depth=b.n_layer)
    if dense_layers == 0:
        # all layers MoE: the dense block stack is empty but attention weights are
        # still needed per layer — keep one stacked block set of attention-only use
        params_blocks = params["blocks"]
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x[:0], params_blocks)
    res_std = 0.02 / np.sqrt(2.0 * b.n_layer)
    # MoE blocks: attention weights + moe mlp, stacked over n_super
    moe_cfg = cfg.moe_config()

    def init_moe_block(key):
        ka, km = jax.random.split(key)
        kq, ko = jax.random.split(ka)
        d = b.d_model
        blk = {
            "ln1_scale": jnp.ones((d,)), "ln1_bias": jnp.zeros((d,)),
            "qkv_w": jax.random.normal(kq, (d, 3 * d), jnp.float32) * 0.02,
            "qkv_b": jnp.zeros((3 * d,)),
            "attn_out_w": jax.random.normal(ko, (d, d), jnp.float32) * res_std,
            "attn_out_b": jnp.zeros((d,)),
            "ln2_scale": jnp.ones((d,)), "ln2_bias": jnp.zeros((d,)),
            "moe": init_moe(km, moe_cfg, std=0.02, res_std=res_std),
        }
        return blk

    params["moe_blocks"] = _stack_init(k_moe, cfg.n_super, init_moe_block)
    return params


def partition_specs(cfg: GPTMoEConfig, param_shapes) -> Dict[str, Any]:
    b = cfg.base
    dense_layers = b.n_layer - cfg.n_super
    base_cfg = dataclasses.replace(b, n_layer=max(dense_layers, 1))
    specs = gpt_partition_specs(base_cfg, None)
    mspecs = moe_specs(cfg.moe_config())

    def prepend(spec: P) -> P:
        return P(None, *tuple(spec))

    specs["moe_blocks"] = {
        "ln1_scale": P(None, None), "ln1_bias": P(None, None),
        "qkv_w": P(None, None, "tp"), "qkv_b": P(None, "tp"),
        "attn_out_w": P(None, "tp", None), "attn_out_b": P(None, None),
        "ln2_scale": P(None, None), "ln2_bias": P(None, None),
        "moe": jax.tree_util.tree_map(
            prepend, mspecs, is_leaf=lambda x: isinstance(x, P)),
    }
    return specs


def _moe_block(cfg: GPTMoEConfig, x, w, positions, rng, train, layer_idx=None):
    b = cfg.base
    x = attention_sublayer(b, x, w, positions, rng, train, layer_idx=layer_idx)
    h = layer_norm(x, w["ln2_scale"], w["ln2_bias"], b.layer_norm_eps)
    # decorrelate gating noise/RTS draws from the dropout mask (both fold small
    # constants into their key; give the gate its own subtree of the key space)
    moe_rng = jax.random.fold_in(rng, 0x6A7E) if rng is not None else None
    y, aux, _counts = apply_moe(cfg.moe_config(), w["moe"], h, rng=moe_rng, train=train)
    x = x + _dropout(y, b.dropout, rng, train, salt=1)
    return x, aux


def forward(cfg: GPTMoEConfig, params, input_ids: jnp.ndarray,
            rngs=None, train: bool = True, return_hidden: bool = False
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (logits [B,T,V], aux_loss) — or (post-LN hidden, aux_loss)
    with ``return_hidden`` (the chunked-loss path)."""
    b = cfg.base
    B, T = input_ids.shape
    if T > b.max_seq_len:
        raise ValueError(
            f"sequence length {T} exceeds max_seq_len {b.max_seq_len} "
            f"(out-of-range position lookups would return NaN)")
    x = jnp.take(params["wte"], input_ids, axis=0)
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    if not b.rotary:
        x = x + jnp.take(params["wpe"], positions + b.pos_offset, axis=0)
    x = x.astype(params["moe_blocks"]["qkv_w"].dtype)
    x = maybe_shard(x, P(BATCH, "sp", None))
    drng = (rngs or {}).get("dropout")

    n_dense_per_super = cfg.moe_freq - 1

    def super_block(x, dense_ws, moe_w, idx):
        # dense blocks of this super-block
        if n_dense_per_super > 0:
            def dense_body(carry, layer_w):
                xx, i = carry
                lrng = jax.random.fold_in(drng, i) if drng is not None else None
                xx = _block(b, xx, layer_w, positions, lrng, train, layer_idx=i)
                return (xx, i + 1), None

            (x, idx), _ = jax.lax.scan(dense_body, (x, idx), dense_ws)
        lrng = jax.random.fold_in(drng, idx) if drng is not None else None
        x, aux = _moe_block(cfg, x, moe_w, positions, lrng, train,
                            layer_idx=idx)
        return x, idx + 1, aux

    if cfg.base.remat:
        policy = getattr(jax.checkpoint_policies, cfg.base.remat_policy)
        super_block = jax.checkpoint(super_block, policy=policy, static_argnums=())

    # reshape stacked dense blocks [L_dense, ...] -> [n_super, n_dense_per_super, ...]
    dense_stack = params["blocks"]
    if n_dense_per_super > 0:
        dense_stack = jax.tree_util.tree_map(
            lambda a: a.reshape(cfg.n_super, n_dense_per_super, *a.shape[1:]),
            dense_stack)

    # super-block loop through the ZeRO-3 gather window (stage3 knobs apply to
    # MoE stacks too; plain scan when unconfigured — runtime/zero/gather.py)
    from ..runtime.zero.gather import zero3_layer_scan

    specs_all = partition_specs(cfg, None)
    moe_specs_t = jax.tree_util.tree_map(
        lambda s: P(*tuple(s)[1:]), specs_all["moe_blocks"],
        is_leaf=lambda s: isinstance(s, P))
    if n_dense_per_super > 0:
        def body(carry, layer_in):
            x, idx, aux_sum = carry
            dense_ws, moe_w = layer_in
            x, idx, aux = super_block(x, dense_ws, moe_w, idx)
            return (x, idx, aux_sum + aux), None

        xs = (dense_stack, params["moe_blocks"])
        dense_specs_t = jax.tree_util.tree_map(
            lambda s: P(None, *tuple(s)[1:]), specs_all["blocks"],
            is_leaf=lambda s: isinstance(s, P))
        gathered = (dense_specs_t, moe_specs_t)
    else:
        def body(carry, moe_w):
            x, idx, aux_sum = carry
            x, idx, aux = super_block(x, None, moe_w, idx)
            return (x, idx, aux_sum + aux), None

        xs = params["moe_blocks"]
        gathered = moe_specs_t

    (x, _, aux_sum) = zero3_layer_scan(
        body, (x, jnp.int32(0), jnp.float32(0.0)), xs, gathered_spec=gathered)

    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], b.layer_norm_eps)
    if return_hidden:
        return x, aux_sum / cfg.n_super
    head = params["wte"] if b.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    return logits, aux_sum / cfg.n_super


def loss_fn(cfg: GPTMoEConfig, params, batch, rngs=None, train: bool = True):
    if cfg.base.loss_chunk:
        # same chunked head as the dense model (the fp32 [B,T,V] logits never
        # materialize) — silently dropping the knob would re-create the exact
        # OOM it exists to avoid
        from .gpt import _chunk_targets, chunked_head_loss

        ids_in, targets, mask, n_tok = _chunk_targets(cfg.base, batch)
        hidden, aux = forward(cfg, params, ids_in, rngs=rngs, train=train,
                              return_hidden=True)
        lm_loss, _ = chunked_head_loss(cfg.base, params, hidden, targets,
                                       mask, num_tokens=n_tok)
        return (lm_loss + cfg.aux_loss_coef * aux,
                {"lm_loss": lm_loss, "moe_aux_loss": aux})
    aux_box = []

    def fwd(ids):
        logits, aux = forward(cfg, params, ids, rngs=rngs, train=train)
        aux_box.append(aux)
        return logits

    lm_loss, _ = next_token_loss(fwd, cfg.base.max_seq_len, batch)
    aux = aux_box[0]
    loss = lm_loss + cfg.aux_loss_coef * aux
    return loss, {"lm_loss": lm_loss, "moe_aux_loss": aux}


def build(cfg_or_name) -> Tuple[Module, GPTMoEConfig]:
    cfg = PRESETS[cfg_or_name] if isinstance(cfg_or_name, str) else cfg_or_name
    return Module(
        init=functools.partial(init_params, cfg),
        apply=lambda params, batch, rngs=None, train=True: loss_fn(
            cfg, params, batch, rngs=rngs, train=train),
        partition_specs=functools.partial(partition_specs, cfg),
    ), cfg


# ------------------------------------------------------------- KV-cache decode
def init_cache(cfg: GPTMoEConfig, batch_size: int, max_len: int,
               dtype=jnp.bfloat16):
    """Dense-block and MoE-block cache stacks (layouts as ``gpt.init_cache``).
    Parity: the reference's MoE inference workspace
    (``ops/transformer/inference/moe_inference.py`` + ``inference_context.h``)."""
    b = cfg.base
    dense_layers = b.n_layer - cfg.n_super
    shape_d = (dense_layers, batch_size, b.n_head, max_len, b.head_dim)
    shape_m = (cfg.n_super, batch_size, b.n_head, max_len, b.head_dim)
    return {"k_dense": jnp.zeros(shape_d, dtype), "v_dense": jnp.zeros(shape_d, dtype),
            "k_moe": jnp.zeros(shape_m, dtype), "v_moe": jnp.zeros(shape_m, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _moe_block_with_cache(cfg: GPTMoEConfig, x, w, k_c, v_c, pos,
                          layer_idx=None):
    """Cached MoE block: cached attention + expert-parallel MLP (eval gating:
    no jitter/RTS, eval capacity factor). Parity: the reference's
    ``DeepSpeedMoEInference`` layer (``ops/transformer/inference/moe_inference.py``)."""
    b = cfg.base
    from .gpt import attn_with_cache

    x, k_c, v_c = attn_with_cache(b, x, w, k_c, v_c, pos, layer_idx=layer_idx)
    h = layer_norm(x, w["ln2_scale"], w["ln2_bias"], b.layer_norm_eps)
    y, _aux, _counts = apply_moe(cfg.moe_config(), w["moe"], h, rng=None,
                                 train=False)
    return x + y, k_c, v_c


def forward_with_cache(cfg: GPTMoEConfig, params, input_ids: jnp.ndarray, cache):
    """Prefill or decode through the dense/MoE super-block structure; returns
    (logits [B, T, V], new_cache)."""
    from .gpt import _block_with_cache

    b = cfg.base
    B, T = input_ids.shape
    pos = cache["pos"]
    x = jnp.take(params["wte"], input_ids, axis=0)
    positions = pos + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    if not b.rotary:
        x = x + jnp.take(params["wpe"], positions + b.pos_offset, axis=0)
    x = x.astype(params["moe_blocks"]["qkv_w"].dtype)
    x = maybe_shard(x, P(BATCH, None, None))

    n_dense = cfg.moe_freq - 1

    def super_body(carry, layer_in):
        x, idx = carry  # idx = global layer index (local-attention schedule)
        if n_dense > 0:
            dense_ws, kd, vd, moe_w, km, vm = layer_in

            def dense_body(c, lin):
                xx, i = c
                layer_w, k_c, v_c = lin
                xx, k_c, v_c = _block_with_cache(b, xx, layer_w, k_c, v_c, pos,
                                                 layer_idx=i)
                return (xx, i + 1), (k_c, v_c)

            (x, idx), (kd, vd) = jax.lax.scan(
                dense_body, (x, idx), (dense_ws, kd, vd))
        else:
            moe_w, km, vm = layer_in
            kd = vd = None
        x, km, vm = _moe_block_with_cache(cfg, x, moe_w, km, vm, pos,
                                          layer_idx=idx)
        out = (kd, vd, km, vm) if n_dense > 0 else (km, vm)
        return (x, idx + 1), out

    if n_dense > 0:
        dense_stack = jax.tree_util.tree_map(
            lambda a: a.reshape(cfg.n_super, n_dense, *a.shape[1:]),
            params["blocks"])
        kd = cache["k_dense"].reshape(cfg.n_super, n_dense, *cache["k_dense"].shape[1:])
        vd = cache["v_dense"].reshape(cfg.n_super, n_dense, *cache["v_dense"].shape[1:])
        xs = (dense_stack, kd, vd, params["moe_blocks"], cache["k_moe"], cache["v_moe"])
    else:
        xs = (params["moe_blocks"], cache["k_moe"], cache["v_moe"])

    (x, _), outs = jax.lax.scan(super_body, (x, jnp.int32(0)), xs)
    if n_dense > 0:
        new_kd, new_vd, new_km, new_vm = outs
        new_kd = new_kd.reshape(cache["k_dense"].shape)
        new_vd = new_vd.reshape(cache["v_dense"].shape)
    else:
        new_km, new_vm = outs
        new_kd, new_vd = cache["k_dense"], cache["v_dense"]

    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], b.layer_norm_eps)
    head = params["wte"] if b.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    new_cache = {"k_dense": new_kd, "v_dense": new_vd, "k_moe": new_km,
                 "v_moe": new_vm, "pos": pos + T}
    return logits, new_cache
