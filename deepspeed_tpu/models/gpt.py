"""GPT-family decoder-only language models, TPU-first.

The reference keeps model definitions out-of-repo (Megatron-DeepSpeed / HF) and
ships fixtures (``tests/unit/simple_model.py``) plus fused transformer kernels.
This framework ships a first-class model family because the benchmarks
(BASELINE.json: GPT-2 350M, GPT-NeoX 6.7B/20B, BLOOM-7B1) need runnable flagships.

TPU-first design:
- parameters are one pytree; per-layer weights are *stacked* on a leading ``L`` axis
  and the block is applied with ``lax.scan`` — one compiled layer body regardless of
  depth (fast compiles, natural unit for pipeline stages later);
- Megatron-style tensor-parallel PartitionSpecs: column-parallel qkv/up projections,
  row-parallel out/down projections, vocab-parallel embedding — XLA inserts exactly
  the two all-reduces per block that Megatron does by hand;
- activations are sharding-constrained to batch x sequence axes so sequence
  parallelism ("sp") shards the residual stream;
- rotary or learned positions (NeoX vs GPT-2), pre-LN, optional remat
  (``jax.checkpoint``) = activation checkpointing parity
  (``runtime/activation_checkpointing/checkpointing.py``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..ops.attention import multihead_attention
from .api import Module, maybe_shard

BATCH = ("dp", "ep")  # batch sharding axes (matches topology.BATCH_AXES)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None  # default 4*d_model
    max_seq_len: int = 1024
    rotary: bool = False  # False: learned positions (GPT-2); True: RoPE (NeoX)
    rotary_pct: float = 1.0
    tie_embeddings: bool = True
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    activation: str = "gelu"  # "gelu" (tanh approx), "gelu_exact", "relu" (OPT)
    parallel_residual: bool = False  # NeoX-style x + attn(ln1 x) + mlp(ln2 x)
    pos_offset: int = 0  # learned-position index offset (OPT uses 2)
    alibi: bool = False  # Bloom: linear attention bias instead of positions
    rotary_interleaved: bool = False  # GPT-J rotate_every_two vs NeoX rotate_half
    embed_layernorm: bool = False  # Bloom: LN right after the token embedding
    lm_head_bias: bool = False  # GPT-J: bias on the (untied) LM head
    remat: bool = False  # activation checkpointing per block
    remat_policy: str = "nothing_saveable"  # jax.checkpoint_policies name
    use_flash: Optional[bool] = None  # None = auto dispatch
    flash_block_q: int = 256  # flash-attention tile sizes (autotunable)
    flash_block_k: int = 256
    # speed-over-bit-exactness kernel flag (parity: the reference's
    # StochasticTransformer, op_builder/stochastic_transformer.py +
    # csrc/transformer/ds_transformer_cuda.cpp:63 stochastic_mode): attention
    # matmul operands ride the MXU's native bf16 pass, fp32 accumulation
    stochastic_mode: bool = False
    # stochastic-DEPTH training (Huang et al.): drop whole blocks with prob p
    # at train time, survivor delta scaled by 1/(1-p)
    stochastic_depth: float = 0.0
    # GPT-Neo-style alternating local attention: every `period`-th layer
    # (1-indexed within the period; GPT-Neo = period 2, layers 1,3,... local)
    # attends only to the trailing `window_size` positions
    local_attention_period: int = 0  # 0 = all layers global
    window_size: int = 256
    attention_scale: Optional[float] = None  # None = 1/sqrt(head_dim); GPT-Neo = 1.0
    has_lm_head: bool = True  # False: pure encoder (CLIP text tower) — only
    # return_hidden=True is valid; the logits path raises instead of fabricating
    # blocksparse attention: a SparsityConfig here routes every layer through
    # the Pallas blocksparse kernel (graft via ops.sparse_attention.
    # sparse_attention_utils; parity: sparse_attention_utils.py:225)
    sparse_attention: Optional[Any] = None
    # random-LTD (layer token dropping): the listed layers process only a
    # random `random_ltd_keep`-token subset at train time, dropped tokens
    # bypassing the layer (parity: data_routing/basic_layer.py:13; the engine
    # drives `keep` from the scheduled data_efficiency config)
    random_ltd_layer_ids: Tuple[int, ...] = ()
    random_ltd_keep: Optional[int] = None
    # sequence-parallel attention over the sp mesh axis: "dense" lets GSPMD
    # gather k/v (O(T) memory per chip); "ring" streams k/v blocks by
    # collective-permute, "ulysses" all-to-alls heads<->sequence — the
    # long-context memory savers (parallel/{ring_attention,ulysses}.py)
    seq_parallel_impl: str = "dense"
    # chunked cross-entropy: compute the LM-head logits + logsumexp over
    # `loss_chunk`-token sequence slices in a rematted scan, so the fp32
    # [B, T, V] logits tensor (3.07 GB at bs16/seq1024/50k vocab — the
    # largest single buffer at the v5e fit boundary, see docs/MFU_NOTES.md)
    # never materializes. 0 = off (whole-sequence loss).
    loss_chunk: int = 0

    @property
    def ffn_dim(self) -> int:
        return self.d_ff if self.d_ff is not None else 4 * self.d_model

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    def num_params(self) -> int:
        d, f, v, l = self.d_model, self.ffn_dim, self.vocab_size, self.n_layer
        per_layer = 4 * d * d + 2 * d * f + 13 * d  # qkv+out + mlp + ln/bias
        emb = v * d + (0 if self.rotary else self.max_seq_len * d)
        return l * per_layer + emb + 2 * d


# Named presets used by benchmarks (sizes follow GPT-2/GPT-NeoX families).
PRESETS: Dict[str, GPTConfig] = {
    "gpt2-125m": GPTConfig(n_layer=12, n_head=12, d_model=768),
    "gpt2-350m": GPTConfig(n_layer=24, n_head=16, d_model=1024),
    "gpt2-760m": GPTConfig(n_layer=24, n_head=16, d_model=1536),
    "gpt2-1.3b": GPTConfig(n_layer=24, n_head=32, d_model=2048),
    "gpt-neox-1.3b": GPTConfig(n_layer=24, n_head=16, d_model=2048, rotary=True, rotary_pct=0.25),
    "gpt-neox-6.7b": GPTConfig(n_layer=32, n_head=32, d_model=4096, rotary=True, rotary_pct=0.25),
    "gpt-neox-20b": GPTConfig(
        vocab_size=50432, n_layer=44, n_head=64, d_model=6144, max_seq_len=2048,
        rotary=True, rotary_pct=0.25),
    # BLOOM-7B1 (BASELINE.json config #3): ALiBi attention, embedding
    # layernorm, tied head — bigscience/bloom-7b1 geometry
    "bloom-7b1": GPTConfig(
        vocab_size=250880, n_layer=30, n_head=32, d_model=4096,
        max_seq_len=2048, alibi=True, embed_layernorm=True,
        tie_embeddings=True),
    # OPT-13B (BASELINE.json config #5 inference model): ReLU MLPs, learned
    # positions at offset 2 — facebook/opt-13b geometry
    "opt-13b": GPTConfig(
        vocab_size=50272, n_layer=40, n_head=40, d_model=5120,
        max_seq_len=2048, rotary=False, pos_offset=2, activation="relu",
        tie_embeddings=True),
    "tiny": GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64, max_seq_len=128),
}


# --------------------------------------------------------------------------- init
def init_params(cfg: GPTConfig, rng: jax.Array,
                total_depth: Optional[int] = None) -> Dict[str, Any]:
    d, f, v, l = cfg.d_model, cfg.ffn_dim, cfg.vocab_size, cfg.n_layer
    k = jax.random.split(rng, 8)
    std = 0.02
    # residual-out projections scaled by 1/sqrt(2L) (GPT-2 init); total_depth
    # overrides L when this stack is a slice of a deeper model (MoE interleave)
    res_std = std / np.sqrt(2.0 * (total_depth or l))

    def normal(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s)

    params: Dict[str, Any] = {
        "wte": normal(k[0], (v, d), std),
        "blocks": {
            "ln1_scale": jnp.ones((l, d)), "ln1_bias": jnp.zeros((l, d)),
            "qkv_w": normal(k[1], (l, d, 3 * d), std), "qkv_b": jnp.zeros((l, 3 * d)),
            "attn_out_w": normal(k[2], (l, d, d), res_std), "attn_out_b": jnp.zeros((l, d)),
            "ln2_scale": jnp.ones((l, d)), "ln2_bias": jnp.zeros((l, d)),
            "mlp_up_w": normal(k[3], (l, d, f), std), "mlp_up_b": jnp.zeros((l, f)),
            "mlp_down_w": normal(k[4], (l, f, d), res_std), "mlp_down_b": jnp.zeros((l, d)),
        },
        "lnf_scale": jnp.ones((d,)),
        "lnf_bias": jnp.zeros((d,)),
    }
    if not cfg.rotary and not cfg.alibi:
        params["wpe"] = normal(k[5], (cfg.max_seq_len + cfg.pos_offset, d), std)
    if cfg.embed_layernorm:
        params["emb_ln_scale"] = jnp.ones((d,))
        params["emb_ln_bias"] = jnp.zeros((d,))
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(k[6], (v, d), std)
        if cfg.lm_head_bias:
            params["lm_head_b"] = jnp.zeros((v,))
    return params


def partition_specs(cfg: GPTConfig, param_shapes) -> Dict[str, Any]:
    """Megatron-style TP specs. Stacked layer leaves carry a leading L axis."""
    specs = {
        "wte": P("tp", None),  # vocab-parallel embedding
        "blocks": {
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "qkv_w": P(None, None, "tp"), "qkv_b": P(None, "tp"),
            "attn_out_w": P(None, "tp", None), "attn_out_b": P(None, None),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
            "mlp_up_w": P(None, None, "tp"), "mlp_up_b": P(None, "tp"),
            "mlp_down_w": P(None, "tp", None), "mlp_down_b": P(None, None),
        },
        "lnf_scale": P(None),
        "lnf_bias": P(None),
    }
    if not cfg.rotary and not cfg.alibi:
        specs["wpe"] = P(None, None)
    if cfg.embed_layernorm:
        specs["emb_ln_scale"] = P(None)
        specs["emb_ln_bias"] = P(None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("tp", None)
        if cfg.lm_head_bias:
            specs["lm_head_b"] = P("tp")
    return specs


# --------------------------------------------------------------------------- layers
def layer_norm(x: jnp.ndarray, scale, bias, eps: float) -> jnp.ndarray:
    # fp32 statistics regardless of compute dtype (reference normalize_kernels.cu
    # accumulates in fp32 for the same reason).
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _rope(x: jnp.ndarray, positions: jnp.ndarray, rotary_dims: int,
          interleaved: bool = False) -> jnp.ndarray:
    """Rotary embedding on the first ``rotary_dims`` of the head dim. x: [B,T,H,Dh].

    ``interleaved=False``: NeoX rotate_half (pair (i, i+half)).
    ``interleaved=True``: GPT-J rotate_every_two (pair (2i, 2i+1))."""
    if rotary_dims == 0:
        return x
    x_rot, x_pass = x[..., :rotary_dims], x[..., rotary_dims:]
    half = rotary_dims // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]  # [B,T,half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    if interleaved:
        x1, x2 = x_rot[..., 0::2], x_rot[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        rotated = jnp.stack([r1, r2], axis=-1).reshape(x_rot.shape)
    else:
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([rotated, x_pass], axis=-1)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Bloom's per-head ALiBi slopes (handles non-power-of-two head counts).
    Parity: the reference's alibi softmax path (``softmax.cu`` alibi mode,
    ``model_implementations/transformers/ds_bloom.py``)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    n = 2 ** int(np.floor(np.log2(n_heads)))
    slopes = pow2_slopes(n)
    if n < n_heads:
        extra = pow2_slopes(2 * n)[0::2][: n_heads - n]
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


def _alibi_bias(cfg: GPTConfig, q_positions: jnp.ndarray, kv_len: int) -> jnp.ndarray:
    """[B, H, T, S] additive bias: slopes[h] * (s - t_abs)."""
    slopes = jnp.asarray(alibi_slopes(cfg.n_head))
    s_idx = jnp.arange(kv_len)[None, None, None, :]
    t_abs = q_positions[:, None, :, None]
    return slopes[None, :, None, None] * (s_idx - t_abs).astype(jnp.float32)


def _act(cfg: GPTConfig, h: jnp.ndarray) -> jnp.ndarray:
    if cfg.activation == "relu":
        return jax.nn.relu(h)
    if cfg.activation == "gelu_exact":
        return jax.nn.gelu(h, approximate=False)
    if cfg.activation == "quick_gelu":  # CLIP: x * sigmoid(1.702 x)
        return h * jax.nn.sigmoid(1.702 * h)
    return jax.nn.gelu(h, approximate=True)


def _is_local_layer(cfg: GPTConfig, layer_idx) -> Optional[jnp.ndarray]:
    """Traced bool: does this layer use windowed (local) attention?
    GPT-Neo alternates [global, local] — the last layer of each period is
    local. None when the config never uses local attention."""
    if cfg.local_attention_period <= 1 or layer_idx is None:
        return None
    p = cfg.local_attention_period
    return (jnp.asarray(layer_idx) % p) == (p - 1)


def _local_window_bias(cfg: GPTConfig, q_positions: jnp.ndarray, kv_len: int,
                       is_local) -> jnp.ndarray:
    """[B, 1, T, S] additive bias masking keys older than window_size
    (inert for global layers: is_local is traced, the program is uniform)."""
    s_idx = jnp.arange(kv_len)[None, None, None, :]
    t_abs = q_positions[:, None, :, None]
    too_old = s_idx <= t_abs - cfg.window_size
    return jnp.where(jnp.logical_and(is_local, too_old),
                     jnp.float32(-1e30), jnp.float32(0.0))


def _attention_delta(cfg: GPTConfig, x: jnp.ndarray, w: Dict[str, jnp.ndarray],
                     positions: jnp.ndarray, layer_idx=None) -> jnp.ndarray:
    """Attention output (pre-residual): attn_out(MHA(ln1(x)))."""
    B, T, D = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    h = layer_norm(x, w["ln1_scale"], w["ln1_bias"], cfg.layer_norm_eps)
    qkv = h @ w["qkv_w"] + w["qkv_b"]
    q, k_, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, Dh)
    k_ = k_.reshape(B, T, H, Dh)
    v = v.reshape(B, T, H, Dh)
    if cfg.rotary:
        rd = int(cfg.rotary_pct * Dh)
        rd -= rd % 2
        q = _rope(q, positions, rd, cfg.rotary_interleaved)
        k_ = _rope(k_, positions, rd, cfg.rotary_interleaved)
    bias = _alibi_bias(cfg, positions, T) if cfg.alibi else None
    is_local = _is_local_layer(cfg, layer_idx)
    if is_local is not None:
        lb = _local_window_bias(cfg, positions, T, is_local)
        bias = lb if bias is None else bias + lb
    if cfg.sparse_attention is not None:
        if bias is not None:
            raise ValueError(
                "sparse_attention cannot compose with alibi/local-window "
                "biases (the blocksparse kernel has no bias input)")
        from ..ops.sparse_attention import sparse_attention as _sparse

        attn = _sparse(q, k_, v, cfg.sparse_attention, causal=True,
                       softmax_scale=cfg.attention_scale)
    elif cfg.seq_parallel_impl in ("ring", "ulysses") and _sp_active():
        if bias is not None:
            raise ValueError(
                f"seq_parallel_impl='{cfg.seq_parallel_impl}' cannot compose "
                f"with alibi/local-window biases")
        from ..parallel import ring_attention, ulysses_attention

        fn = (ring_attention if cfg.seq_parallel_impl == "ring"
              else ulysses_attention)
        attn = fn(q, k_, v, _bound_mesh(), causal=True,
                  softmax_scale=cfg.attention_scale)
    else:
        attn = multihead_attention(q, k_, v, causal=True, bias=bias,
                                   use_flash=cfg.use_flash,
                                   softmax_scale=cfg.attention_scale,
                                   block_q=cfg.flash_block_q,
                                   block_k=cfg.flash_block_k,
                                   stochastic_mode=cfg.stochastic_mode)
    attn = attn.reshape(B, T, D)
    return checkpoint_name(attn @ w["attn_out_w"] + w["attn_out_b"], "attn_out")


def _bound_mesh():
    """The mesh governing the CURRENT trace: the engine traces its programs
    inside ``mesh_context(engine.mesh)``, so the trace-bound mesh is the
    right one even when several engines with different topologies coexist
    (a process-global would go stale). Falls back to the default topology for
    direct (non-engine) calls."""
    from ..runtime.topology import bound_mesh, get_topology

    pm = bound_mesh()
    if pm is not None:
        return pm
    try:
        topo = get_topology()
    except Exception:
        return None
    return topo.mesh if topo is not None else None


def _sp_active() -> bool:
    """True when the trace-bound mesh has sp > 1 (the ring/Ulysses paths only
    make sense with the sequence dim actually sharded)."""
    mesh = _bound_mesh()
    return mesh is not None and dict(mesh.shape).get("sp", 1) > 1


def _wm(h: jnp.ndarray, leaf) -> jnp.ndarray:
    """``h @ W`` where W is dense OR a quantized leaf: int8 ``{"q","s"}`` or
    packed int4 ``{"q4","s"}``.

    Quantized leaves route through the Pallas quantized-weight matmuls
    (ops/pallas/int8_matmul.py): the narrow weights stay in HBM,
    dequantization happens per VMEM tile — no bf16 weight buffer exists at
    any scope, and decode moves half (int8) or a quarter (int4) of the
    weight bytes (the decode bottleneck)."""
    if not _is_qleaf(leaf):
        return h @ leaf
    shape = h.shape
    if "q4" in leaf:
        from ..ops.pallas.int8_matmul import int4_matmul

        q4, s = leaf["q4"], leaf["s"]
        group = (2 * q4.size) // s.size
        out = int4_matmul(h.reshape(-1, shape[-1]), q4, s.reshape(-1),
                          group_size=group)
        return out.reshape(*shape[:-1], 2 * q4.shape[1])
    from ..ops.pallas.int8_matmul import int8_matmul

    q, s = leaf["q"], leaf["s"]
    group = q.size // s.size
    out = int8_matmul(h.reshape(-1, shape[-1]), q, s.reshape(-1),
                      group_size=group)
    return out.reshape(*shape[:-1], q.shape[1])


def _mlp_delta(cfg: GPTConfig, x: jnp.ndarray, w: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """MLP output (pre-residual): mlp(ln2(x))."""
    h = layer_norm(x, w["ln2_scale"], w["ln2_bias"], cfg.layer_norm_eps)
    h = _wm(h, w["mlp_up_w"]) + w["mlp_up_b"]
    h = _act(cfg, h)
    return checkpoint_name(_wm(h, w["mlp_down_w"]) + w["mlp_down_b"],
                           "mlp_out")


def attention_sublayer(cfg: GPTConfig, x: jnp.ndarray, w: Dict[str, jnp.ndarray],
                       positions: jnp.ndarray, dropout_rng, train: bool,
                       layer_idx=None) -> jnp.ndarray:
    """Pre-LN self-attention + residual (shared by dense and MoE blocks)."""
    attn = _attention_delta(cfg, x, w, positions, layer_idx=layer_idx)
    return x + _dropout(attn, cfg.dropout, dropout_rng, train, salt=0)


def _block(cfg: GPTConfig, x: jnp.ndarray, w: Dict[str, jnp.ndarray],
           positions: jnp.ndarray, dropout_rng, train: bool,
           layer_idx=None) -> jnp.ndarray:
    if cfg.parallel_residual:
        # NeoX/GPT-J style: both sublayers read the same input
        attn = _dropout(_attention_delta(cfg, x, w, positions, layer_idx=layer_idx),
                        cfg.dropout, dropout_rng, train, salt=0)
        mlp = _dropout(_mlp_delta(cfg, x, w), cfg.dropout, dropout_rng, train, salt=1)
        return x + attn + mlp
    x = attention_sublayer(cfg, x, w, positions, dropout_rng, train,
                           layer_idx=layer_idx)
    h = _mlp_delta(cfg, x, w)
    x = x + _dropout(h, cfg.dropout, dropout_rng, train, salt=1)
    return x


def _dropout(x, rate, rng, train, salt: int):
    if rate == 0.0 or not train or rng is None:
        return x
    key = jax.random.fold_in(rng, salt)
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _head_quantization():
    """Active quantized-LM-head config (``zero_quantized_head``), or None.
    Read from the same trace-bound config the gather windowing rides; inert
    inside the quantized-gradient shard_map (no config is bound there)."""
    from ..runtime.zero.gather import _active_cfg

    zcfg = _active_cfg()
    if zcfg is None or int(getattr(zcfg, "stage", 0)) < 3:
        return None
    if not (getattr(zcfg, "zero_quantized_weights", False)
            and getattr(zcfg, "zero_quantized_head", False)):
        return None
    from ..comm.quantized import QuantizedCommConfig

    return QuantizedCommConfig.from_zero_config(zcfg)


# --------------------------------------------------------------------------- forward
def forward(cfg: GPTConfig, params: Dict[str, Any], input_ids: jnp.ndarray,
            rngs: Optional[Dict[str, jax.Array]] = None, train: bool = True,
            return_hidden: bool = False, pld_theta=None) -> jnp.ndarray:
    """Return logits [B, T, V] (or the final-LN hidden states [B, T, D] with
    ``return_hidden`` — the encoder surface CLIP-style text towers need).

    ``pld_theta``: traced scalar keep-probability from the engine's Progressive
    Layer Drop schedule (reference ``runtime/progressive_layer_drop.py:5``);
    gates each block with the paper's depth-scaled probability."""
    B, T = input_ids.shape
    if T > cfg.max_seq_len:
        raise ValueError(
            f"sequence length {T} exceeds max_seq_len {cfg.max_seq_len} "
            f"(out-of-range position lookups would return NaN)")
    x = jnp.take(params["wte"], input_ids, axis=0)
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    if not cfg.rotary and not cfg.alibi:
        x = x + jnp.take(params["wpe"], positions + cfg.pos_offset, axis=0)
    if cfg.embed_layernorm:
        x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                       cfg.layer_norm_eps)
    x = x.astype(params["blocks"]["qkv_w"].dtype)
    # residual stream sharded over batch and (if sp>1) sequence
    x = maybe_shard(x, P(BATCH, "sp", None))

    drng = (rngs or {}).get("dropout")

    def block_fn(x, layer_w, pos, lrng, layer_idx):
        return _block(cfg, x, layer_w, pos, lrng, train, layer_idx=layer_idx)

    if cfg.remat:
        if cfg.remat_policy == "save_attn_mlp_out":
            # selective: keep each sublayer's projected output (2*d_model per
            # token per layer) so backward skips recomputing the output
            # projections; everything else (flash internals, ln, gelu) remats
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out", "mlp_out")
        else:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy)
        block_fn = jax.checkpoint(block_fn, policy=policy)

    sd = cfg.stochastic_depth if train else 0.0
    if pld_theta is not None and (sd > 0.0 or not train):
        raise ValueError(
            "progressive_layer_drop is train-only and exclusive with "
            "stochastic_depth (both gate whole blocks)")
    use_ltd = (train and cfg.random_ltd_keep is not None
               and cfg.random_ltd_keep < T and cfg.random_ltd_layer_ids)
    ltd_ids = jnp.asarray(cfg.random_ltd_layer_ids or (0,), jnp.int32)

    def body(carry, layer_w):
        x, i = carry
        lrng = jax.random.fold_in(drng, i) if drng is not None else None
        if use_ltd:
            from ..runtime.data_pipeline.data_routing.random_ltd import (
                random_ltd_gather, random_ltd_scatter)

            def ltd_branch(xx):
                krng = jax.random.fold_in(
                    lrng if lrng is not None else jax.random.PRNGKey(0x17D), i)
                kept, idx = random_ltd_gather(xx, cfg.random_ltd_keep, krng)
                kept_pos = jnp.take_along_axis(positions, idx, axis=1)
                out = block_fn(kept, layer_w, kept_pos, lrng, i)
                return random_ltd_scatter(out, idx, xx)

            y = jax.lax.cond(jnp.isin(i, ltd_ids), ltd_branch,
                             lambda xx: block_fn(xx, layer_w, positions,
                                                 lrng, i), x)
        else:
            y = block_fn(x, layer_w, positions, lrng, i)
        if pld_theta is not None:
            # PLD depth scaling (arXiv:2010.13369): deeper layers drop first —
            # layer i keeps with p_i = 1 - (i+1)/L * (1 - theta(t)); surviving
            # deltas are rescaled so eval runs the full stack uncorrected
            keep_p = (1.0 - (jnp.asarray(i + 1, jnp.float32) / cfg.n_layer)
                      * (1.0 - pld_theta))
            if lrng is None:
                # a fixed fallback key would freeze the drop mask across steps
                # (layers past their draw would never train again)
                raise ValueError(
                    "progressive_layer_drop needs a dropout rng: pass "
                    "rngs={'dropout': key} to forward()")
            keep = jax.random.bernoulli(
                jax.random.fold_in(jax.random.fold_in(lrng, 0x91D), i), keep_p)
            # max() keeps the untaken branch's gradient finite when keep_p -> 0
            x = x + jnp.where(keep, (y - x) / jnp.maximum(keep_p, 1e-3),
                              0.0).astype(x.dtype)
        elif sd > 0.0 and lrng is not None:
            # stochastic depth: drop the whole block with prob sd; the
            # surviving delta is scaled so eval needs no correction
            keep = jax.random.bernoulli(jax.random.fold_in(lrng, 0x5D), 1.0 - sd)
            x = x + jnp.where(keep, (y - x) / (1.0 - sd), 0.0).astype(x.dtype)
        else:
            x = y
        return (x, i + 1), None

    # layer loop with explicit ZeRO-3 gather windowing (stage3_max_live_parameters
    # / stage3_prefetch_bucket_size; plain per-layer scan when unconfigured)
    from ..runtime.zero.gather import zero3_layer_scan

    layer_specs = jax.tree_util.tree_map(
        lambda s: P(*tuple(s)[1:]), partition_specs(cfg, None)["blocks"],
        is_leaf=lambda s: isinstance(s, P))
    (x, _) = zero3_layer_scan(body, (x, jnp.int32(0)), params["blocks"],
                              gathered_spec=layer_specs)
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], cfg.layer_norm_eps)
    if return_hidden:
        return x
    if not cfg.has_lm_head:
        raise ValueError(
            "this config is a pure encoder (has_lm_head=False, e.g. an "
            "imported CLIP text tower): call forward(..., return_hidden=True) "
            "— there is no LM head to produce logits with")
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    qh = _head_quantization()
    if qh is not None:
        # zero_quantized_head: the head gather rides the int wire AND the
        # dequantized fp copy is never materialized — the payload feeds the
        # logits matmul's prologue (ops/pallas/dequant_matmul.py on TPU, the
        # fused XLA fallback elsewhere), with a straight-through backward
        from ..comm.quantized import quantized_matmul_reshard

        B2, T2, D2 = x.shape
        logits = quantized_matmul_reshard(
            x.reshape(-1, D2), head.astype(x.dtype).T, P(None, "tp"),
            qh.bits, qh.block_size, "qmatmul[lm_head]").reshape(B2, T2, -1)
    else:
        logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    if cfg.lm_head_bias and not cfg.tie_embeddings:
        logits = logits + params["lm_head_b"].astype(logits.dtype)
    return logits


def next_token_loss(forward_fn, max_seq_len: int, batch: Dict[str, jnp.ndarray]
                    ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Shared next-token cross-entropy: handles the optional "labels"/"loss_mask"
    keys and the seq-vs-seq+1 packing cases identically for every GPT variant
    (dense / MoE / pipelined). ``forward_fn(input_ids) -> logits``."""
    input_ids = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        labels = input_ids[:, 1:]
        if input_ids.shape[1] > max_seq_len:
            # seq+1 token packing: slice inputs to max_seq_len (labels align 1:1)
            logits = forward_fn(input_ids[:, :-1])
        else:
            # keep the full (tile-friendly) length through attention; drop the
            # last logit instead of the last input token
            logits = forward_fn(input_ids)[:, :-1]
    else:
        logits = forward_fn(input_ids)
    logits32 = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits32, axis=-1)
    gold = jnp.take_along_axis(logits32, labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    mask = batch.get("loss_mask")
    if mask is not None:
        mask = mask.astype(jnp.float32)
        if labels.shape != batch["input_ids"].shape:
            mask = mask[:, 1:]
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    else:
        loss = jnp.mean(nll)
    return loss, {"num_tokens": nll.size}


def _chunked_ce(hidden: jnp.ndarray, head: jnp.ndarray,
                head_bias: Optional[jnp.ndarray], targets: jnp.ndarray,
                mask: jnp.ndarray, chunk: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masked cross entropy over `chunk`-token sequence slices.

    Each scan step computes ONE chunk's logits (``[B, chunk, V]``) and its
    fp32 logsumexp, and the step is rematted so backward recomputes the chunk
    logits instead of keeping them — peak memory holds one chunk's logits,
    not ``[B, T, V]``. Returns (sum of masked nll, sum of mask)."""
    B, T, D = hidden.shape
    if T % chunk:
        raise ValueError(f"loss_chunk {chunk} must divide seq len {T}")
    n = T // chunk
    h = hidden.reshape(B, n, chunk, D).transpose(1, 0, 2, 3)
    t = targets.reshape(B, n, chunk).transpose(1, 0, 2)
    m = mask.reshape(B, n, chunk).transpose(1, 0, 2)

    def body(carry, xs):
        h_c, t_c, m_c = xs
        logits = jnp.einsum("bcd,vd->bcv", h_c, head.astype(h_c.dtype))
        if head_bias is not None:
            logits = logits + head_bias.astype(logits.dtype)
        logits32 = logits.astype(jnp.float32)
        logz = jax.scipy.special.logsumexp(logits32, axis=-1)
        gold = jnp.take_along_axis(logits32, t_c[..., None], axis=-1)[..., 0]
        nll = (logz - gold) * m_c
        s, c = carry
        return (s + jnp.sum(nll), c + jnp.sum(m_c)), None

    (s, c), _ = jax.lax.scan(jax.checkpoint(body),
                             (jnp.float32(0.0), jnp.float32(0.0)), (h, t, m))
    return s, c


def _chunk_targets(cfg: GPTConfig, batch: Dict[str, jnp.ndarray]
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """(input_ids_for_forward, targets [B,T], mask [B,T], num_real_targets)
    replicating :func:`next_token_loss`'s label/mask/packing semantics on
    full-T tiles (unmatched positions masked out; ``num_real_targets`` is
    the whole-sequence path's ``nll.size`` — the padded dummy position in
    the standard shift case is excluded)."""
    input_ids = batch["input_ids"]
    labels = batch.get("labels")
    loss_mask = batch.get("loss_mask")
    if labels is None and input_ids.shape[1] > cfg.max_seq_len:
        # seq+1 token packing: inputs are the first max_seq_len tokens
        ids_in = input_ids[:, :-1]
        shift_targets = input_ids[:, 1:]
    else:
        ids_in = input_ids
        shift_targets = None
    B, T = ids_in.shape
    if labels is not None:
        targets = labels
        mask = (loss_mask.astype(jnp.float32) if loss_mask is not None
                else jnp.ones((B, T), jnp.float32))
        return ids_in, targets, mask, int(targets.size)
    elif shift_targets is not None:
        targets = shift_targets
        mask = (loss_mask[:, 1:].astype(jnp.float32)
                if loss_mask is not None else jnp.ones((B, T), jnp.float32))
        return ids_in, targets, mask, int(targets.size)
    else:
        # standard next-token shift: last position has no target — mask it
        # (and pad targets with a dummy 0 there) so chunks tile the full T
        targets = jnp.concatenate(
            [input_ids[:, 1:], jnp.zeros((B, 1), input_ids.dtype)], axis=1)
        mask = jnp.concatenate(
            [jnp.ones((B, T - 1), jnp.float32),
             jnp.zeros((B, 1), jnp.float32)], axis=1)
        if loss_mask is not None:
            shifted = jnp.concatenate(
                [loss_mask[:, 1:], jnp.zeros((B, 1), loss_mask.dtype)], axis=1)
            mask = mask * shifted.astype(jnp.float32)
    return ids_in, targets, mask, int(targets.size - B)  # dummy col excluded


def chunked_head_loss(cfg: GPTConfig, params, hidden: jnp.ndarray,
                      targets: jnp.ndarray, mask: jnp.ndarray,
                      num_tokens: Optional[int] = None
                      ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Chunked LM head + masked cross entropy over post-LN ``hidden`` — shared
    by the dense and pipelined models."""
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    head_b = (params.get("lm_head_b")
              if (cfg.lm_head_bias and not cfg.tie_embeddings) else None)
    s, c = _chunked_ce(hidden, head, head_b, targets, mask, cfg.loss_chunk)
    # masked mean == next_token_loss semantics in every case: without a
    # loss_mask the mask counts exactly the real target positions
    return s / jnp.maximum(c, 1.0), {
        "num_tokens": int(num_tokens if num_tokens is not None
                          else targets.size)}


def chunked_loss(cfg: GPTConfig, params, batch: Dict[str, jnp.ndarray],
                 rngs=None, train: bool = True, pld_theta=None
                 ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """:func:`loss_fn` semantics with the LM head + cross entropy evaluated in
    ``cfg.loss_chunk``-token slices (see :func:`_chunked_ce`). Numerically the
    same masked mean as :func:`next_token_loss`."""
    ids_in, targets, mask, n_tok = _chunk_targets(cfg, batch)
    hidden = forward(cfg, params, ids_in, rngs=rngs, train=train,
                     return_hidden=True, pld_theta=pld_theta)
    return chunked_head_loss(cfg, params, hidden, targets, mask,
                             num_tokens=n_tok)


def loss_fn(cfg: GPTConfig, params, batch: Dict[str, jnp.ndarray],
            rngs=None, train: bool = True, pld_theta=None
            ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Next-token cross entropy. ``batch``: {"input_ids": [B,T]} (+ optional
    "labels"/"loss_mask")."""
    if cfg.loss_chunk:
        if not cfg.has_lm_head:
            raise ValueError("loss_chunk needs an LM head")
        return chunked_loss(cfg, params, batch, rngs=rngs, train=train,
                            pld_theta=pld_theta)
    return next_token_loss(
        lambda ids: forward(cfg, params, ids, rngs=rngs, train=train,
                            pld_theta=pld_theta),
        cfg.max_seq_len, batch)


# ------------------------------------------------------- ZeRO-Infinity stream
class GPTStream:
    """ZeRO-Infinity unit decomposition of the GPT stack (``Module.stream``).

    The model is exposed as ``embed`` / ``layer_0..L-1`` / ``final`` units so
    the param-stream runner (:mod:`deepspeed_tpu.runtime.zero.infinity`) can
    keep master weights in host RAM and stream ONE unit at a time through HBM —
    the ``offload_param`` capability (reference: ``deepspeed/runtime/zero/
    partition_parameters.py`` remote-device "cpu"/"nvme" + ``docs/_pages/
    training.md:301`` 13B-on-one-V100). Host init is numpy — the full model is
    never materialized on device — and every layer unit is shape-identical, so
    the runner compiles exactly one fwd and one bwd program for all L layers.
    """

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.n_layer = cfg.n_layer
        self.tied = cfg.tie_embeddings

    def unit_names(self):
        return (["embed"] + [f"layer_{i}" for i in range(self.n_layer)]
                + ["final"])

    # ---------------------------------------------------------- host init
    def init_unit(self, name: str, seed: int) -> Dict[str, np.ndarray]:
        cfg = self.cfg
        d, f, v = cfg.d_model, cfg.ffn_dim, cfg.vocab_size
        idx = self.unit_names().index(name)
        rng = np.random.default_rng([int(seed) & 0x7FFFFFFF, idx])
        std = 0.02
        res_std = float(std / np.sqrt(2.0 * cfg.n_layer))

        def normal(shape, s):
            # float(s): a np.float64 scalar would NEP50-promote the product
            return rng.standard_normal(shape, np.float32) * np.float32(s)

        def ones(shape):
            return np.ones(shape, np.float32)

        def zeros(shape):
            return np.zeros(shape, np.float32)

        if name == "embed":
            out = {"wte": normal((v, d), std)}
            if not cfg.rotary and not cfg.alibi:
                out["wpe"] = normal((cfg.max_seq_len + cfg.pos_offset, d), std)
            if cfg.embed_layernorm:
                out["emb_ln_scale"] = ones((d,))
                out["emb_ln_bias"] = zeros((d,))
            return out
        if name == "final":
            out = {"lnf_scale": ones((d,)), "lnf_bias": zeros((d,))}
            if not cfg.tie_embeddings:
                out["lm_head"] = normal((v, d), std)
                if cfg.lm_head_bias:
                    out["lm_head_b"] = zeros((v,))
            return out
        return {
            "ln1_scale": ones((d,)), "ln1_bias": zeros((d,)),
            "qkv_w": normal((d, 3 * d), std), "qkv_b": zeros((3 * d,)),
            "attn_out_w": normal((d, d), res_std), "attn_out_b": zeros((d,)),
            "ln2_scale": ones((d,)), "ln2_bias": zeros((d,)),
            "mlp_up_w": normal((d, f), std), "mlp_up_b": zeros((f,)),
            "mlp_down_w": normal((f, d), res_std), "mlp_down_b": zeros((d,)),
        }

    # ---------------------------------------------------------- device programs
    def embed_fwd(self, emb: Dict[str, jnp.ndarray], input_ids: jnp.ndarray,
                  compute_dtype) -> jnp.ndarray:
        cfg = self.cfg
        B, T = input_ids.shape
        x = jnp.take(emb["wte"], input_ids, axis=0)
        if "wpe" in emb:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
            x = x + jnp.take(emb["wpe"], positions + cfg.pos_offset, axis=0)
        if cfg.embed_layernorm:
            x = layer_norm(x, emb["emb_ln_scale"], emb["emb_ln_bias"],
                           cfg.layer_norm_eps)
        return x.astype(compute_dtype)

    def layer_fwd(self, w: Dict[str, jnp.ndarray], x: jnp.ndarray,
                  layer_idx, rng) -> jnp.ndarray:
        cfg = self.cfg
        B, T = x.shape[:2]
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        return _block(cfg, x, w, positions, rng, train=True,
                      layer_idx=layer_idx)

    def head_loss(self, final: Dict[str, jnp.ndarray], wte: jnp.ndarray,
                  x: jnp.ndarray, input_ids: jnp.ndarray,
                  labels: Optional[jnp.ndarray] = None,
                  loss_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """Same semantics as :func:`next_token_loss`: explicit ``labels`` score
        the full sequence; otherwise next-token targets are the shifted input
        ids. ``loss_mask`` weights positions (shifted alongside the labels)."""
        cfg = self.cfg
        x = layer_norm(x, final["lnf_scale"], final["lnf_bias"],
                       cfg.layer_norm_eps)
        head = wte if cfg.tie_embeddings else final["lm_head"]
        logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
        if cfg.lm_head_bias and not cfg.tie_embeddings:
            logits = logits + final["lm_head_b"].astype(logits.dtype)
        if labels is None:
            logits32 = logits[:, :-1].astype(jnp.float32)
            labels = input_ids[:, 1:]
            if loss_mask is not None:
                loss_mask = loss_mask[:, 1:]
        else:
            logits32 = logits.astype(jnp.float32)
        logz = jax.scipy.special.logsumexp(logits32, axis=-1)
        gold = jnp.take_along_axis(logits32, labels[..., None], axis=-1)[..., 0]
        nll = logz - gold
        if loss_mask is not None:
            mask = loss_mask.astype(jnp.float32)
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.mean(nll)


# ------------------------------------------------------------- int8 weights
def quantize_for_inference(cfg: GPTConfig, params, bits: int = 8,
                           group_size: int = 128):
    """Replace the stacked block weight matrices with per-layer-grouped int8
    ``{"q", "s"}`` leaves. The cached forward feeds them to the Pallas
    int8-weight matmul (``ops/pallas/int8_matmul.py``): s8 stays in HBM and
    dequantization happens per VMEM tile, so no bf16 weight buffer exists at
    any scope (parity: the reference's int8 inference kernels consuming
    quantized weights directly, ``csrc/transformer/inference/csrc/
    dequantize.cu`` + GroupQuantizer, ``module_inject/replace_module.py:144``).
    ``group_size`` defaults to 128 — the kernel needs scale runs covering
    whole lanes; smaller groups fall back to XLA dequant-then-matmul."""
    from ..ops.quantizer import quantize

    L = cfg.n_layer
    blocks = {}
    for k, v in params["blocks"].items():
        per_layer = int(v.size) // L
        if v.ndim >= 3 and per_layer % group_size == 0 and not k.startswith("ln"):
            ng_l = max(1, per_layer // group_size)
            q, s = quantize(v, bits=bits, num_groups=L * ng_l)
            if bits == 4 and v.shape[-1] % 2 == 0:
                # two nibbles per byte (pack_int4 half-split layout): the
                # weight stack shrinks to a QUARTER of bf16 — 20B decode
                # becomes chip-resident on one v5e
                from ..ops.pallas.int8_matmul import pack_int4

                blocks[k] = {"q4": pack_int4(q), "s": s.reshape(L, ng_l)}
            else:
                blocks[k] = {"q": q, "s": s.reshape(L, ng_l)}
        else:
            blocks[k] = v
    out = dict(params)
    out["blocks"] = blocks
    return out


def init_quantized_decode_params(cfg: GPTConfig, seed: int = 0,
                                 bits: int = 4, group_size: int = 128,
                                 compute_dtype=jnp.bfloat16):
    """Build the quantized decode tree WITHOUT ever materializing the fp32
    model: layer units are host-initialized one at a time (``GPTStream``
    numpy init), quantized + nibble-packed in numpy, and only the narrow
    stacks are pushed to the device. A 20B model's device footprint is the
    ~10 GB int4 stacks + bf16 embeddings — the fp32 tree (80 GB) that
    ``init_params`` -> ``quantize_for_inference`` would need never exists on
    host OR device, which is what makes a MEASURED 20B-decode row possible
    on one chip. Quantization math is bit-identical to
    ``ops/quantizer.quantize`` (symmetric group-wise, round-half-even)."""
    import ml_dtypes

    s = GPTStream(cfg)
    L = cfg.n_layer
    qmax = 2.0 ** (bits - 1) - 1.0
    cd_np = (ml_dtypes.bfloat16 if jnp.dtype(compute_dtype) == jnp.bfloat16
             else np.float32)

    def np_quantize(w, ng):
        g = np.ascontiguousarray(w, np.float32).reshape(ng, -1)
        absmax = np.max(np.abs(g), axis=1, keepdims=True)
        scales = np.where(absmax > 0, absmax / qmax, 1.0).astype(np.float32)
        q = np.clip(np.round(g / scales), -qmax - 1, qmax).astype(np.int8)
        return q.reshape(w.shape), scales[:, 0]

    def np_pack4(q):
        F = q.shape[-1]
        lo = q[..., : F // 2].astype(np.int32) & 0xF
        hi = q[..., F // 2:].astype(np.int32)
        return (lo | (hi << 4)).astype(np.int8)

    acc_q: Dict[str, list] = {}
    acc_s: Dict[str, list] = {}
    acc_dense: Dict[str, list] = {}
    packed_keys = set()
    for i in range(L):
        unit = s.init_unit(f"layer_{i}", seed)
        for k, v in unit.items():
            # same predicate as quantize_for_inference (there: stacked
            # ndim >= 3 == per-layer ndim >= 2)
            if (v.ndim >= 2 and v.size % group_size == 0
                    and not k.startswith("ln")):
                q, sc = np_quantize(v, v.size // group_size)
                if bits == 4 and v.shape[-1] % 2 == 0:
                    q = np_pack4(q)
                    packed_keys.add(k)
                acc_q.setdefault(k, []).append(q)
                acc_s.setdefault(k, []).append(sc)
            else:
                acc_dense.setdefault(k, []).append(v.astype(cd_np))
        del unit
    blocks: Dict[str, Any] = {}
    for k in acc_q:
        qk = "q4" if k in packed_keys else "q"
        blocks[k] = {qk: jnp.asarray(np.stack(acc_q[k])),
                     "s": jnp.asarray(np.stack(acc_s[k]))}
        acc_q[k] = None
    for k in acc_dense:
        blocks[k] = jnp.asarray(np.stack(acc_dense[k]))
    params: Dict[str, Any] = {"blocks": blocks}
    for unit in ("embed", "final"):
        for k, v in s.init_unit(unit, seed).items():
            params[k] = jnp.asarray(v.astype(cd_np))
    return params


def _is_qleaf(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) in ({"q", "s"}, {"q4", "s"})


def quantized_partition_specs(params, specs):
    """Expand spec leaves to match ``{"q", "s"}`` quantized leaves (int8 keeps
    the weight's spec; per-layer scales replicate)."""
    from jax.sharding import PartitionSpec as P_

    def expand(leaf, spec):
        if _is_qleaf(leaf):
            qk = "q4" if "q4" in leaf else "q"
            return {qk: spec, "s": P_(None, None)}
        return spec

    return jax.tree_util.tree_map(
        expand, params, specs, is_leaf=_is_qleaf)


# --------------------------------------------------------------------- KV-cache decode
def init_cache(cfg: GPTConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16):
    """Per-layer stacked KV cache. Parity: the reference's inference workspace
    (``csrc/transformer/inference/includes/inference_context.h``) — here a pytree
    of [L, B, H, S, Dh] arrays living in HBM. Heads lead the sequence axis so the
    Pallas decode kernel streams Mosaic-tileable (block_k, Dh) slices."""
    shape = (cfg.n_layer, batch_size, cfg.n_head, max_len, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def attn_with_cache(cfg: GPTConfig, x, w, k_cache, v_cache, pos, layer_idx=None):
    """Cached self-attention sublayer (pre-LN + residual), shared by the dense
    and MoE cached forwards.

    x: [B, T, D] new tokens (T=prompt len at prefill, 1 at decode);
    k_cache/v_cache: [B, H, S, Dh]; pos: scalar — tokens already in the cache.
    Returns (x + attn_out, k_cache, v_cache).
    """
    B, T, D = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    S = k_cache.shape[2]
    h = layer_norm(x, w["ln1_scale"], w["ln1_bias"], cfg.layer_norm_eps)
    qkv = _wm(h, w["qkv_w"]) + w["qkv_b"]
    q, k_, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, Dh)
    k_ = k_.reshape(B, T, H, Dh)
    v = v.reshape(B, T, H, Dh)
    positions = pos + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    if cfg.rotary:
        rd = int(cfg.rotary_pct * Dh)
        rd -= rd % 2
        q = _rope(q, positions, rd, cfg.rotary_interleaved)
        k_ = _rope(k_, positions, rd, cfg.rotary_interleaved)
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_.transpose(0, 2, 1, 3).astype(k_cache.dtype), (0, 0, pos, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.transpose(0, 2, 1, 3).astype(v_cache.dtype), (0, 0, pos, 0))
    scale = (cfg.attention_scale if cfg.attention_scale is not None
             else 1.0 / np.sqrt(Dh))
    use_kernel = (cfg.use_flash is True
                  or (cfg.use_flash is None and jax.default_backend() == "tpu"))
    if cfg.alibi or cfg.local_attention_period > 1:
        use_kernel = False  # decode kernel has no bias/window input yet
    if T == 1 and use_kernel:
        # per-token decode: fused Pallas cache-attention kernel (parity:
        # softmax_context, csrc/transformer/inference); auto mode gates on the
        # TPU backend like the prefill flash dispatch (ops/attention.py)
        from ..ops.pallas.decode_attention import decode_attention

        attn = decode_attention(q.astype(k_cache.dtype), k_cache, v_cache, pos + 1,
                                softmax_scale=scale)
        attn = attn.reshape(B, T, D).astype(x.dtype)
    else:
        # prefill: attend over the whole cache with a validity+causal mask
        logits = jnp.einsum("bthd,bhsd->bhts", q.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) * scale
        s_idx = jnp.arange(S)[None, :]
        t_idx = positions[:, :, None]  # absolute position of each query token
        mask = s_idx <= t_idx  # [B, T, S]
        is_local = _is_local_layer(cfg, layer_idx)
        if is_local is not None:
            # windowed layers additionally drop keys older than window_size
            mask = jnp.logical_and(
                mask, jnp.logical_or(~is_local, s_idx > t_idx - cfg.window_size))
        if cfg.alibi:
            logits = logits + _alibi_bias(cfg, positions, S)
        logits = jnp.where(mask[:, None, :, :], logits, jnp.float32(-1e30))
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhts,bhsd->bthd", probs.astype(v_cache.dtype), v_cache)
        attn = attn.reshape(B, T, D).astype(x.dtype)
    attn = _wm(attn, w["attn_out_w"]) + w["attn_out_b"]
    return x + attn, k_cache, v_cache


def _block_with_cache(cfg: GPTConfig, x, w, k_cache, v_cache, pos,
                      layer_idx=None):
    """One transformer block (attention + dense MLP) over a KV cache slice."""
    if cfg.parallel_residual:
        y, k_cache, v_cache = attn_with_cache(cfg, x, w, k_cache, v_cache, pos,
                                              layer_idx=layer_idx)
        return y + _mlp_delta(cfg, x, w), k_cache, v_cache
    x, k_cache, v_cache = attn_with_cache(cfg, x, w, k_cache, v_cache, pos,
                                          layer_idx=layer_idx)
    return x + _mlp_delta(cfg, x, w), k_cache, v_cache


def forward_with_cache(cfg: GPTConfig, params, input_ids: jnp.ndarray, cache):
    """Prefill or decode: run ``input_ids`` [B, T] through the model appending to
    ``cache``; returns (logits [B, T, V], new_cache)."""
    B, T = input_ids.shape
    pos = cache["pos"]
    x = jnp.take(params["wte"], input_ids, axis=0)
    positions = pos + jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    if not cfg.rotary and not cfg.alibi:
        x = x + jnp.take(params["wpe"], positions + cfg.pos_offset, axis=0)
    if cfg.embed_layernorm:
        x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                       cfg.layer_norm_eps)
    qkv_w = params["blocks"]["qkv_w"]
    quantized = _is_qleaf(qkv_w)
    compute_dtype = (params["lnf_scale"].dtype if quantized
                     else qkv_w.dtype)
    x = x.astype(compute_dtype)
    x = maybe_shard(x, P(BATCH, None, None))

    blocks = params["blocks"]
    if quantized:
        # int8 stacks are INDEXED per layer, not scanned over: scan xs get a
        # loop-friendly layout, and for a quantized stack XLA realizes that
        # as a full transposed COPY of every weight array (measured: OPT-13B
        # int8 decode carried 11.8 GB of s8 copies — the difference between
        # fitting a 13B model in 15.75 GB HBM and OOMing at 27 GB). A
        # dynamic_index_in_dim on the leading axis reads the argument buffer
        # in place; the {q,s} leaves then flow into the Pallas int8-weight
        # matmuls via _wm — no bf16 weight buffer exists at any scope.
        def body(carry, layer_in):
            x, i = carry
            k_c, v_c = layer_in
            layer_w = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, i, 0,
                                                       keepdims=False),
                blocks)
            # {q,s} leaves flow straight into the int8-weight Pallas matmuls
            # (_wm); no bf16 weight buffer exists at any scope
            x, k_c, v_c = _block_with_cache(cfg, x, layer_w, k_c, v_c, pos,
                                            layer_idx=i)
            return (x, i + 1), (k_c, v_c)

        (x, _), (new_k, new_v) = jax.lax.scan(
            body, (x, jnp.int32(0)), (cache["k"], cache["v"]))
    else:
        def body(carry, layer_in):
            x, i = carry
            layer_w, k_c, v_c = layer_in
            x, k_c, v_c = _block_with_cache(cfg, x, layer_w, k_c, v_c, pos,
                                            layer_idx=i)
            return (x, i + 1), (k_c, v_c)

        (x, _), (new_k, new_v) = jax.lax.scan(
            body, (x, jnp.int32(0)), (blocks, cache["k"], cache["v"]))
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], cfg.layer_norm_eps)
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    if cfg.lm_head_bias and not cfg.tie_embeddings:
        logits = logits + params["lm_head_b"].astype(logits.dtype)
    return logits, {"k": new_k, "v": new_v, "pos": pos + T}


# ----------------------------------------------------------- paged KV decode
KV_QMAX = {8: 127.0, 4: 7.0}


def init_paged_cache(cfg: GPTConfig, num_pages: int, page_size: int,
                     dtype=jnp.bfloat16,
                     kv_bits: Optional[int] = None) -> Dict[str, jnp.ndarray]:
    """Block-allocated KV cache: one shared page pool per layer,
    [L, H, P, page_size, Dh]. Requests own pages through a *block table*
    (``inference/serving/paging.py``); HBM holds ``P * page_size`` token
    slots total, shared by every in-flight request — the vLLM/paged-attention
    memory model, vs the contiguous :func:`init_cache` which reserves
    ``max_len`` slots per batch row whether used or not.

    ``kv_bits`` (8 or 4) stores the pools QUANTIZED: int8 payloads (int4
    nibble-packs two values per byte along Dh) plus one symmetric fp32
    scale per (layer, head, page) in ``k_scales``/``v_scales`` —
    2x/4x the token capacity at fixed HBM vs bf16 pools, dequantized per
    tile inside the Pallas decode kernel. A quantized cache is recognized
    by the presence of the scale stacks.

    Page 0 is the allocator's reserved sink: inactive decode slots and
    masked scatter lanes write there, so pool page ids handed to requests
    start at 1."""
    if kv_bits is None or kv_bits == 0:
        shape = (cfg.n_layer, cfg.n_head, num_pages, page_size, cfg.head_dim)
        return {"k_pages": jnp.zeros(shape, dtype),
                "v_pages": jnp.zeros(shape, dtype)}
    if kv_bits not in KV_QMAX:
        raise ValueError(f"kv_bits must be 8 or 4 (or None), got {kv_bits}")
    if kv_bits == 4 and cfg.head_dim % 2:
        raise ValueError("int4 KV needs an even head_dim (nibble packing)")
    dq = cfg.head_dim // 2 if kv_bits == 4 else cfg.head_dim
    shape = (cfg.n_layer, cfg.n_head, num_pages, page_size, dq)
    sshape = (cfg.n_layer, cfg.n_head, num_pages)
    return {"k_pages": jnp.zeros(shape, jnp.int8),
            "v_pages": jnp.zeros(shape, jnp.int8),
            "k_scales": jnp.ones(sshape, jnp.float32),
            "v_scales": jnp.ones(sshape, jnp.float32)}


def paged_cache_bits(paged_cache, head_dim: int) -> Optional[int]:
    """The cache's KV quantization width (None = dense pools)."""
    if "k_scales" not in paged_cache:
        return None
    return 4 if paged_cache["k_pages"].shape[-1] * 2 == head_dim else 8


def paged_kv_bytes_per_token(cfg: GPTConfig, kv_bits: Optional[int] = None,
                             page_size: int = 64,
                             dtype=jnp.bfloat16) -> float:
    """HBM bytes one cached token costs in an :func:`init_paged_cache`
    pool: dense payload at ``dtype``, or quantized payload at ``kv_bits``
    plus the amortized fp32 per-(layer, head, page) scales. The ONE byte
    formula shared by the AOT fit ladder, the serving engine's equal-HBM
    A/B axis, and the bench's emulated pool sizing — a scale-layout change
    in ``init_paged_cache`` must be priced here, once."""
    per_tok = 2 * cfg.n_layer * cfg.n_head * cfg.head_dim
    if not kv_bits:
        return float(per_tok * jnp.dtype(dtype).itemsize)
    payload = per_tok // (2 if kv_bits == 4 else 1)
    scales = 2 * cfg.n_layer * cfg.n_head * 4 / page_size
    return float(payload + scales)


def _pack_kv_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Values in [-8, 7] pack two per byte along the last dim — the one
    canonical half-split layout (``ops.pallas.int8_matmul.pack_int4``,
    inverted by ``decode_attention.unpack_kv_int4``)."""
    from ..ops.pallas.int8_matmul import pack_int4

    return pack_int4(q)


def write_prompt_kv_batch(paged_cache: Dict[str, jnp.ndarray],
                          dense_cache: Dict[str, jnp.ndarray],
                          block_tables: jnp.ndarray,  # [F, pages_per_seq]
                          lengths: jnp.ndarray,       # [F] valid tokens/row
                          starts: Optional[jnp.ndarray] = None,  # [F] or 0
                          ) -> Dict[str, jnp.ndarray]:
    """Scatter a BATCH of prefilled requests' dense K/V into their pages.

    Prefill runs on the contiguous cache (the existing, tested
    :func:`forward_with_cache` path, compiled per bucket shape); each row's
    K/V is then placed into the pages its block-table row names — the
    prefill/decode disaggregation boundary. Positions past a row's length
    (bucket padding, or a wholly inactive row with length 0) scatter out of
    bounds and are dropped. ``starts`` additionally drops positions BELOW a
    per-row floor: a request admitted with shared prefix pages
    (copy-on-write prefix caching) must never write the pages it only
    borrows, so its scatter begins at the first unshared position.

    Quantized pools (``init_paged_cache(kv_bits=...)``) quantize at scatter
    time: one symmetric scale per (layer, head, page) from the absmax of
    the tokens landing in that page, payloads rounded/clipped exactly like
    ``ops.quantizer.quantize``."""
    k = dense_cache["k"]  # [L, F, H, S, Dh]
    v = dense_cache["v"]
    S = k.shape[3]
    F = k.shape[1]
    P = paged_cache["k_pages"].shape[2]
    ps = paged_cache["k_pages"].shape[3]
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (F, S))
    tables = jnp.asarray(block_tables, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    if starts is None:
        starts = jnp.zeros((F,), jnp.int32)
    else:
        starts = jnp.broadcast_to(jnp.asarray(starts, jnp.int32), (F,))
    page_of_pos = jnp.take_along_axis(tables, pos // ps, axis=1)  # [F, S]
    valid = (pos >= starts[:, None]) & (pos < lengths[:, None])
    # invalid positions get page id P (out of bounds) -> mode="drop"
    page = jnp.where(valid, page_of_pos, P)
    off = pos % ps
    bits = paged_cache_bits(paged_cache, k.shape[-1])
    if bits is None:
        dt = paged_cache["k_pages"].dtype
        # k_pages[l, h, page[f, s], off[f, s], :] = k[l, f, h, s, :]
        return {
            "k_pages": paged_cache["k_pages"].at[:, :, page, off, :].set(
                k.transpose(0, 2, 1, 3, 4).astype(dt), mode="drop"),
            "v_pages": paged_cache["v_pages"].at[:, :, page, off, :].set(
                v.transpose(0, 2, 1, 3, 4).astype(dt), mode="drop"),
        }
    qmax = KV_QMAX[bits]
    L, _, H, _, Dh = k.shape
    Sp = -(-S // ps) * ps  # pad S up to whole pages for the grouped absmax
    npg = Sp // ps
    vmask = valid
    if Sp != S:
        vmask = jnp.concatenate(
            [valid, jnp.zeros((F, Sp - S), bool)], axis=1)
    vmask_g = vmask.reshape(F, npg, ps)
    any_valid = vmask_g.any(axis=2)  # [F, npg]
    # page ids per (row, page-slot); unwritten pages scatter out of bounds.
    # The dense scratch may be PADDED past the table (its S rounds up to
    # whole prefill chunks, the table to whole pages of max_model_len) —
    # pad the excess page slots with the drop index; they can never hold a
    # valid token, matching the dense path's clip-then-mask semantics.
    tbl = tables[:, :npg]
    if tbl.shape[1] < npg:
        tbl = jnp.concatenate(
            [tbl, jnp.full((F, npg - tbl.shape[1]), P, jnp.int32)], axis=1)
    pages_w = jnp.where(any_valid, tbl, P)

    def quantize_side(x, pages_key, scales_key):
        xt = x.transpose(0, 2, 1, 3, 4).astype(jnp.float32)  # [L,H,F,S,Dh]
        if Sp != S:
            xt = jnp.concatenate(
                [xt, jnp.zeros(xt.shape[:3] + (Sp - S, Dh), jnp.float32)],
                axis=3)
        xg = xt.reshape(L, H, F, npg, ps, Dh)
        amax = jnp.max(jnp.abs(xg) * vmask_g[None, None, :, :, :, None],
                       axis=(4, 5))                          # [L,H,F,npg]
        scales = jnp.where(amax > 0, amax / qmax, 1.0)
        q = jnp.clip(jnp.round(xg / scales[..., None, None]),
                     -qmax - 1, qmax)
        if bits == 4:
            q = _pack_kv_int4(q)
        else:
            q = q.astype(jnp.int8)
        q = q.reshape(L, H, F, Sp, q.shape[-1])[:, :, :, :S]
        return {
            pages_key: paged_cache[pages_key].at[:, :, page, off, :].set(
                q, mode="drop"),
            # k_scales[l, h, pages_w[f, j]] = scales[l, h, f, j]
            scales_key: paged_cache[scales_key].at[:, :, pages_w].set(
                scales, mode="drop"),
        }

    out = quantize_side(k, "k_pages", "k_scales")
    out.update(quantize_side(v, "v_pages", "v_scales"))
    return out


def write_prompt_kv(paged_cache: Dict[str, jnp.ndarray],
                    dense_cache: Dict[str, jnp.ndarray],
                    block_table: jnp.ndarray,  # [pages_per_seq] int32
                    length: jnp.ndarray,       # scalar int32: valid tokens
                    row: int = 0,
                    start: jnp.ndarray = 0) -> Dict[str, jnp.ndarray]:
    """Single-request :func:`write_prompt_kv_batch` over ``dense_cache`` row
    ``row``. ``start`` skips positions below it (shared prefix pages)."""
    one = {"k": dense_cache["k"][:, row:row + 1],
           "v": dense_cache["v"][:, row:row + 1]}
    table = jnp.asarray(block_table, jnp.int32)[None]
    return write_prompt_kv_batch(paged_cache, one, table,
                                 jnp.asarray(length, jnp.int32)[None],
                                 jnp.asarray(start, jnp.int32)[None])


def _append_kv_token(pages_q: jnp.ndarray, scales: jnp.ndarray,
                     tok: jnp.ndarray, page: jnp.ndarray, off: jnp.ndarray,
                     bits: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """THE sequential quantized-pool append: one token per batch row into its
    tail page. ``pages_q``: [H, P, ps, Dq]; ``scales``: [H, P]; ``tok``:
    [H, B, Dh] float32; ``page``/``off``: [B].

    A row OPENING a page (offset 0) re-establishes the page scale from its
    own token (the pool's prior value there is garbage — init, or a recycled
    page's previous tenant); mid-page the scale grows monotonically and, on
    the rare step where some row's scale actually grew, the page's existing
    payload requantizes under it via ``lax.cond`` (ratio 1.0 rows round-trip
    bit-identically). Shared by the single-token decode step AND the
    speculative commit scatter (:func:`commit_window_kv`) so the two paths
    cannot drift — committing n accepted tokens reproduces n sequential
    appends of the same values (payloads bitwise; scales to the last ULP,
    where XLA may compile the ``amax / qmax`` divide as a reciprocal
    multiply in one program and not the other)."""
    from ..ops.pallas.decode_attention import unpack_kv_int4

    qmax = KV_QMAX[bits]
    B = tok.shape[1]
    opening = (off == 0)[None, :]                     # [1, B]
    s_old = scales[:, page]                           # [H, B]
    amax = jnp.max(jnp.abs(tok), axis=-1)
    fresh = jnp.where(amax > 0, amax / qmax, 1.0)
    s_new = jnp.where(opening, fresh, jnp.maximum(s_old, fresh))
    tq = jnp.clip(jnp.round(tok / s_new[..., None]), -qmax - 1, qmax)
    if bits == 4:
        tq = _pack_kv_int4(tq)
    else:
        tq = tq.astype(jnp.int8)

    def token_only(pages_q):
        # the common decode step: the page scale already covers the
        # token — one [H, B, Dq] position write, no page rewrite
        return pages_q.at[:, page, off, :].set(tq)

    def requantize(pages_q):
        # some mid-page row's scale GREW: rescale that page's existing
        # payload under the new scale (opening rows just overwrite
        # garbage), then insert the token
        cur = pages_q[:, page]                        # [H, B, ps, Dq]
        cur = (unpack_kv_int4(cur) if bits == 4
               else cur.astype(jnp.float32))
        ratio = (s_old / s_new)[..., None, None]
        curq = jnp.clip(jnp.round(cur * ratio), -qmax - 1, qmax)
        curq = (_pack_kv_int4(curq) if bits == 4
                else curq.astype(jnp.int8))
        curq = curq.at[:, jnp.arange(B), off, :].set(tq)
        return pages_q.at[:, page].set(curq)

    grew = jnp.any(jnp.logical_and(~opening, s_new > s_old))
    pages_q = jax.lax.cond(grew, requantize, token_only, pages_q)
    return pages_q, scales.at[:, page].set(s_new)


def _paged_attn_sublayer(cfg: GPTConfig, x, w, k_pages, v_pages, tables,
                         lengths, impl=None, k_scales=None, v_scales=None):
    """Cached self-attention over the page pool (pre-LN + residual) for ONE
    new token per row. x: [B, 1, D]; k_pages/v_pages: [H, P, ps, Dh] (or
    int8 [..., Dh(/2)] with per-page ``k_scales``/``v_scales`` [H, P]);
    tables: [B, pages_per_seq]; lengths: [B] tokens already in the cache
    (the new token is appended at position ``lengths[b]``).
    Returns (x + attn_out, k_pages, v_pages, k_scales, v_scales).

    Quantized append: a row OPENING a new page (offset 0) establishes the
    page scale from its own token — the pool's prior value there is
    garbage (init, or a recycled page's previous tenant). Mid-page, the
    token quantizes against the page scale; when its absmax exceeds what
    the scale covers, the scale GROWS and the page's existing payload
    requantizes under it (one [ps, Dh] elementwise pass, taken via
    ``lax.cond`` only on steps where some row actually grew — the common
    step is a single-position write) — no clipping of outlier tokens,
    scales only ever grow within a page's lifetime."""
    from ..ops.pallas.decode_attention import paged_decode_attention

    B, T, D = x.shape
    assert T == 1
    H, Dh = cfg.n_head, cfg.head_dim
    ps = k_pages.shape[2]
    h = layer_norm(x, w["ln1_scale"], w["ln1_bias"], cfg.layer_norm_eps)
    qkv = _wm(h, w["qkv_w"]) + w["qkv_b"]
    q, k_, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, 1, H, Dh)
    k_ = k_.reshape(B, 1, H, Dh)
    v = v.reshape(B, 1, H, Dh)
    positions = lengths[:, None]  # [B, 1] — each row at its OWN position
    if cfg.rotary:
        rd = int(cfg.rotary_pct * Dh)
        rd -= rd % 2
        q = _rope(q, positions, rd, cfg.rotary_interleaved)
        k_ = _rope(k_, positions, rd, cfg.rotary_interleaved)
    # append the new token's k/v into each row's current tail page
    page = jnp.take_along_axis(tables, (lengths // ps)[:, None],
                               axis=1)[:, 0]  # [B]
    off = lengths % ps
    quantized = k_scales is not None
    if not quantized:
        dt = k_pages.dtype
        k_pages = k_pages.at[:, page, off, :].set(
            k_[:, 0].astype(dt).transpose(1, 0, 2))
        v_pages = v_pages.at[:, page, off, :].set(
            v[:, 0].astype(dt).transpose(1, 0, 2))
    else:
        bits = 4 if k_pages.shape[-1] * 2 == Dh else 8
        # shared sequential append semantics (opening / grow / requantize):
        # _append_kv_token, also the speculative commit scatter's writer
        k_pages, k_scales = _append_kv_token(
            k_pages, k_scales,
            k_[:, 0].transpose(1, 0, 2).astype(jnp.float32), page, off, bits)
        v_pages, v_scales = _append_kv_token(
            v_pages, v_scales,
            v[:, 0].transpose(1, 0, 2).astype(jnp.float32), page, off, bits)
    scale = (cfg.attention_scale if cfg.attention_scale is not None
             else 1.0 / np.sqrt(Dh))
    qdt = x.dtype if quantized else k_pages.dtype
    attn = paged_decode_attention(q.astype(qdt), k_pages, v_pages,
                                  lengths + 1, tables, softmax_scale=scale,
                                  impl=impl, k_scales=k_scales,
                                  v_scales=v_scales)
    attn = attn.reshape(B, 1, D).astype(x.dtype)
    attn = _wm(attn, w["attn_out_w"]) + w["attn_out_b"]
    return x + attn, k_pages, v_pages, k_scales, v_scales


def paged_decode_step(cfg: GPTConfig, params, input_ids: jnp.ndarray,
                      paged_cache: Dict[str, jnp.ndarray],
                      block_tables: jnp.ndarray, lengths: jnp.ndarray,
                      impl: Optional[str] = None
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One decode step over the paged cache: ``input_ids`` [B] (or [B, 1]) new
    tokens, one per slot, each appended at its row's own ``lengths[b]``.
    Returns (logits [B, V], new paged_cache).

    The continuous-batching hot path: B is the FIXED decode slot count, so
    one compiled program serves every step regardless of which requests
    occupy the slots; inactive slots (lengths 0, table row all page-0) write
    to the reserved sink page and produce ignored logits. Supports the dense
    and the quantized ({"q"/"q4","s"}) layer stacks like
    :func:`forward_with_cache`, and dense OR quantized KV pools
    (``init_paged_cache(kv_bits=...)`` — recognized by the scale stacks);
    alibi/local-attention configs are not yet paged."""
    if cfg.alibi or cfg.local_attention_period > 1:
        raise ValueError("paged decode does not support alibi/local-window "
                         "attention yet (the paged kernel has no bias input)")
    ids = jnp.asarray(input_ids)
    if ids.ndim == 1:
        ids = ids[:, None]
    B = ids.shape[0]
    lengths = jnp.asarray(lengths, jnp.int32)
    positions = lengths[:, None]
    x = jnp.take(params["wte"], ids, axis=0)
    if not cfg.rotary and not cfg.alibi:
        x = x + jnp.take(params["wpe"], positions + cfg.pos_offset, axis=0)
    if cfg.embed_layernorm:
        x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                       cfg.layer_norm_eps)
    qkv_w = params["blocks"]["qkv_w"]
    quantized = _is_qleaf(qkv_w)
    kv_q = "k_scales" in paged_cache
    compute_dtype = (params["lnf_scale"].dtype if quantized else qkv_w.dtype)
    x = x.astype(compute_dtype)
    x = maybe_shard(x, P(BATCH, None, None))
    blocks = params["blocks"]

    def one_block(x, layer_w, kv):
        k_p, v_p = kv[0], kv[1]
        k_s, v_s = (kv[2], kv[3]) if kv_q else (None, None)
        y, k_p, v_p, k_s, v_s = _paged_attn_sublayer(
            cfg, x, layer_w, k_p, v_p, block_tables, lengths, impl=impl,
            k_scales=k_s, v_scales=v_s)
        # parallel residual (NeoX/GPT-J): the MLP reads the PRE-attention
        # stream — same composition as _block_with_cache
        mlp_in = x if cfg.parallel_residual else y
        out_kv = (k_p, v_p, k_s, v_s) if kv_q else (k_p, v_p)
        return y + _mlp_delta(cfg, mlp_in, layer_w), out_kv

    kv_xs = ((paged_cache["k_pages"], paged_cache["v_pages"],
              paged_cache["k_scales"], paged_cache["v_scales"]) if kv_q
             else (paged_cache["k_pages"], paged_cache["v_pages"]))
    if quantized:
        # indexed (not scanned) weight stacks — same HBM-copy avoidance as
        # forward_with_cache's quantized branch
        def body(carry, layer_in):
            x, i = carry
            layer_w = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, i, 0,
                                                       keepdims=False),
                blocks)
            x, kv = one_block(x, layer_w, layer_in)
            return (x, i + 1), kv

        (x, _), new_kv = jax.lax.scan(body, (x, jnp.int32(0)), kv_xs)
    else:
        def body(carry, layer_in):
            x, i = carry
            x, kv = one_block(x, layer_in[0], layer_in[1:])
            return (x, i + 1), kv

        (x, _), new_kv = jax.lax.scan(
            body, (x, jnp.int32(0)), (blocks,) + kv_xs)
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"],
                   cfg.layer_norm_eps)
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    if cfg.lm_head_bias and not cfg.tie_embeddings:
        logits = logits + params["lm_head_b"].astype(logits.dtype)
    new_cache = {"k_pages": new_kv[0], "v_pages": new_kv[1]}
    if kv_q:
        new_cache["k_scales"] = new_kv[2]
        new_cache["v_scales"] = new_kv[3]
    return logits[:, 0, :], new_cache


# ------------------------------------------------- speculative verification
def _paged_verify_sublayer(cfg: GPTConfig, x, w, k_pages, v_pages, tables,
                           lengths, impl=None, k_scales=None, v_scales=None):
    """Cached self-attention over the page pool for a ``W``-token
    speculation window per row (pre-LN + residual). x: [B, W, D]; window
    position ``i`` sits at absolute position ``lengths[b] + i`` and attends
    pool history + the window's causal prefix (the window K/V stay DENSE —
    nothing is written to the pool; the accepted prefix commits later via
    :func:`commit_window_kv`). Returns (x + attn_out, win_k, win_v) with
    win_k/win_v [B, W, H, Dh] post-rope in the compute dtype — exactly the
    values a sequential decode step would have appended."""
    from ..ops.pallas.decode_attention import paged_verify_attention

    B, W, D = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    h = layer_norm(x, w["ln1_scale"], w["ln1_bias"], cfg.layer_norm_eps)
    qkv = _wm(h, w["qkv_w"]) + w["qkv_b"]
    q, k_, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, W, H, Dh)
    k_ = k_.reshape(B, W, H, Dh)
    v = v.reshape(B, W, H, Dh)
    positions = lengths[:, None] + jnp.arange(W)[None, :]   # [B, W]
    if cfg.rotary:
        rd = int(cfg.rotary_pct * Dh)
        rd -= rd % 2
        q = _rope(q, positions, rd, cfg.rotary_interleaved)
        k_ = _rope(k_, positions, rd, cfg.rotary_interleaved)
    scale = (cfg.attention_scale if cfg.attention_scale is not None
             else 1.0 / np.sqrt(Dh))
    quantized = k_scales is not None
    qdt = x.dtype if quantized else k_pages.dtype
    attn = paged_verify_attention(q.astype(qdt), k_pages, v_pages, lengths,
                                  tables, k_, v, softmax_scale=scale,
                                  impl=impl, k_scales=k_scales,
                                  v_scales=v_scales)
    attn = attn.reshape(B, W, D).astype(x.dtype)
    attn = _wm(attn, w["attn_out_w"]) + w["attn_out_b"]
    return x + attn, k_, v


def paged_verify_step(cfg: GPTConfig, params, window_ids: jnp.ndarray,
                      paged_cache: Dict[str, jnp.ndarray],
                      block_tables: jnp.ndarray, lengths: jnp.ndarray,
                      impl: Optional[str] = None
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Score a speculation window — ``window_ids`` [B, W] per slot: the
    verified next input token followed by up to W-1 drafted tokens — in ONE
    dispatch over the paged cache. Returns (logits [B, W, V], win_k, win_v)
    where win_k/win_v [L, B, W, H, Dh] are the window's per-layer post-rope
    K/V in the compute dtype.

    The weight-bound speculative-decoding bet: every weight matrix is read
    ONCE for W positions, where W sequential :func:`paged_decode_step`
    dispatches read it W times — verifying k drafted tokens costs barely
    more than one token. The pool is READ-ONLY here: window K/V stay dense
    so the rejected suffix needs no undo, and :func:`commit_window_kv`
    afterwards appends exactly the accepted prefix with sequential-append
    semantics (what spec-off decode would have written, to XLA
    reduction-tiling noise — argmax-stable, gated at
    greedy_match_rate == 1.0). One caveat: over QUANTIZED pools the window
    attends its own in-window context at dense precision while spec-off
    would read those positions int8/int4-round-tripped from the pool —
    spec-on == spec-off there is quantization-tolerance-gated (measured
    1.0 on the tested configs, same bar as the kv8 serving rows), not
    reduction-noise-exact like dense pools. Same model
    support matrix as :func:`paged_decode_step` (dense/quantized weight
    stacks, dense/int8/int4 KV pools; alibi/local attention rejected)."""
    if cfg.alibi or cfg.local_attention_period > 1:
        raise ValueError("paged verification does not support alibi/"
                         "local-window attention yet (same bound as "
                         "paged_decode_step)")
    ids = jnp.asarray(window_ids)
    B, W = ids.shape
    lengths = jnp.asarray(lengths, jnp.int32)
    positions = lengths[:, None] + jnp.arange(W)[None, :]
    x = jnp.take(params["wte"], ids, axis=0)
    if not cfg.rotary and not cfg.alibi:
        x = x + jnp.take(params["wpe"], positions + cfg.pos_offset, axis=0)
    if cfg.embed_layernorm:
        x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                       cfg.layer_norm_eps)
    qkv_w = params["blocks"]["qkv_w"]
    quantized = _is_qleaf(qkv_w)
    kv_q = "k_scales" in paged_cache
    compute_dtype = (params["lnf_scale"].dtype if quantized else qkv_w.dtype)
    x = x.astype(compute_dtype)
    x = maybe_shard(x, P(BATCH, None, None))
    blocks = params["blocks"]

    def one_block(x, layer_w, kv):
        k_p, v_p = kv[0], kv[1]
        k_s, v_s = (kv[2], kv[3]) if kv_q else (None, None)
        y, wk, wv = _paged_verify_sublayer(
            cfg, x, layer_w, k_p, v_p, block_tables, lengths, impl=impl,
            k_scales=k_s, v_scales=v_s)
        mlp_in = x if cfg.parallel_residual else y
        return y + _mlp_delta(cfg, mlp_in, layer_w), (wk, wv)

    kv_xs = ((paged_cache["k_pages"], paged_cache["v_pages"],
              paged_cache["k_scales"], paged_cache["v_scales"]) if kv_q
             else (paged_cache["k_pages"], paged_cache["v_pages"]))
    if quantized:
        def body(carry, layer_in):
            x, i = carry
            layer_w = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, i, 0,
                                                       keepdims=False),
                blocks)
            x, win = one_block(x, layer_w, layer_in)
            return (x, i + 1), win

        (x, _), (win_k, win_v) = jax.lax.scan(body, (x, jnp.int32(0)), kv_xs)
    else:
        def body(carry, layer_in):
            x, i = carry
            x, win = one_block(x, layer_in[0], layer_in[1:])
            return (x, i + 1), win

        (x, _), (win_k, win_v) = jax.lax.scan(
            body, (x, jnp.int32(0)), (blocks,) + kv_xs)
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"],
                   cfg.layer_norm_eps)
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))
    if cfg.lm_head_bias and not cfg.tie_embeddings:
        logits = logits + params["lm_head_b"].astype(logits.dtype)
    return logits, win_k, win_v


def commit_window_kv(paged_cache: Dict[str, jnp.ndarray],
                     win_k: jnp.ndarray,  # [L, B, W, H, Dh]
                     win_v: jnp.ndarray,
                     block_tables: jnp.ndarray,   # [B, pages_per_seq]
                     lengths: jnp.ndarray,        # [B]: pool tokens pre-window
                     n_commit: jnp.ndarray,       # [B]: accepted writes (0..W)
                     ) -> Dict[str, jnp.ndarray]:
    """Append each row's ACCEPTED window prefix — ``n_commit[b]`` tokens at
    positions ``lengths[b] .. lengths[b] + n_commit[b] - 1`` — into the
    paged pool, exactly as ``n_commit[b]`` sequential decode steps would
    have: one :func:`_append_kv_token` per window step, so quantized page
    scales keep the monotone-per-lifetime semantics (opening offsets
    re-establish, mid-page grows requantize) and the committed pool state
    reproduces the spec-off path's (payloads bitwise given the same
    values; see :func:`_append_kv_token` for the last-ULP scale caveat).
    Window positions past the accepted frontier are NEVER written (their
    rows redirect to the reserved sink page 0) — rejected-suffix rollback
    is the absence of a write, not an undo."""
    kv_q = "k_scales" in paged_cache
    ps = paged_cache["k_pages"].shape[3]
    L, B, W, H, Dh = win_k.shape
    tables = jnp.asarray(block_tables, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    n_commit = jnp.asarray(n_commit, jnp.int32)
    bits = paged_cache_bits(paged_cache, Dh)

    def layer_commit(layer_in):
        if kv_q:
            k_p, v_p, k_s, v_s, wk, wv = layer_in
        else:
            k_p, v_p, wk, wv = layer_in
            k_s = v_s = None

        def step(carry, i):
            k_p, v_p, k_s, v_s = carry
            pos = lengths + i
            write = i < n_commit
            pidx = jnp.clip(pos // ps, 0, tables.shape[1] - 1)
            page = jnp.where(
                write, jnp.take_along_axis(tables, pidx[:, None],
                                           axis=1)[:, 0], 0)
            off = pos % ps
            tok_k = wk[:, i].transpose(1, 0, 2)   # [H, B, Dh]
            tok_v = wv[:, i].transpose(1, 0, 2)
            if bits is None:
                dt = k_p.dtype
                k_p = k_p.at[:, page, off, :].set(tok_k.astype(dt))
                v_p = v_p.at[:, page, off, :].set(tok_v.astype(dt))
            else:
                k_p, k_s = _append_kv_token(k_p, k_s,
                                            tok_k.astype(jnp.float32),
                                            page, off, bits)
                v_p, v_s = _append_kv_token(v_p, v_s,
                                            tok_v.astype(jnp.float32),
                                            page, off, bits)
            return (k_p, v_p, k_s, v_s), None

        (k_p, v_p, k_s, v_s), _ = jax.lax.scan(
            step, (k_p, v_p, k_s, v_s), jnp.arange(W))
        return (k_p, v_p, k_s, v_s) if kv_q else (k_p, v_p)

    def body(_, layer_in):
        return None, layer_commit(layer_in)

    xs = ((paged_cache["k_pages"], paged_cache["v_pages"],
           paged_cache["k_scales"], paged_cache["v_scales"], win_k, win_v)
          if kv_q else
          (paged_cache["k_pages"], paged_cache["v_pages"], win_k, win_v))
    _, out = jax.lax.scan(body, None, xs)
    new_cache = {"k_pages": out[0], "v_pages": out[1]}
    if kv_q:
        new_cache["k_scales"] = out[2]
        new_cache["v_scales"] = out[3]
    return new_cache


def build(cfg_or_name) -> Tuple[Module, GPTConfig]:
    """Build a GPT :class:`Module` from a config or preset name."""
    cfg = PRESETS[cfg_or_name] if isinstance(cfg_or_name, str) else cfg_or_name

    def to_pipeline(num_stages: int, num_micro: int) -> Module:
        from . import gpt_pipe

        module, _ = gpt_pipe.build(cfg, num_stages, num_micro)
        return module

    def with_ltd_keep(keep: int, layer_ids) -> Module:
        return build(dataclasses.replace(
            cfg, random_ltd_keep=int(keep),
            random_ltd_layer_ids=tuple(layer_ids)))[0]

    return Module(
        init=functools.partial(init_params, cfg),
        apply=lambda params, batch, rngs=None, train=True, pld_theta=None:
            loss_fn(cfg, params, batch, rngs=rngs, train=train,
                    pld_theta=pld_theta),
        partition_specs=functools.partial(partition_specs, cfg),
        to_pipeline=to_pipeline,
        with_ltd_keep=with_ltd_keep,
        stream=lambda: GPTStream(cfg),
        gpt_config=cfg,
        grad_bucket_key="blocks",
    ), cfg
