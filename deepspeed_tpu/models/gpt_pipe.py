"""Pipeline-parallel GPT: the stacked-layer GPT executed over the ``pp`` mesh axis.

Capability parity with the reference's pipeline training path (``PipelineModule`` +
``PipelineEngine.train_batch``, ``runtime/pipe/module.py:86`` /
``runtime/pipe/engine.py:295``) for its flagship workload (decoder LM). The generic
layer-list machinery lives in :mod:`deepspeed_tpu.runtime.pipe.module`; this module
is the homogeneous-transformer fast path that actually pipelines on TPU:

- block params ``[L, ...]`` are reshaped to ``[S, L/S, ...]`` with the stage axis
  sharded ``P("pp", ...)``;
- micro-batches stream through :func:`~deepspeed_tpu.runtime.pipe.spmd.pipelined_apply`
  (collective-permute pipelining, autodiff backward pipeline);
- embedding and LM head stay outside the pipelined scan, replicated over ``pp``;
  tied-embedding gradients combine automatically (the reference's explicit
  tied-weight allreduce at ``runtime/pipe/module.py:421`` is autodiff here).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.pipe.spmd import (
    pipelined_apply,
    split_microbatches,
    stack_stage_params,
)
from .api import Module, maybe_shard
from . import gpt as G

BATCH = G.BATCH


def init_params(cfg: G.GPTConfig, num_stages: int, rng: jax.Array) -> Dict[str, Any]:
    params = G.init_params(cfg, rng)
    params["blocks"] = stack_stage_params(params["blocks"], num_stages)
    return params


def partition_specs(cfg: G.GPTConfig, num_stages: int, param_shapes) -> Dict[str, Any]:
    """Stage axis over pp; per-layer axis free; tp specs shifted right by one."""
    base = G.partition_specs(cfg, param_shapes)
    base["blocks"] = jax.tree_util.tree_map(
        lambda spec: P("pp", None, *tuple(spec)[1:]), base["blocks"],
        is_leaf=lambda x: isinstance(x, P))
    return base


def forward(cfg: G.GPTConfig, num_stages: int, num_micro: int, params,
            input_ids: jnp.ndarray, rngs=None, train: bool = True,
            return_hidden: bool = False) -> jnp.ndarray:
    """Logits [B, T, V] via pipelined blocks (or the post-LN hidden states
    with ``return_hidden``). B must divide by num_micro."""
    B, T = input_ids.shape
    if T > cfg.max_seq_len:
        raise ValueError(
            f"sequence length {T} exceeds max_seq_len {cfg.max_seq_len} "
            f"(out-of-range position lookups would return NaN)")
    x = jnp.take(params["wte"], input_ids, axis=0)
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    if not cfg.rotary:
        x = x + jnp.take(params["wpe"], positions + cfg.pos_offset, axis=0)
    x = x.astype(params["blocks"]["qkv_w"].dtype)

    drng = (rngs or {}).get("dropout")
    # positions per micro-batch are identical slices; recompute inside the stage
    mb = B // num_micro
    stream = split_microbatches(x, num_micro)  # [M, mb, T, D]

    layers_per_stage = cfg.n_layer // num_stages

    def stage_fn(w, x, micro_id, stage_id):
        # w: blocks dict with leading [L/S]; one micro-batch x: [mb, T, D]
        pos = jnp.broadcast_to(jnp.arange(T)[None, :], (x.shape[0], T))

        def body(carry, layer_w):
            x, i = carry  # i = GLOBAL layer index (matches dense rng folding)
            lrng = (jax.random.fold_in(jax.random.fold_in(drng, micro_id), i)
                    if drng is not None else None)
            x = G._block(cfg, x, layer_w, pos, lrng, train, layer_idx=i)
            return (x, i + 1), None

        (x, _), _ = jax.lax.scan(
            body, (x, stage_id * layers_per_stage), w)
        return x

    stream_spec = P(BATCH, None, None)  # [mb, T, D] per micro-batch
    out = pipelined_apply(
        stage_fn, params["blocks"], stream, num_stages,
        stream_spec=stream_spec, remat=True)
    x = out.reshape(B, T, -1)
    x = maybe_shard(x, P(BATCH, None, None))
    x = G.layer_norm(x, params["lnf_scale"], params["lnf_bias"], cfg.layer_norm_eps)
    if return_hidden:
        return x
    head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    return jnp.einsum("btd,vd->btv", x, head.astype(x.dtype))


def loss_fn(cfg: G.GPTConfig, num_stages: int, num_micro: int, params, batch,
            rngs=None, train: bool = True):
    if cfg.loss_chunk:
        # same chunked head as the dense model — the fp32 [B,T,V] logits
        # never materialize (G.chunked_head_loss)
        ids_in, targets, mask, n_tok = G._chunk_targets(cfg, batch)
        hidden = forward(cfg, num_stages, num_micro, params, ids_in,
                         rngs=rngs, train=train, return_hidden=True)
        return G.chunked_head_loss(cfg, params, hidden, targets, mask,
                                   num_tokens=n_tok)
    return G.next_token_loss(
        lambda ids: forward(cfg, num_stages, num_micro, params, ids,
                            rngs=rngs, train=train),
        cfg.max_seq_len, batch)


def build(cfg_or_name, num_stages: int, num_micro: int) -> Tuple[Module, G.GPTConfig]:
    """Pipeline-parallel GPT :class:`Module`. ``num_stages`` must equal the mesh's
    ``pp`` extent; ``cfg.n_layer`` must divide by it; the per-step batch must
    divide by ``num_micro``."""
    cfg = G.PRESETS[cfg_or_name] if isinstance(cfg_or_name, str) else cfg_or_name
    if cfg.n_layer % num_stages != 0:
        raise ValueError(f"n_layer {cfg.n_layer} % stages {num_stages} != 0")
    return Module(
        init=functools.partial(init_params, cfg, num_stages),
        apply=lambda params, batch, rngs=None, train=True: loss_fn(
            cfg, num_stages, num_micro, params, batch, rngs=rngs, train=train),
        partition_specs=functools.partial(partition_specs, cfg, num_stages),
        pipelined=True,
    ), cfg
