from .api import Module, replicated_specs
from .gpt import GPTConfig, PRESETS, build as build_gpt

__all__ = ["Module", "replicated_specs", "GPTConfig", "PRESETS", "build_gpt"]
