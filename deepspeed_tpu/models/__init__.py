from .api import Module, replicated_specs
from .gpt import GPTConfig, PRESETS, build as build_gpt
from .gpt_moe import GPTMoEConfig, build as build_gpt_moe
from .gpt_moe import PRESETS as MOE_PRESETS

__all__ = [
    "Module", "replicated_specs", "GPTConfig", "PRESETS", "build_gpt",
    "GPTMoEConfig", "MOE_PRESETS", "build_gpt_moe",
]
