"""BERT-family encoder models, TPU-first.

Capability parity with the reference's BERT workloads: the fused training
transformer kernel targets BERT (``csrc/transformer/ds_transformer_cuda.cpp``,
``DeepSpeedTransformerLayer`` ``ops/transformer/transformer.py:459``), the
flagship benchmark is BERT SQuAD fine-tuning (``docs/_posts/2020-05-28-fastest-
bert-training.md``), and the inference policies cover bert/distilbert
(``module_inject/containers/bert.py``).

Same TPU-first structure as :mod:`.gpt`: stacked per-layer params under a
``lax.scan``, Megatron-style TP specs, flash/XLA attention dispatch. Post-LN
residuals (original BERT), learned positions + token-type embeddings, MLM head
with tied decoder; the ``Module`` loss is masked-LM cross-entropy over
``labels`` (-100 = unmasked, the HF convention).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import multihead_attention
from .api import Module, maybe_shard
from .gpt import layer_norm

BATCH = ("dp", "ep")


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None  # default 4*d_model
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.0
    layer_norm_eps: float = 1e-12
    use_flash: Optional[bool] = None
    # a SparsityConfig routes attention through the blocksparse kernel
    # (graft via ops.sparse_attention.sparse_attention_utils)
    sparse_attention: Optional[Any] = None

    @property
    def ffn_dim(self) -> int:
        return self.d_ff if self.d_ff is not None else 4 * self.d_model

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head


PRESETS: Dict[str, BertConfig] = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(n_layer=24, n_head=16, d_model=1024),
    "tiny-bert": BertConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                            max_seq_len=128),
}


# --------------------------------------------------------------------------- init
def init_params(cfg: BertConfig, rng: jax.Array) -> Dict[str, Any]:
    d, f, v, l = cfg.d_model, cfg.ffn_dim, cfg.vocab_size, cfg.n_layer
    k = jax.random.split(rng, 8)
    std = 0.02

    def normal(key, shape, s=std):
        return jax.random.normal(key, shape, jnp.float32) * s

    return {
        "wte": normal(k[0], (v, d)),
        "wpe": normal(k[1], (cfg.max_seq_len, d)),
        "wtt": normal(k[2], (cfg.type_vocab_size, d)),
        "emb_ln_scale": jnp.ones((d,)), "emb_ln_bias": jnp.zeros((d,)),
        "blocks": {
            "qkv_w": normal(k[3], (l, d, 3 * d)), "qkv_b": jnp.zeros((l, 3 * d)),
            "attn_out_w": normal(k[4], (l, d, d)), "attn_out_b": jnp.zeros((l, d)),
            "ln1_scale": jnp.ones((l, d)), "ln1_bias": jnp.zeros((l, d)),
            "mlp_up_w": normal(k[5], (l, d, f)), "mlp_up_b": jnp.zeros((l, f)),
            "mlp_down_w": normal(k[6], (l, f, d)), "mlp_down_b": jnp.zeros((l, d)),
            "ln2_scale": jnp.ones((l, d)), "ln2_bias": jnp.zeros((l, d)),
        },
        # MLM head: dense transform + LN; decoder tied to wte with its own bias
        "mlm_dense_w": normal(k[7], (d, d)), "mlm_dense_b": jnp.zeros((d,)),
        "mlm_ln_scale": jnp.ones((d,)), "mlm_ln_bias": jnp.zeros((d,)),
        "mlm_bias": jnp.zeros((v,)),
        # pooler (for sentence-level tasks)
        "pooler_w": normal(k[0], (d, d)), "pooler_b": jnp.zeros((d,)),
    }


def partition_specs(cfg: BertConfig, param_shapes) -> Dict[str, Any]:
    return {
        "wte": P("tp", None), "wpe": P(None, None), "wtt": P(None, None),
        "emb_ln_scale": P(None), "emb_ln_bias": P(None),
        "blocks": {
            "qkv_w": P(None, None, "tp"), "qkv_b": P(None, "tp"),
            "attn_out_w": P(None, "tp", None), "attn_out_b": P(None, None),
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "mlp_up_w": P(None, None, "tp"), "mlp_up_b": P(None, "tp"),
            "mlp_down_w": P(None, "tp", None), "mlp_down_b": P(None, None),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
        },
        "mlm_dense_w": P(None, None), "mlm_dense_b": P(None),
        "mlm_ln_scale": P(None), "mlm_ln_bias": P(None),
        "mlm_bias": P("tp"),
        "pooler_w": P(None, None), "pooler_b": P(None),
    }


# --------------------------------------------------------------------------- fwd
def _block(cfg: BertConfig, x, w, pad_bias):
    """Post-LN encoder block: LN(x + attn(x)), LN(x + mlp(x))."""
    B, T, D = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    qkv = x @ w["qkv_w"] + w["qkv_b"]
    q, k_, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, Dh)
    k_ = k_.reshape(B, T, H, Dh)
    v = v.reshape(B, T, H, Dh)
    if getattr(cfg, "sparse_attention", None) is not None:
        if pad_bias is not None:
            raise ValueError(
                "sparse_attention + attention_mask is unsupported (the "
                "blocksparse kernel has no bias input); drop the mask or pad "
                "with ops.sparse_attention.sparse_attention_utils helpers")
        from ..ops.sparse_attention import sparse_attention as _sparse

        attn = _sparse(q, k_, v, cfg.sparse_attention, causal=False)
    else:
        attn = multihead_attention(q, k_, v, causal=False, bias=pad_bias,
                                   use_flash=False if pad_bias is not None
                                   else cfg.use_flash)
    attn = attn.reshape(B, T, D) @ w["attn_out_w"] + w["attn_out_b"]
    x = layer_norm(x + attn, w["ln1_scale"], w["ln1_bias"], cfg.layer_norm_eps)
    h = jax.nn.gelu(x @ w["mlp_up_w"] + w["mlp_up_b"], approximate=False)
    h = h @ w["mlp_down_w"] + w["mlp_down_b"]
    return layer_norm(x + h, w["ln2_scale"], w["ln2_bias"], cfg.layer_norm_eps)


def encode(cfg: BertConfig, params, input_ids: jnp.ndarray,
           attention_mask: Optional[jnp.ndarray] = None,
           token_type_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Hidden states [B, T, D]."""
    B, T = input_ids.shape
    if T > cfg.max_seq_len:
        raise ValueError(f"sequence length {T} exceeds max_seq_len {cfg.max_seq_len}")
    x = jnp.take(params["wte"], input_ids, axis=0)
    x = x + params["wpe"][None, :T, :]
    if token_type_ids is not None:
        x = x + jnp.take(params["wtt"], token_type_ids, axis=0)
    else:
        x = x + params["wtt"][0][None, None, :]
    x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                   cfg.layer_norm_eps)
    x = x.astype(params["blocks"]["qkv_w"].dtype)
    x = maybe_shard(x, P(BATCH, None, None))

    pad_bias = None
    if attention_mask is not None:
        pad_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                             -1e30).astype(jnp.float32)

    def body(x, layer_w):
        return _block(cfg, x, layer_w, pad_bias), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return x


def mlm_logits(cfg: BertConfig, params, hidden: jnp.ndarray) -> jnp.ndarray:
    h = hidden @ params["mlm_dense_w"].astype(hidden.dtype) + \
        params["mlm_dense_b"].astype(hidden.dtype)
    h = jax.nn.gelu(h, approximate=False)
    h = layer_norm(h, params["mlm_ln_scale"], params["mlm_ln_bias"],
                   cfg.layer_norm_eps)
    return jnp.einsum("btd,vd->btv", h, params["wte"].astype(h.dtype)) + \
        params["mlm_bias"].astype(h.dtype)


def pooled_output(params, hidden: jnp.ndarray) -> jnp.ndarray:
    cls = hidden[:, 0, :]
    return jnp.tanh(cls @ params["pooler_w"].astype(cls.dtype)
                    + params["pooler_b"].astype(cls.dtype))


def init_classifier(cfg: BertConfig, num_labels: int,
                    rng: jax.Array) -> Dict[str, jnp.ndarray]:
    """Sentence-task head over the pooled [CLS] (parity: the fine-tuning
    surface the reference's fused BERT kernel targets — SQuAD/GLUE heads)."""
    w = jax.random.normal(rng, (cfg.d_model, num_labels), jnp.float32) * 0.02
    return {"cls_w": w, "cls_b": jnp.zeros((num_labels,))}


def classification_logits(cfg: BertConfig, params, head,
                          input_ids: jnp.ndarray,
                          attention_mask=None,
                          token_type_ids=None) -> jnp.ndarray:
    """[B, num_labels] logits from pooled encoder output."""
    hidden = encode(cfg, params, input_ids, attention_mask=attention_mask,
                    token_type_ids=token_type_ids)
    pooled = pooled_output(params, hidden)
    return pooled @ head["cls_w"].astype(pooled.dtype) + \
        head["cls_b"].astype(pooled.dtype)


def mlm_loss(cfg: BertConfig, params, batch: Dict[str, jnp.ndarray],
             rngs=None, train: bool = True):
    """Masked-LM cross-entropy; labels==-100 positions are ignored (HF
    convention). Without "labels", every position contributes (sanity mode)."""
    hidden = encode(cfg, params, batch["input_ids"],
                    attention_mask=batch.get("attention_mask"),
                    token_type_ids=batch.get("token_type_ids"))
    logits = mlm_logits(cfg, params, hidden).astype(jnp.float32)
    labels = batch.get("labels", batch["input_ids"])
    mask = (labels != -100)
    safe_labels = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    loss = jnp.where(mask, nll, 0.0).sum() / denom
    correct = (jnp.argmax(logits, -1) == safe_labels) & mask
    return loss, {"mlm_acc": correct.sum() / denom}


def build(cfg_or_name) -> Tuple[Module, BertConfig]:
    cfg = PRESETS[cfg_or_name] if isinstance(cfg_or_name, str) else cfg_or_name
    return Module(
        init=functools.partial(init_params, cfg),
        apply=lambda params, batch, rngs=None, train=True: mlm_loss(
            cfg, params, batch, rngs=rngs, train=train),
        partition_specs=functools.partial(partition_specs, cfg),
    ), cfg
