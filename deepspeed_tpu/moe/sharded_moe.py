"""GShard-style top-k gating and dispatch, TPU-first.

Capability parity with the reference's ``deepspeed/moe/sharded_moe.py`` (``TopKGate``
``:351``, ``top1gating`` ``:177``, ``top2gating`` ``:278``, ``MOELayer`` ``:419``):
capacity-factor token routing with jitter noise, load-balance auxiliary loss,
capacity overflow dropping, and the einsum dispatch/combine formulation.

TPU-native design: the reference moves tokens between expert ranks with an explicit
``_AllToAll`` autograd function (``sharded_moe.py:89``) over a torch process group.
Here dispatch/combine are einsums against a one-hot dispatch mask and the routed
tensor is sharding-constrained onto the ``ep`` mesh axis — XLA emits the all-to-all
(and its transpose in the backward pass) automatically, scheduled on ICI.

Gating runs per *group* (leading ``G`` dim), matching GShard and the reference's
per-rank groups: capacity and the position cumsum are group-local, so no
cross-device serialization in the routing math.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def compute_capacity(tokens_per_group: int, num_experts: int,
                     capacity_factor: float, min_capacity: int = 4) -> int:
    """Static per-expert capacity. Parity: ``sharded_moe.py:191-197`` (capacity =
    tokens/experts * factor, floored at min_capacity). Static => XLA-friendly."""
    cap = int(np.ceil(tokens_per_group / num_experts * capacity_factor))
    return max(cap, int(min_capacity))


def _one_hot(x: jnp.ndarray, n: int, dtype=jnp.float32) -> jnp.ndarray:
    return jax.nn.one_hot(x, n, dtype=dtype)


def _load_balance_loss(gates: jnp.ndarray, mask1: jnp.ndarray) -> jnp.ndarray:
    """GShard aux loss: E * sum_e mean_t(gates[t,e]) * mean_t(routed[t,e]).
    Parity: ``sharded_moe.py:212-216``."""
    num_experts = gates.shape[-1]
    me = jnp.mean(gates, axis=-2)          # [..., E] mean gate prob
    ce = jnp.mean(mask1, axis=-2)          # [..., E] fraction routed
    return jnp.mean(jnp.sum(me * ce, axis=-1)) * num_experts


def top1gating(
    logits: jnp.ndarray,
    capacity: int,
    rng: Optional[jax.Array] = None,
    noisy_gate_policy: Optional[str] = None,
    drop_tokens: bool = True,
    use_rts: bool = True,
    train: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 gating. ``logits``: [G, N, E]. Returns
    (aux_loss, combine_weights [G,N,E,C], dispatch_mask [G,N,E,C], exp_counts [G,E]).

    Parity: ``sharded_moe.py:177-275`` including RSample noisy gating (jitter on the
    routing argmax only) and random-token-selection (RTS) tie-breaking for which
    tokens win capacity slots.
    """
    G, N, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    route_logits = logits
    if train and noisy_gate_policy == "RSample" and rng is not None:
        route_logits = logits + jax.random.normal(rng, logits.shape, logits.dtype)
    index1 = jnp.argmax(route_logits, axis=-1)                   # [G, N]
    mask1 = _one_hot(index1, E)                                   # [G, N, E]

    aux = _load_balance_loss(gates, mask1)
    exp_counts = jnp.sum(mask1, axis=1)                           # [G, E]

    # capacity slots: rank tokens per expert; RTS randomizes which tokens win
    if train and use_rts and rng is not None:
        prio = jax.random.uniform(jax.random.fold_in(rng, 1), (G, N))
    else:
        prio = -jnp.arange(N, dtype=jnp.float32)[None, :]         # FIFO
    # sort tokens by priority within each expert: position = rank in arrival order
    # cumsum formulation (GShard): positions in expert queue, order = token order
    order = jnp.argsort(-prio, axis=1)                            # winners first
    mask1_sorted = jnp.take_along_axis(mask1, order[:, :, None], axis=1)
    pos_sorted = jnp.cumsum(mask1_sorted, axis=1) - mask1_sorted  # queue position
    inv = jnp.argsort(order, axis=1)
    positions = jnp.take_along_axis(pos_sorted, inv[:, :, None], axis=1)  # [G,N,E]
    locations1 = jnp.sum(positions * mask1, axis=-1)              # [G, N]

    if drop_tokens:
        keep = locations1 < capacity
        mask1 = mask1 * keep[..., None]

    gates1 = jnp.sum(gates * mask1, axis=-1)                      # [G, N]
    loc_oh = _one_hot(locations1.astype(jnp.int32), capacity)     # [G, N, C]
    combine = gates1[..., None, None] * mask1[..., None] * loc_oh[:, :, None, :]
    dispatch = combine > 0
    return aux, combine, dispatch, exp_counts


def top2gating(
    logits: jnp.ndarray,
    capacity: int,
    rng: Optional[jax.Array] = None,
    train: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-2 gating. Parity: ``sharded_moe.py:278-348`` — second expert chosen from
    the masked logits, both gate weights renormalized, capacity accounted jointly."""
    G, N, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    index1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(index1, E)
    logits_wo1 = jnp.where(mask1 > 0, -jnp.inf, logits)
    index2 = jnp.argmax(logits_wo1, axis=-1)
    mask2 = _one_hot(index2, E)

    aux = _load_balance_loss(gates, mask1)
    exp_counts = jnp.sum(mask1 + mask2, axis=1)

    # queue positions: expert queues fill with all first-choice tokens, then seconds
    loc1 = jnp.cumsum(mask1, axis=1) - mask1                      # [G, N, E]
    loc2 = jnp.cumsum(mask2, axis=1) - mask2 + jnp.sum(mask1, axis=1, keepdims=True)
    locations1 = jnp.sum(loc1 * mask1, axis=-1)                   # [G, N]
    locations2 = jnp.sum(loc2 * mask2, axis=-1)

    mask1 = mask1 * (locations1 < capacity)[..., None]
    mask2 = mask2 * (locations2 < capacity)[..., None]

    gates1 = jnp.sum(gates * mask1, axis=-1)
    gates2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.clip(gates1 + gates2, 1e-9, None)
    gates1, gates2 = gates1 / denom, gates2 / denom

    loc1_oh = _one_hot(locations1.astype(jnp.int32), capacity)
    loc2_oh = _one_hot(locations2.astype(jnp.int32), capacity)
    combine = (gates1[..., None, None] * mask1[..., None] * loc1_oh[:, :, None, :]
               + gates2[..., None, None] * mask2[..., None] * loc2_oh[:, :, None, :])
    dispatch = combine > 0
    return aux, combine, dispatch, exp_counts


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """Parity: ``TopKGate`` ctor args (``sharded_moe.py:351``)."""

    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None  # None | 'Jitter' | 'RSample'
    drop_tokens: bool = True
    use_rts: bool = True


def gate(
    cfg: GateConfig,
    gate_w: jnp.ndarray,
    x: jnp.ndarray,
    rng: Optional[jax.Array] = None,
    train: bool = True,
):
    """Route ``x`` [G, N, D] through a linear gate. Returns
    (aux_loss, combine [G,N,E,C], dispatch [G,N,E,C], exp_counts).

    Gate math in fp32 regardless of compute dtype (parity: ``TopKGate`` keeps the
    gate in fp32, ``sharded_moe.py:373-379``).
    """
    G, N, D = x.shape
    xg = x.astype(jnp.float32)
    if train and cfg.noisy_gate_policy == "Jitter" and rng is not None:
        eps = 1e-2
        xg = xg * jax.random.uniform(
            rng, xg.shape, jnp.float32, 1.0 - eps, 1.0 + eps)
    logits = xg @ gate_w.astype(jnp.float32)                      # [G, N, E]
    factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
    capacity = compute_capacity(N, cfg.num_experts, factor, cfg.min_capacity)
    if not cfg.drop_tokens:
        capacity = N  # every token fits (the reference pads capacity to max count)
    if cfg.k == 1:
        return top1gating(
            logits, capacity, rng=rng, noisy_gate_policy=cfg.noisy_gate_policy,
            drop_tokens=cfg.drop_tokens, use_rts=cfg.use_rts, train=train)
    if cfg.k == 2:
        return top2gating(logits, capacity, rng=rng, train=train)
    raise ValueError(f"k={cfg.k} not supported (reference supports top-1/top-2)")
