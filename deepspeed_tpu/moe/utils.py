"""MoE parameter bookkeeping.

Parity: ``deepspeed/moe/utils.py`` (``is_moe_param``, ``split_params_into_
different_moe_groups_for_optimizer``) — the reference tags expert parameters so
ZeRO partitions them over the *expert-data-parallel* group instead of the full DP
world. Here the analog is spec-level: expert leaves already carry ``P("ep", ...)``
on their expert axis, and these helpers let policies and optimizers treat
expert/non-expert subtrees differently by path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax


def is_moe_path(path: Tuple) -> bool:
    """True if a tree path addresses an expert-parallel leaf (any path component
    containing "expert"). Gate weights are NOT expert-parallel — they are dense
    params replicated over ep, matching the reference where only ``is_moe_param``
    tensors (``allreduce=False``) join the expert group."""
    for p in path:
        key = getattr(p, "key", getattr(p, "name", None))
        if key is not None and "expert" in str(key):
            return True
    return False


def split_moe_params(tree: Any) -> Tuple[Any, Any]:
    """Split a pytree into (dense, expert) subtrees (None where absent in each).
    Parity: ``split_params_into_different_moe_groups_for_optimizer``."""
    dense = jax.tree_util.tree_map_with_path(
        lambda path, x: None if is_moe_path(path) else x, tree)
    moe = jax.tree_util.tree_map_with_path(
        lambda path, x: x if is_moe_path(path) else None, tree)
    return dense, moe


def count_moe_params(tree: Any) -> Dict[str, int]:
    dense = moe = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        n = leaf.size
        if is_moe_path(path):
            moe += n
        else:
            dense += n
    return {"dense": int(dense), "expert": int(moe)}
