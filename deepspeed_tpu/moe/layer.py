"""The MoE layer facade.

Capability parity with the reference's ``deepspeed/moe/layer.py:15`` (``MoE``) and
``sharded_moe.py:419`` (``MOELayer``): top-k gated routing into a bank of expert
FFNs with capacity-factor dropping, an auxiliary load-balance loss, and expert
parallelism over a dedicated process dimension — plus PR-MoE's residual-expert
variant (``moe/layer.py:34``, ``use_residual``).

TPU-native dataflow (one jitted program, no explicit all-to-all calls):

    x [B,S,D]  --reshape-->  [G, N, D]      G groups ~ dp*ep ranks (gating local)
    gate: combine/dispatch [G, N, E, C]     fp32 gate math
    dispatch einsum -> [E, G*C, D]          sharding-constrained to P("ep",...)
                                            => XLA emits all-to-all over ICI
                                            (parity: _AllToAll, sharded_moe.py:89)
    expert FFN bank einsum                  each ep slice computes its E/ep experts
    combine einsum -> [G, N, D]             transpose all-to-all back
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models.api import maybe_shard
from .experts import apply_experts, expert_specs, init_experts
from .sharded_moe import GateConfig, gate

BATCH = ("dp", "ep")


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Parity: ``MoE.__init__`` kwargs (``moe/layer.py:15-46``)."""

    d_model: int
    d_ff: int
    num_experts: int
    ep_size: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    use_residual: bool = False  # PR-MoE: dense MLP in parallel, learned mix
    num_groups: int = 1  # gating groups (>= dp*ep extent for rank-local parity)

    def gate_config(self) -> GateConfig:
        return GateConfig(
            num_experts=self.num_experts, k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            drop_tokens=self.drop_tokens, use_rts=self.use_rts)


def init_moe(rng: jax.Array, cfg: MoEConfig, std: float = 0.02,
             res_std: Optional[float] = None) -> Dict[str, Any]:
    k = jax.random.split(rng, 3)
    params = {
        "gate_w": jax.random.normal(
            k[0], (cfg.d_model, cfg.num_experts), jnp.float32) * std,
        "experts": init_experts(
            k[1], cfg.num_experts, cfg.d_model, cfg.d_ff, std=std, res_std=res_std),
    }
    if cfg.use_residual:
        kk = jax.random.split(k[2], 2)
        params["residual_mlp"] = {
            "up_w": jax.random.normal(kk[0], (cfg.d_model, cfg.d_ff), jnp.float32) * std,
            "up_b": jnp.zeros((cfg.d_ff,)),
            "down_w": jax.random.normal(kk[1], (cfg.d_ff, cfg.d_model), jnp.float32)
            * (res_std if res_std is not None else std),
            "down_b": jnp.zeros((cfg.d_model,)),
        }
        params["coefficient"] = jnp.zeros((cfg.d_model, 2))
    return params


def moe_specs(cfg: MoEConfig) -> Dict[str, Any]:
    specs: Dict[str, Any] = {
        "gate_w": P(None, None),  # gate replicated (fp32, tiny)
        "experts": expert_specs(),
    }
    if cfg.use_residual:
        specs["residual_mlp"] = {
            "up_w": P(None, "tp"), "up_b": P("tp"),
            "down_w": P("tp", None), "down_b": P(None),
        }
        specs["coefficient"] = P(None, None)
    return specs


def apply_moe(
    cfg: MoEConfig,
    params: Dict[str, Any],
    x: jnp.ndarray,
    rng: Optional[jax.Array] = None,
    train: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run the MoE layer. ``x``: [B, S, D] (or [N, D]).

    Returns (y, aux_loss, exp_counts). Parity: ``MoELayer.forward``
    (``sharded_moe.py:491-560``) + residual mixing (``moe/layer.py:115-128``).
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    tokens = x.reshape(-1, D)
    N_total = tokens.shape[0]
    G = cfg.num_groups
    if N_total % G != 0:
        from ..utils.logging import warning_once

        warning_once(
            f"MoE: token count {N_total} not divisible by num_groups {G}; "
            f"falling back to global gating (num_groups=1) — capacity per group "
            f"changes from {N_total // max(G, 1)} to {N_total}")
        G = 1
    xg = tokens.reshape(G, N_total // G, D)

    aux, combine, dispatch, exp_counts = gate(
        cfg.gate_config(), params["gate_w"], xg, rng=rng, train=train)

    # dispatch: [G,N,E,C] x [G,N,D] -> [E, G, C, D], folded to [E, G*C, D]
    dispatched = jnp.einsum(
        "gnec,gnd->egcd", dispatch.astype(x.dtype), xg)
    E, _, C, _ = dispatched.shape
    dispatched = dispatched.reshape(E, G * C, D)
    # land the routed tokens on the expert-parallel axis: XLA inserts the
    # all-to-all here (and its transpose in backward). Under
    # zero_quantized_weights (the engine's fwd-wire knob, read from the
    # trace-time config binding) the routed tokens travel as block-int8/int4:
    # quantize, constrain the payload, dequantize on the expert side —
    # straight-through backward, so the combine-transpose a2a stays fp.
    from ..comm.quantized import active_quantization

    qc = active_quantization()
    if qc is not None and qc.weights:
        from ..comm.quantized import quantized_reshard

        dispatched = quantized_reshard(
            dispatched, P("ep", None, None), qc.bits, qc.block_size,
            "qall_to_all[moe_dispatch]")
    else:
        dispatched = maybe_shard(dispatched, P("ep", None, None))

    out = apply_experts(params["experts"], dispatched)
    out = out.reshape(E, G, C, D)

    y = jnp.einsum("gnec,egcd->gnd", combine.astype(x.dtype), out)
    y = y.reshape(orig_shape)
    y = maybe_shard(y, P(BATCH, *([None] * (len(orig_shape) - 2))))

    if cfg.use_residual:
        w = params["residual_mlp"]
        h = x @ w["up_w"].astype(x.dtype) + w["up_b"].astype(x.dtype)
        h = jax.nn.gelu(h, approximate=True)
        res = h @ w["down_w"].astype(x.dtype) + w["down_b"].astype(x.dtype)
        coef = jax.nn.softmax(
            (x @ params["coefficient"].astype(x.dtype)), axis=-1)
        y = y * coef[..., 0:1] + res * coef[..., 1:2]

    return y, aux, exp_counts


@dataclasses.dataclass(frozen=True)
class MoE:
    """User-facing carrier mirroring the reference's ``deepspeed.moe.layer.MoE``."""

    config: MoEConfig

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        return init_moe(rng, self.config)

    def specs(self) -> Dict[str, Any]:
        return moe_specs(self.config)

    def __call__(self, params, x, rng=None, train=True):
        return apply_moe(self.config, params, x, rng=rng, train=train)
