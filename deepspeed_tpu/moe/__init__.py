"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

Parity: ``deepspeed/moe/`` (layer.py, sharded_moe.py, experts.py, utils.py).
"""

from .experts import apply_experts, expert_specs, init_experts  # noqa: F401
from .layer import MoE, MoEConfig, apply_moe, init_moe, moe_specs  # noqa: F401
from .sharded_moe import (  # noqa: F401
    GateConfig,
    compute_capacity,
    gate,
    top1gating,
    top2gating,
)
from .utils import count_moe_params, is_moe_path, split_moe_params  # noqa: F401
