"""Expert FFN bank.

Parity: ``deepspeed/moe/experts.py:9`` (``Experts`` — a ModuleList of per-rank local
experts). TPU-native: the bank is ONE stacked pytree with a leading expert axis
``E``, sharded ``P("ep", ...)`` — each ep-mesh slice holds ``E/ep`` experts, the
exact analog of the reference's ``num_local_experts`` ModuleList, but a single
einsum applies all local experts at once on the MXU.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_experts(rng: jax.Array, num_experts: int, d_model: int, d_ff: int,
                 std: float = 0.02, res_std: float = None) -> Dict[str, Any]:
    """Per-expert FFN weights stacked on a leading E axis."""
    k = jax.random.split(rng, 2)
    res_std = res_std if res_std is not None else std
    return {
        "up_w": jax.random.normal(k[0], (num_experts, d_model, d_ff), jnp.float32) * std,
        "up_b": jnp.zeros((num_experts, d_ff)),
        "down_w": jax.random.normal(k[1], (num_experts, d_ff, d_model), jnp.float32) * res_std,
        "down_b": jnp.zeros((num_experts, d_model)),
    }


def expert_specs() -> Dict[str, P]:
    """Expert dim over ``ep``; hidden dim over ``tp`` (experts can themselves be
    tensor-parallel, like the reference's Megatron-MoE integration)."""
    return {
        "up_w": P("ep", None, "tp"),
        "up_b": P("ep", "tp"),
        "down_w": P("ep", "tp", None),
        "down_b": P("ep", None),
    }


def apply_experts(w: Dict[str, jnp.ndarray], x: jnp.ndarray) -> jnp.ndarray:
    """Apply each expert to its capacity slice. ``x``: [E, GC, D] -> [E, GC, D]."""
    h = (jnp.einsum("ecd,edf->ecf", x, w["up_w"].astype(x.dtype))
         + w["up_b"].astype(x.dtype)[:, None, :])
    h = jax.nn.gelu(h, approximate=True)
    return (jnp.einsum("ecf,efd->ecd", h, w["down_w"].astype(x.dtype))
            + w["down_b"].astype(x.dtype)[:, None, :])
