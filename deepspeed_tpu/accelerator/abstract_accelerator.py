"""Accelerator abstraction.

Capability parity with the reference's ``accelerator/abstract_accelerator.py:5``
(``DeepSpeedAccelerator`` ABC): a single seam through which every device touch goes,
so the runtime never imports a platform module directly. On TPU the operations map
to JAX device APIs instead of ``torch.cuda``; streams/events collapse into JAX's
async dispatch model (``block_until_ready``), so the stream API here is intentionally
minimal: it exists to keep call sites structured, not to schedule work (XLA does that).
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional


class Accelerator(abc.ABC):
    """Platform abstraction: device enumeration, memory stats, RNG, dtypes."""

    _name: str = "abstract"

    # ------------------------------------------------------------------ identity
    @property
    def name(self) -> str:
        return self._name

    @abc.abstractmethod
    def platform(self) -> str:
        """JAX platform string: 'tpu' | 'cpu' | 'gpu'."""

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    # ------------------------------------------------------------------ devices
    @abc.abstractmethod
    def devices(self) -> List[Any]:
        """All addressable devices for this process."""

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    @abc.abstractmethod
    def global_device_count(self) -> int:
        ...

    @abc.abstractmethod
    def process_index(self) -> int:
        ...

    @abc.abstractmethod
    def process_count(self) -> int:
        ...

    def current_device(self) -> Any:
        return self.devices()[0]

    def current_device_name(self) -> str:
        d = self.current_device()
        return f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"

    # ------------------------------------------------------------------ sync
    def synchronize(self, x: Optional[Any] = None) -> None:
        """Block until async dispatch has finished (CUDA stream-sync analog)."""
        import jax

        if x is not None:
            jax.block_until_ready(x)
        else:
            jax.effects_barrier()

    # ------------------------------------------------------------------ memory
    @abc.abstractmethod
    def memory_stats(self) -> dict:
        """Per-device memory statistics (bytes): {'bytes_in_use', 'bytes_limit', ...}."""

    def memory_allocated(self) -> int:
        return int(self.memory_stats().get("bytes_in_use", 0))

    def total_memory(self) -> int:
        return int(self.memory_stats().get("bytes_limit", 0))

    def available_memory(self) -> int:
        s = self.memory_stats()
        return int(s.get("bytes_limit", 0)) - int(s.get("bytes_in_use", 0))

    # ------------------------------------------------------------------ dtypes
    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    def supported_dtypes(self) -> list:
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    def communication_backend_name(self) -> str:
        return "xla"

    # ------------------------------------------------------------------ rng
    def default_rng(self, seed: int):
        import jax

        return jax.random.PRNGKey(seed)
