from .abstract_accelerator import Accelerator
from .real_accelerator import (
    CPUAccelerator,
    TPUAccelerator,
    get_accelerator,
    set_accelerator,
)

__all__ = [
    "Accelerator",
    "TPUAccelerator",
    "CPUAccelerator",
    "get_accelerator",
    "set_accelerator",
]
