"""Accelerator discovery & selection.

Parity with the reference's ``accelerator/real_accelerator.py:37,55``
(``get_accelerator()`` / ``set_accelerator()``): a process-global accelerator object
picked automatically (TPU if present, else CPU) or forced via the
``DS_TPU_ACCELERATOR`` environment variable (values: ``tpu`` | ``cpu``).
"""

from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import Accelerator

_accelerator: Optional[Accelerator] = None


class _JaxAccelerator(Accelerator):
    """Concrete accelerator backed by the active JAX backend."""

    def __init__(self, platform: str):
        self._platform = platform
        self._name = platform

    def platform(self) -> str:
        return self._platform

    def is_available(self) -> bool:
        import jax

        try:
            return len(jax.devices(self._platform)) > 0
        except RuntimeError:
            return False

    def devices(self):
        import jax

        return jax.local_devices()

    def device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def global_device_count(self) -> int:
        import jax

        return jax.device_count()

    def process_index(self) -> int:
        import jax

        return jax.process_index()

    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def memory_stats(self) -> dict:
        d = self.current_device()
        try:
            return dict(d.memory_stats() or {})
        except Exception:
            return {}

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self._platform != "cpu" else jnp.float32


class TPUAccelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("tpu")


class CPUAccelerator(_JaxAccelerator):
    def __init__(self):
        super().__init__("cpu")


def _detect() -> Accelerator:
    forced = os.environ.get("DS_TPU_ACCELERATOR", "").lower()
    if forced == "cpu":
        return CPUAccelerator()
    if forced == "tpu":
        return TPUAccelerator()
    import jax

    platform = jax.default_backend()
    if platform == "cpu":
        return CPUAccelerator()
    # tpu or any other accelerator backend (e.g. experimental tunnels) — treat as TPU-class.
    acc = _JaxAccelerator(platform)
    return acc


def get_accelerator() -> Accelerator:
    global _accelerator
    if _accelerator is None:
        _accelerator = _detect()
    return _accelerator


def set_accelerator(acc: Accelerator) -> None:
    global _accelerator
    _accelerator = acc
