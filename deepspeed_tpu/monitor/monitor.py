"""Metrics monitor fan-out.

Parity: reference ``monitor/monitor.py:24`` (``MonitorMaster``) with TensorBoard
(``monitor/tensorboard.py:8``), WandB (``monitor/wandb.py:8``) and CSV
(``monitor/csv_monitor.py``) backends, plus a custom callback backend.
Events are ``(name, value, step)`` tuples, written only from process 0 — same
rank-filtering the reference does. The wandb package is imported lazily; its
absence disables that backend with a warning instead of failing the job.
"""

from __future__ import annotations

import csv
import os
from typing import Callable, List, Optional, Sequence, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class TensorBoardMonitor:
    def __init__(self, output_path: str, job_name: str = "DeepSpeedJobName"):
        try:
            from tensorboardX import SummaryWriter
        except ImportError:  # torch ships its own writer in this image
            from torch.utils.tensorboard import SummaryWriter

        path = os.path.join(output_path or "runs", job_name)
        os.makedirs(path, exist_ok=True)
        self.writer = SummaryWriter(path)

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class CSVMonitor:
    def __init__(self, output_path: str, job_name: str = "DeepSpeedJobName"):
        self.dir = os.path.join(output_path or "csv_out", job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class WandbMonitor:
    """Parity: the reference's ``WandbMonitor`` (``monitor/wandb.py:8``)."""

    def __init__(self, team: Optional[str] = None, group: Optional[str] = None,
                 project: str = "deepspeed"):
        import wandb  # lazy: not baked into every image

        self.wandb = wandb
        wandb.init(entity=team, group=group, project=project)

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            self.wandb.log({name: value}, step=step)


class CallbackMonitor:
    def __init__(self, fn: Callable[[Sequence[Event]], None]):
        self.fn = fn

    def write_events(self, events: Sequence[Event]) -> None:
        self.fn(events)


class JSONLMonitor:
    """Append-only JSONL backend: one ``{"name", "value", "step", "unix_time"}``
    object per line. TPU-native addition for the resilience layer: unlike the
    CSV/TB writers it is crash-tolerant by construction (a torn final line is
    skipped by readers) and trivially mergeable across process generations —
    the recovery-event trail (``Resilience/*``/``Serving/*`` events) survives
    any number of preemptions and restarts. The file rotates by size
    (``max_bytes``/``keep``, shared :func:`rotate_jsonl` machinery with the
    recovery-event sink) so week-long serving runs cannot grow host disk
    without bound."""

    def __init__(self, output_path: str, job_name: str = "DeepSpeedJobName",
                 max_bytes: Optional[int] = None, keep: int = 3):
        import time as _time

        from ..resilience.events import DEFAULT_ROTATE_BYTES

        self._time = _time
        d = os.path.join(output_path or "jsonl_out", job_name)
        os.makedirs(d, exist_ok=True)
        self.path = os.path.join(d, "events.jsonl")
        self.max_bytes = (DEFAULT_ROTATE_BYTES if max_bytes is None
                          else int(max_bytes))
        self.keep = int(keep)

    def write_events(self, events: Sequence[Event]) -> None:
        import json

        from ..resilience.events import rotate_jsonl

        rotate_jsonl(self.path, self.max_bytes, self.keep)
        with open(self.path, "a") as f:
            for name, value, step in events:
                f.write(json.dumps(
                    {"name": name, "value": float(value), "step": int(step),
                     "unix_time": self._time.time()}) + "\n")


class _SafeBackend:
    """Degraded-mode wrapper: a backend whose sink fails (full disk, sick
    remote FS, wandb outage) buffers events in memory instead of killing the
    training step, and re-flushes the buffer — in order — once the sink
    recovers. The buffer is bounded (oldest events drop first); entering and
    leaving degraded mode each log once. Part of the resilience layer's
    graceful-degradation contract (``docs/RESILIENCE.md`` "In-run health")."""

    def __init__(self, backend, buffer_limit: int = 4096):
        self.backend = backend
        self.buffer_limit = int(buffer_limit)
        self._buffer: List[Event] = []
        self.degraded = False
        self.dropped = 0

    @property
    def name(self) -> str:
        return type(self.backend).__name__

    def write_events(self, events: Sequence[Event]) -> None:
        pending = self._buffer + list(events)
        try:
            self.backend.write_events(pending)
        except Exception as e:
            if len(pending) > self.buffer_limit:
                self.dropped += len(pending) - self.buffer_limit
                pending = pending[-self.buffer_limit:]
            self._buffer = pending
            if not self.degraded:
                self.degraded = True
                logger.warning(
                    f"monitor backend {self.name} failed ({e}); degrading to "
                    f"in-memory buffering (limit {self.buffer_limit} events) "
                    f"— training continues")
            return
        if self.degraded:
            logger.warning(
                f"monitor backend {self.name} recovered; "
                f"{len(self._buffer)} buffered events flushed"
                + (f", {self.dropped} dropped" if self.dropped else ""))
            self.degraded = False
        self._buffer = []


class MonitorMaster:
    """Fan-out to every enabled backend; only process 0 writes. Each backend
    rides a :class:`_SafeBackend`: a failing sink buffers in memory and
    never fails the training step."""

    def __init__(self, monitor_config, extra_backends: Optional[List] = None):
        self.backends: List = [_SafeBackend(b) for b in (extra_backends or [])]
        self.enabled = jax.process_index() == 0
        if not self.enabled:
            return
        tb = monitor_config.tensorboard
        if tb.enabled:
            try:
                self.backends.append(
                    _SafeBackend(TensorBoardMonitor(tb.output_path, tb.job_name)))
            except Exception as e:  # tensorboardX missing/broken shouldn't kill training
                logger.warning(f"tensorboard monitor disabled: {e}")
        wb = getattr(monitor_config, "wandb", None)
        if wb is not None and wb.enabled:
            try:
                self.backends.append(
                    _SafeBackend(WandbMonitor(wb.team, wb.group, wb.project)))
            except Exception as e:  # wandb not installed / offline init failure
                logger.warning(f"wandb monitor disabled: {e}")
        cs = monitor_config.csv_monitor
        if cs.enabled:
            self.backends.append(
                _SafeBackend(CSVMonitor(cs.output_path, cs.job_name)))
        jl = getattr(monitor_config, "jsonl", None)
        if jl is not None and jl.enabled:
            rotate_mb = float(getattr(jl, "rotate_mb", 0.0) or 0.0)
            self.backends.append(_SafeBackend(JSONLMonitor(
                jl.output_path, jl.job_name,
                max_bytes=int(rotate_mb * 2**20) if rotate_mb > 0 else None,
                keep=int(getattr(jl, "rotate_keep", 3)))))

    @property
    def degraded(self) -> bool:
        """Whether any backend is currently buffering in degraded mode."""
        return any(b.degraded for b in self.backends)

    def write_events(self, events: Sequence[Event]) -> None:
        if not self.enabled:
            return
        for b in self.backends:
            b.write_events(events)
