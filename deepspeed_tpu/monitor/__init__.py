from .monitor import CallbackMonitor, CSVMonitor, MonitorMaster, TensorBoardMonitor

__all__ = ["MonitorMaster", "TensorBoardMonitor", "CSVMonitor", "CallbackMonitor"]
