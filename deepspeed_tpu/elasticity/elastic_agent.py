"""Elastic agent: failure detection + resize-and-resume supervision.

Capability parity with the reference's ``DSElasticAgent``
(``elasticity/elastic_agent.py:23``, subclassing torchelastic's
``LocalElasticAgent``): monitor the training workers, and on worker failure or
cluster membership change restart the worker group at the new world size with a
batch decomposition that keeps the effective batch constant.

TPU-native shape: there is no per-GPU process group to re-rendezvous — a
training job is ONE controller process over a device mesh, so the agent is a
supervisor that

1. resolves the elastic batch triangle for the current world size via
   :func:`~deepspeed_tpu.elasticity.compute_elastic_config` (the same math the
   reference's v0.1/0.2 elasticity uses);
2. launches the worker process (``make_cmd(world, micro, gas)``) and watches it
   (exit code + optional device-membership polling);
3. on a non-zero exit or a membership change, kills the worker, re-resolves the
   triangle at the new world size, and relaunches — the worker resumes from the
   latest universal checkpoint (topology-free format: any dp/tp regrid reloads,
   ``deepspeed_tpu/checkpoint/serialization.py``), which replaces torchelastic's
   rendezvous-and-rebroadcast recovery path.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..resilience import RecoveryLog, quarantine_tag, read_latest
from ..resilience.preemption import PREEMPTED_EXIT_CODE
from ..resilience.retry import backoff_delay
from ..utils.logging import logger
from .elasticity import (ELASTICITY_CONFIG_ENV, ElasticityError,
                         compute_elastic_config, validate_elasticity_block)


def probe_device_count(timeout: float = 120.0) -> int:
    """Device count probed OUT of process: the supervisor must never acquire
    the accelerator itself (libtpu grants exclusive per-process access — an
    in-process ``jax.device_count()`` would lock the chips away from the very
    worker this agent launches)."""
    forced = __import__("os").environ.get("DS_ELASTIC_WORLD")
    if forced:
        return int(forced)
    p = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.device_count())"],
        capture_output=True, text=True, timeout=timeout)
    if p.returncode != 0:
        raise RuntimeError(f"device probe failed: {p.stderr[-300:]}")
    return int(p.stdout.strip().splitlines()[-1])


@dataclasses.dataclass
class WorkerSpec:
    """One launch decision: the resolved decomposition for a world size."""

    world_size: int
    micro_batch: int
    gas: int
    global_batch: int


@dataclasses.dataclass
class AgentResult:
    state: str  # "SUCCEEDED" | "FAILED"
    restarts: int
    history: List[WorkerSpec]
    preemptions: int = 0            # graceful drain exits survived
    quarantined: List[str] = dataclasses.field(default_factory=list)
    membership_changes: int = 0     # budget-free resize relaunches


class DSElasticAgent:
    """Supervise an elastic training worker. Parity: ``DSElasticAgent``
    (``elasticity/elastic_agent.py:23``) — monitor/restart semantics of
    ``_invoke_run``; rendezvous is replaced by checkpoint-resume.

    Args:
      make_cmd: ``(spec: WorkerSpec) -> argv`` building the worker command; the
        worker must resume from its checkpoint dir on start.
      ds_config: dict with the ``elasticity`` block (and anything the caller's
        ``make_cmd`` needs).
      device_count_fn: current usable world size (chips/hosts). Defaults to
        :func:`probe_device_count` (out-of-process, cached per poll). A change
        triggers restart-at-new-size.
      max_restarts: give up after this many failures (parity: torchelastic
        ``max_restarts``). Graceful preemption exits
        (:data:`~deepspeed_tpu.resilience.preemption.PREEMPTED_EXIT_CODE`)
        do NOT consume restart budget — the worker checkpointed and left on
        purpose; it is relaunched immediately without backoff. Membership
        changes are equally budget-free (docs/RESILIENCE.md "Elastic
        membership"): a worker dying together with a device-count change (a
        lost host kills its worker) relaunches at the re-resolved world size
        with no restart counted and a ``membership_change`` recovery event;
        only same-world crashes spend budget and back off.
      poll_interval: seconds between membership checks while the worker runs.
      checkpoint_dir: the worker's checkpoint directory. When set, the agent
        (a) applies exponential restart backoff, (b) detects crash loops —
        ``crash_loop_threshold`` consecutive failures while ``latest`` points
        at the same tag quarantine that tag
        (:func:`~deepspeed_tpu.resilience.quarantine_tag`: the next resume
        falls back to the previous committed tag instead of dying on the
        poisoned one forever), and (c) appends recovery events to
        ``<checkpoint_dir>/recovery_events.jsonl``.
      crash_loop_threshold: K consecutive failures on one tag before it is
        quarantined.
      backoff_base / backoff_max: restart delay ``min(max, base * 2**(n-1))``
      with decorrelating jitter; reset on any successful-looking transition
        (preemption, membership change, new tag).
    """

    def __init__(self, make_cmd: Callable[[WorkerSpec], Sequence[str]],
                 ds_config: dict,
                 device_count_fn: Optional[Callable[[], int]] = None,
                 max_restarts: int = 10, poll_interval: float = 1.0,
                 checkpoint_dir: Optional[str] = None,
                 crash_loop_threshold: int = 3,
                 backoff_base: float = 1.0, backoff_max: float = 60.0,
                 preempted_exit_code: int = PREEMPTED_EXIT_CODE):
        self.make_cmd = make_cmd
        self.ds_config = ds_config
        # config may be a dict or an object with .elasticity (the pydantic
        # DeepSpeedConfig) — normalize once for the fingerprint export
        self._elastic_block = dict(
            ds_config.get("elasticity", {}) if isinstance(ds_config, dict)
            else getattr(ds_config, "elasticity", None) or {})
        if self._elastic_block.get("enabled"):
            # fail at construction, not at the first resize: this is the same
            # validation runtime/config.py applies to the worker's copy
            self._elastic_block = validate_elasticity_block(
                self._elastic_block, warn=logger.warning)
        self.device_count_fn = device_count_fn or probe_device_count
        self.max_restarts = int(max_restarts)
        self.poll_interval = float(poll_interval)
        self.checkpoint_dir = checkpoint_dir
        self.crash_loop_threshold = int(crash_loop_threshold)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        # must match the worker's resilience.exit_code when that knob is
        # customized — otherwise graceful drains are counted as crashes
        code = int(preempted_exit_code)
        if code == PREEMPTED_EXIT_CODE:  # not overridden: read the config
            res_block = (ds_config.get("resilience", {})
                         if isinstance(ds_config, dict)
                         else getattr(ds_config, "resilience", None))
            if isinstance(res_block, dict):
                code = int(res_block.get("exit_code", PREEMPTED_EXIT_CODE))
            elif res_block is not None:
                code = int(getattr(res_block, "exit_code", PREEMPTED_EXIT_CODE))
        self.preempted_exit_code = code
        self._events = (RecoveryLog.for_dir(checkpoint_dir, role="agent")
                        if checkpoint_dir else RecoveryLog(role="agent"))

    # ------------------------------------------------------------- resolution
    def resolve(self, world_size: int) -> WorkerSpec:
        """Largest valid world size <= ``world_size``, and its decomposition
        keeping the elastic global batch fixed."""
        final_bs, valid, _ = compute_elastic_config(self.ds_config, 0)
        usable = [w for w in valid if w <= world_size]
        if not usable:
            raise ElasticityError(
                f"no valid elastic world size <= {world_size} (valid: {valid})")
        w = max(usable)
        _, _, micro = compute_elastic_config(self.ds_config, w)
        gas = final_bs // (micro * w)
        return WorkerSpec(world_size=w, micro_batch=micro, gas=gas,
                          global_batch=final_bs)

    # ------------------------------------------------------------- supervision
    def _latest_tag(self) -> Optional[str]:
        return read_latest(self.checkpoint_dir) if self.checkpoint_dir else None

    def _backoff(self, consecutive_failures: int) -> float:
        return backoff_delay(consecutive_failures,
                             self.backoff_base, self.backoff_max)

    def run(self) -> AgentResult:
        restarts = 0
        preemptions = 0
        membership_changes = 0
        quarantined: List[str] = []
        history: List[WorkerSpec] = []
        consecutive_failures = 0    # resets on preemption/membership change
        same_tag_failures = 0
        last_failed_tag: Optional[str] = None
        prev_spec: Optional[WorkerSpec] = None
        # the (world, spec) a post-death probe already resolved: carried into
        # the next launch so ONE probe drives both the budget decision and
        # the membership event/relaunch — two independent probes around an
        # unstable dying runtime could classify the death one way and
        # relaunch another
        pending: Optional[Tuple[int, WorkerSpec]] = None
        while True:
            # re-probe device count before EVERY launch: the world this
            # worker group is resolved for is the world that exists NOW, not
            # the one the agent started with
            if pending is not None:
                world, spec = pending
                pending = None
            else:
                world = self.device_count_fn()
                spec = self.resolve(world)
            if prev_spec is not None and spec.world_size != prev_spec.world_size:
                # membership change: budget-free like a drained preemption —
                # losing a device is the cluster's fault, not the worker's
                membership_changes += 1
                consecutive_failures = 0
                same_tag_failures = 0
                last_failed_tag = None
                self._events.record(
                    "membership_change", value=membership_changes,
                    old_world=prev_spec.world_size,
                    new_world=spec.world_size,
                    tag=self._latest_tag() or "")
                logger.warning(
                    f"elastic agent: membership change "
                    f"{prev_spec.world_size} -> {spec.world_size}; "
                    f"relaunching at the new decomposition (budget-free, "
                    f"{membership_changes} change(s) absorbed)")
            prev_spec = spec
            history.append(spec)
            resume_tag = self._latest_tag()
            argv = list(self.make_cmd(spec))
            logger.info(
                f"elastic agent: launching worker (attempt "
                f"{restarts + preemptions + membership_changes + 1}): "
                f"world={spec.world_size} "
                f"micro={spec.micro_batch} gas={spec.gas} "
                f"global_batch={spec.global_batch}"
                + (f" resume_tag={resume_tag}" if resume_tag else ""))
            # export the fingerprint the worker's runtime must match
            # (ensure_immutable_elastic_config, elasticity.py) — the agent IS
            # the resource scheduler here
            env = dict(os.environ)
            env[ELASTICITY_CONFIG_ENV] = json.dumps(
                {"elasticity": self._elastic_block})
            proc = subprocess.Popen(argv, env=env)
            rc = self._watch(proc, launched_world=world)
            if rc == 0:
                logger.info("elastic agent: worker SUCCEEDED")
                return AgentResult("SUCCEEDED", restarts, history,
                                   preemptions=preemptions,
                                   quarantined=quarantined,
                                   membership_changes=membership_changes)
            if rc == self.preempted_exit_code:
                # graceful drain: the worker committed an emergency checkpoint
                # and left — relaunch immediately, spend no restart budget
                preemptions += 1
                consecutive_failures = 0
                self._events.record("preemption_restart",
                                    value=preemptions, tag=resume_tag or "")
                logger.warning(
                    f"elastic agent: worker preempted (rc={rc}, drained "
                    f"cleanly); relaunching from its emergency checkpoint "
                    f"({preemptions} preemption(s) survived)")
                continue
            post = self._probe_after_death()
            if rc is None or (post is not None
                              and post[1].world_size != spec.world_size):
                # the worker died WITH a membership change (a lost device
                # kills its worker): budget-free — the SAME probe that made
                # this call is carried to the loop top, which records the
                # membership_change event and launches at its decomposition
                pending = post
                logger.warning(
                    f"elastic agent: worker exited rc={rc} with a membership "
                    "change pending; re-resolving the world size "
                    "(budget-free restart)")
                continue
            restarts += 1
            consecutive_failures += 1
            if restarts > self.max_restarts:
                logger.error(
                    f"elastic agent: giving up after {restarts - 1} restarts")
                return AgentResult("FAILED", restarts - 1, history,
                                   preemptions=preemptions,
                                   quarantined=quarantined,
                                   membership_changes=membership_changes)
            self._events.record("worker_restart", value=restarts,
                                rc=rc, tag=resume_tag or "")
            # crash-loop detection: K consecutive crashes while 'latest'
            # still points at the same tag → the tag is poisoned (loads but
            # kills the worker); quarantine it so the next resume falls back
            # to the previous committed tag
            failed_tag = self._latest_tag()
            if failed_tag is not None:
                if failed_tag == last_failed_tag:
                    same_tag_failures += 1
                else:
                    # latest moved since the previous failure: the worker made
                    # real progress, so this is not an escalating crash loop
                    consecutive_failures = 1
                    same_tag_failures = 1
                    last_failed_tag = failed_tag
                if same_tag_failures >= self.crash_loop_threshold:
                    new_latest = quarantine_tag(
                        self.checkpoint_dir, failed_tag,
                        f"crash loop: {same_tag_failures} consecutive worker "
                        f"failures (last rc={rc}) resuming this tag")
                    quarantined.append(failed_tag)
                    self._events.record("tag_quarantined", tag=failed_tag,
                                        new_latest=new_latest or "")
                    same_tag_failures = 0
                    last_failed_tag = None
            delay = self._backoff(consecutive_failures)
            logger.warning(
                f"elastic agent: worker exited rc={rc}; restarting in "
                f"{delay:.1f}s ({restarts}/{self.max_restarts}) from the "
                f"latest committed checkpoint")
            time.sleep(delay)

    def _probe_after_death(self) -> Optional[Tuple[int, WorkerSpec]]:
        """ONE device probe after a worker death, resolved against the
        ladder. Its spec decides whether the death was a membership change
        (budget-free) AND — carried to the loop top — what to launch next,
        so a probe flapping between the two decisions cannot classify the
        death one way and relaunch another. ``None`` when the probe or
        resolution fails: not a membership change the agent can act on, so
        the exit counts as a plain crash (backoff + budget)."""
        try:
            world = self.device_count_fn()
            return world, self.resolve(world)
        except (ElasticityError, RuntimeError, OSError) as e:
            logger.warning(f"elastic agent: post-crash device probe failed "
                           f"({e}); counting the exit as a crash")
            return None

    def _watch(self, proc: subprocess.Popen,
               launched_world: int) -> Optional[int]:
        """Wait on the worker, polling membership against the world size the
        launch was RESOLVED for (a change in the launch window is caught on the
        first poll); a change kills + restarts (``None`` re-resolves — a
        synthetic int would collide with real signal exits, ``poll()`` returns
        ``-signum``)."""
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            time.sleep(self.poll_interval)
            now = self.device_count_fn()
            if now != launched_world:
                logger.warning(
                    f"elastic agent: membership change {launched_world} -> {now}; "
                    "restarting worker group")
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``ds_elastic`` CLI (parity: ``bin/ds_elastic``): supervise
    ``python <script> ...`` with `--world/--micro/--gas` appended per launch."""
    import argparse
    import json

    p = argparse.ArgumentParser("ds_elastic")
    p.add_argument("--config", required=True, help="DeepSpeed JSON with an elasticity block")
    p.add_argument("--max-restarts", type=int, default=10)
    p.add_argument("--checkpoint-dir", default=None,
                   help="worker checkpoint dir: enables crash-loop tag "
                        "quarantine + recovery-event logging")
    p.add_argument("--crash-loop-threshold", type=int, default=3)
    p.add_argument("script", help="worker script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)

    def make_cmd(spec: WorkerSpec):
        return [sys.executable, args.script, *args.script_args,
                "--elastic-world", str(spec.world_size),
                "--elastic-micro", str(spec.micro_batch),
                "--elastic-gas", str(spec.gas)]

    agent = DSElasticAgent(make_cmd, ds_config,
                           device_count_fn=probe_device_count,
                           max_restarts=args.max_restarts,
                           poll_interval=30.0,
                           checkpoint_dir=args.checkpoint_dir,
                           crash_loop_threshold=args.crash_loop_threshold)
    result = agent.run()
    return 0 if result.state == "SUCCEEDED" else 1


if __name__ == "__main__":
    sys.exit(main())
