"""Elastic agent: failure detection + resize-and-resume supervision.

Capability parity with the reference's ``DSElasticAgent``
(``elasticity/elastic_agent.py:23``, subclassing torchelastic's
``LocalElasticAgent``): monitor the training workers, and on worker failure or
cluster membership change restart the worker group at the new world size with a
batch decomposition that keeps the effective batch constant.

TPU-native shape: there is no per-GPU process group to re-rendezvous — a
training job is ONE controller process over a device mesh, so the agent is a
supervisor that

1. resolves the elastic batch triangle for the current world size via
   :func:`~deepspeed_tpu.elasticity.compute_elastic_config` (the same math the
   reference's v0.1/0.2 elasticity uses);
2. launches the worker process (``make_cmd(world, micro, gas)``) and watches it
   (exit code + optional device-membership polling);
3. on a non-zero exit or a membership change, kills the worker, re-resolves the
   triangle at the new world size, and relaunches — the worker resumes from the
   latest universal checkpoint (topology-free format: any dp/tp regrid reloads,
   ``deepspeed_tpu/checkpoint/serialization.py``), which replaces torchelastic's
   rendezvous-and-rebroadcast recovery path.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

from ..utils.logging import logger
from .elasticity import (ELASTICITY_CONFIG_ENV, ElasticityError,
                         compute_elastic_config)


def probe_device_count(timeout: float = 120.0) -> int:
    """Device count probed OUT of process: the supervisor must never acquire
    the accelerator itself (libtpu grants exclusive per-process access — an
    in-process ``jax.device_count()`` would lock the chips away from the very
    worker this agent launches)."""
    forced = __import__("os").environ.get("DS_ELASTIC_WORLD")
    if forced:
        return int(forced)
    p = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.device_count())"],
        capture_output=True, text=True, timeout=timeout)
    if p.returncode != 0:
        raise RuntimeError(f"device probe failed: {p.stderr[-300:]}")
    return int(p.stdout.strip().splitlines()[-1])


@dataclasses.dataclass
class WorkerSpec:
    """One launch decision: the resolved decomposition for a world size."""

    world_size: int
    micro_batch: int
    gas: int
    global_batch: int


@dataclasses.dataclass
class AgentResult:
    state: str  # "SUCCEEDED" | "FAILED"
    restarts: int
    history: List[WorkerSpec]


class DSElasticAgent:
    """Supervise an elastic training worker. Parity: ``DSElasticAgent``
    (``elasticity/elastic_agent.py:23``) — monitor/restart semantics of
    ``_invoke_run``; rendezvous is replaced by checkpoint-resume.

    Args:
      make_cmd: ``(spec: WorkerSpec) -> argv`` building the worker command; the
        worker must resume from its checkpoint dir on start.
      ds_config: dict with the ``elasticity`` block (and anything the caller's
        ``make_cmd`` needs).
      device_count_fn: current usable world size (chips/hosts). Defaults to
        :func:`probe_device_count` (out-of-process, cached per poll). A change
        triggers restart-at-new-size.
      max_restarts: give up after this many failures (parity: torchelastic
        ``max_restarts``).
      poll_interval: seconds between membership checks while the worker runs.
    """

    def __init__(self, make_cmd: Callable[[WorkerSpec], Sequence[str]],
                 ds_config: dict,
                 device_count_fn: Optional[Callable[[], int]] = None,
                 max_restarts: int = 10, poll_interval: float = 1.0):
        self.make_cmd = make_cmd
        self.ds_config = ds_config
        # config may be a dict or an object with .elasticity (the pydantic
        # DeepSpeedConfig) — normalize once for the fingerprint export
        self._elastic_block = dict(
            ds_config.get("elasticity", {}) if isinstance(ds_config, dict)
            else getattr(ds_config, "elasticity", None) or {})
        self.device_count_fn = device_count_fn or probe_device_count
        self.max_restarts = int(max_restarts)
        self.poll_interval = float(poll_interval)

    # ------------------------------------------------------------- resolution
    def resolve(self, world_size: int) -> WorkerSpec:
        """Largest valid world size <= ``world_size``, and its decomposition
        keeping the elastic global batch fixed."""
        final_bs, valid, _ = compute_elastic_config(self.ds_config, 0)
        usable = [w for w in valid if w <= world_size]
        if not usable:
            raise ElasticityError(
                f"no valid elastic world size <= {world_size} (valid: {valid})")
        w = max(usable)
        _, _, micro = compute_elastic_config(self.ds_config, w)
        gas = final_bs // (micro * w)
        return WorkerSpec(world_size=w, micro_batch=micro, gas=gas,
                          global_batch=final_bs)

    # ------------------------------------------------------------- supervision
    def run(self) -> AgentResult:
        restarts = 0
        history: List[WorkerSpec] = []
        while True:
            world = self.device_count_fn()
            spec = self.resolve(world)
            history.append(spec)
            argv = list(self.make_cmd(spec))
            logger.info(
                f"elastic agent: launching worker (attempt {restarts + 1}): "
                f"world={spec.world_size} micro={spec.micro_batch} "
                f"gas={spec.gas} global_batch={spec.global_batch}")
            # export the fingerprint the worker's runtime must match
            # (ensure_immutable_elastic_config, elasticity.py) — the agent IS
            # the resource scheduler here
            env = dict(os.environ)
            env[ELASTICITY_CONFIG_ENV] = json.dumps(
                {"elasticity": self._elastic_block})
            proc = subprocess.Popen(argv, env=env)
            rc = self._watch(proc, launched_world=world)
            if rc == 0:
                logger.info("elastic agent: worker SUCCEEDED")
                return AgentResult("SUCCEEDED", restarts, history)
            restarts += 1
            if restarts > self.max_restarts:
                logger.error(
                    f"elastic agent: giving up after {restarts - 1} restarts")
                return AgentResult("FAILED", restarts - 1, history)
            logger.warning(
                f"elastic agent: worker exited rc={rc}; restarting "
                f"({restarts}/{self.max_restarts}) from the latest checkpoint")

    def _watch(self, proc: subprocess.Popen, launched_world: int) -> int:
        """Wait on the worker, polling membership against the world size the
        launch was RESOLVED for (a change in the launch window is caught on the
        first poll); a change kills + restarts (synthetic rc -1 re-resolves)."""
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            time.sleep(self.poll_interval)
            now = self.device_count_fn()
            if now != launched_world:
                logger.warning(
                    f"elastic agent: membership change {launched_world} -> {now}; "
                    "restarting worker group")
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                return -1


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``ds_elastic`` CLI (parity: ``bin/ds_elastic``): supervise
    ``python <script> ...`` with `--world/--micro/--gas` appended per launch."""
    import argparse
    import json

    p = argparse.ArgumentParser("ds_elastic")
    p.add_argument("--config", required=True, help="DeepSpeed JSON with an elasticity block")
    p.add_argument("--max-restarts", type=int, default=10)
    p.add_argument("script", help="worker script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)

    def make_cmd(spec: WorkerSpec):
        return [sys.executable, args.script, *args.script_args,
                "--elastic-world", str(spec.world_size),
                "--elastic-micro", str(spec.micro_batch),
                "--elastic-gas", str(spec.gas)]

    agent = DSElasticAgent(make_cmd, ds_config,
                           device_count_fn=probe_device_count,
                           max_restarts=args.max_restarts,
                           poll_interval=30.0)
    result = agent.run()
    return 0 if result.state == "SUCCEEDED" else 1


if __name__ == "__main__":
    sys.exit(main())
