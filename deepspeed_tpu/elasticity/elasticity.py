"""Elastic training batch math.

Capability parity with the reference's elasticity v0.1/0.2
(``elasticity/elasticity.py:125,173,287``): given an acceptable-batch-size
ceiling and a set of micro-batch sizes, find a global batch size that remains
valid (batch = micro x gas x world) across a whole RANGE of world sizes, so a
preempted/resized job resumes without changing the effective batch.

The algorithm is the reference's: candidate global batch sizes are each
micro-batch scaled by powers of two up to the ceiling; a world size is valid for
a candidate if the candidate divides by (micro x world) for some micro; the
chosen candidate maximizes the number of valid world sizes, tie-broken by the
preference for larger batch.

Pure host math, ported off the torch-era GPU fingerprinting: "world" is a
device count probed from the runtime (chips or hosts — the elastic agent's
``probe_device_count``), never a GPU model sniff, and the block's canonical
range keys are ``min_world_size``/``max_world_size`` (the reference's
``min_gpus``/``max_gpus`` stay accepted as aliases so imported configs keep
working). :func:`validate_elasticity_block` is the ONE validation both the
runtime config (``runtime/config.py``) and the agent resolve through;
:func:`elastic_ladder` enumerates the resulting valid
``(world, micro, gas)`` decompositions.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"

# the resource scheduler exports the elastic config it scaled the job by;
# runtime must refuse to train with a different one (the reference's
# DEEPSPEED_ELASTICITY_CONFIG, elasticity/elasticity.py:254). The reference
# spelling is accepted too so imported launch scripts keep working.
ELASTICITY_CONFIG_ENV = "DS_TPU_ELASTICITY_CONFIG"
_ELASTICITY_CONFIG_ENV_COMPAT = "DEEPSPEED_ELASTICITY_CONFIG"


class ElasticityError(Exception):
    """Parity: ``elasticity/elasticity.py`` error types (collapsed)."""


def elasticity_enabled(ds_config: Dict[str, Any]) -> bool:
    """Parity: ``elasticity.py:248``."""
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


# the block's schema: canonical TPU-native keys plus the reference's spellings
# (accepted as aliases); anything else is a typo that would silently change
# the resize plan — rejected, not ignored
_KNOWN_KEYS = {
    "enabled", "max_train_batch_size", "micro_batch_sizes",
    "min_world_size", "max_world_size",          # canonical (world = devices)
    "min_gpus", "max_gpus",                      # reference aliases
    "prefer_larger_batch", "version", "ignore_non_elastic_batch_info",
    "min_time", "model_parallel_size", "num_gpus_per_node",  # accepted, inert
}
_INERT_KEYS = {"min_time", "model_parallel_size", "num_gpus_per_node"}


def world_bounds(e: Dict[str, Any]) -> Tuple[int, int]:
    """The valid world-size range: canonical ``min_world_size``/
    ``max_world_size``, falling back to the reference's gpu-keyed aliases."""
    lo = int(e.get("min_world_size", e.get("min_gpus", 1)))
    hi = int(e.get("max_world_size", e.get("max_gpus", 10000)))
    return lo, hi


def validate_elasticity_block(e: Dict[str, Any], warn=None) -> Dict[str, Any]:
    """Validate an ``elasticity`` block's shape and ranges; returns a
    normalized copy (canonical world keys resolved). Raises
    :class:`ElasticityError` with the exact offending knob — this is the one
    validation the runtime config AND the elastic agent go through, so a bad
    block dies at config load, not mid-resize."""
    if not isinstance(e, dict):
        raise ElasticityError(
            f"elasticity block must be a dict, got {type(e).__name__}")
    unknown = set(e) - _KNOWN_KEYS
    if unknown:
        raise ElasticityError(
            f"unknown elasticity keys {sorted(unknown)}; known: "
            f"{sorted(_KNOWN_KEYS)}")
    inert = sorted(set(e) & _INERT_KEYS)
    if inert and warn is not None:
        warn(f"elasticity keys {inert} accepted for reference-config "
             f"compatibility but inert on TPU")
    version = float(e.get("version", LATEST_ELASTICITY_VERSION))
    if version > LATEST_ELASTICITY_VERSION:
        raise ElasticityError(f"unsupported elasticity version {version}")
    max_batch = int(e.get("max_train_batch_size", 2000))
    if max_batch < 1:
        raise ElasticityError(
            f"max_train_batch_size must be >= 1, got {max_batch}")
    micro = e.get("micro_batch_sizes", [2, 4, 6])
    if not isinstance(micro, (list, tuple)) or not micro:
        raise ElasticityError(
            f"micro_batch_sizes must be a non-empty list, got {micro!r}")
    micro = [int(m) for m in micro]
    if any(m < 1 for m in micro):
        raise ElasticityError(
            f"micro_batch_sizes must be positive, got {micro}")
    if min(micro) > max_batch:
        raise ElasticityError(
            f"every micro batch in {micro} exceeds max_train_batch_size="
            f"{max_batch}: no candidate global batch exists")
    lo, hi = world_bounds(e)
    if lo < 1 or hi < lo:
        raise ElasticityError(f"invalid world-size range [{lo}, {hi}]")
    out = dict(e)
    out["micro_batch_sizes"] = micro
    out["max_train_batch_size"] = max_batch
    out["min_world_size"] = lo
    out["max_world_size"] = hi
    out["version"] = version
    return out


def _fingerprint(e: Dict[str, Any]) -> Dict[str, Any]:
    """The convergence-relevant knobs: changing any of these mid-job changes
    the effective batch schedule the scheduler planned resizes around."""
    return {
        "max_train_batch_size": int(e.get("max_train_batch_size", 2000)),
        "micro_batch_sizes": sorted(
            int(m) for m in e.get("micro_batch_sizes", [2, 4, 6])),
        "version": float(e.get("version", LATEST_ELASTICITY_VERSION)),
    }


def ensure_immutable_elastic_config(runtime_elastic_config: Dict[str, Any],
                                    warn=None) -> bool:
    """Refuse to run if the scheduler scaled this job with a DIFFERENT elastic
    config than the runtime is using (parity:
    ``ensure_immutable_elastic_config``, ``elasticity/elasticity.py:254``).

    Returns True when the fingerprint was verified, False when no scheduler
    config is present (warned — resizes are then unguaranteed)."""
    raw = (os.environ.get(ELASTICITY_CONFIG_ENV)
           or os.environ.get(_ELASTICITY_CONFIG_ENV_COMPAT))
    if raw is None:
        msg = (f"{ELASTICITY_CONFIG_ENV} not set: cannot guarantee the "
               "resource scheduler will resize this job at compatible "
               "worker counts")
        if warn is not None:
            warn(msg)
        else:
            import logging

            from ..utils.logging import log_dist

            log_dist(msg, level=logging.WARNING)
        return False
    try:
        sched = json.loads(raw)
    except ValueError as e:
        raise ElasticityError(
            f"{ELASTICITY_CONFIG_ENV} is not valid JSON: {e}") from e
    sched_fp = _fingerprint(sched.get("elasticity", sched))
    run_fp = _fingerprint(runtime_elastic_config)
    for k in sched_fp:
        if sched_fp[k] != run_fp[k]:
            raise ElasticityError(
                f"elastic config '{k}' seen by the resource scheduler "
                f"({sched_fp[k]}) does not match the runtime config "
                f"({run_fp[k]}) — the scheduler's resize plan would break "
                "the effective batch invariant")
    return True


def get_candidate_batch_sizes(base_list: List[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """Each micro-batch size scaled by powers of 2 up to the ceiling."""
    candidates = set()
    for base in base_list:
        if base <= 0:
            raise ElasticityError(f"micro batch size must be positive, got {base}")
        b = base
        while b <= max_acceptable_batch_size:
            candidates.add(b)
            b *= 2
    return sorted(candidates)


def get_valid_world_sizes(batch_size: int, micro_batches: List[int],
                          min_world: int, max_world: int) -> List[int]:
    """World sizes at which ``batch_size`` decomposes as micro x gas x world."""
    valid = []
    for w in range(min_world, max_world + 1):
        for mb in micro_batches:
            if batch_size % (mb * w) == 0:
                valid.append(w)
                break
    return valid


# reference-spelling alias (torch-era name; world = device count here)
get_valid_gpus = get_valid_world_sizes


def _best_candidate(candidates: List[int], micro_batches: List[int],
                    min_gpus: int, max_gpus: int, prefer_larger: bool
                    ) -> Tuple[Optional[int], List[int]]:
    best_bs, best_gpus = None, []
    order = reversed(candidates) if prefer_larger else iter(candidates)
    for bs in order:
        gpus = get_valid_world_sizes(bs, micro_batches, min_gpus, max_gpus)
        if len(gpus) > len(best_gpus):
            best_bs, best_gpus = bs, gpus
    return best_bs, best_gpus


def compute_elastic_config(ds_config: Dict[str, Any], world_size: int = 0
                           ) -> Tuple[int, List[int], int]:
    """Resolve the elasticity block. Parity: ``elasticity.py:287``.

    Returns ``(final_batch_size, valid_world_sizes, micro_batch)`` where
    ``micro_batch`` is resolved only when ``world_size`` > 0 (0 = just planning).
    """
    e = dict(ds_config.get("elasticity", {}) if isinstance(ds_config, dict)
             else ds_config.elasticity or {})
    if not e.get("enabled", False):
        raise ElasticityError("elasticity block missing or disabled")
    e = validate_elasticity_block(e)
    # fingerprint check against the scheduler's copy BEFORE resolving: a
    # drifted config must fail loudly, not train at the wrong batch plan
    ensure_immutable_elastic_config(e)
    max_batch = e["max_train_batch_size"]
    micro_batches = e["micro_batch_sizes"]
    min_gpus, max_gpus = world_bounds(e)
    prefer_larger = bool(e.get("prefer_larger_batch", True))

    candidates = get_candidate_batch_sizes(micro_batches, max_batch)
    final_bs, valid_gpus = _best_candidate(
        candidates, micro_batches, min_gpus, max_gpus, prefer_larger)
    if final_bs is None:
        raise ElasticityError(
            f"no batch size <= {max_batch} works for micro batches {micro_batches} "
            f"over [{min_gpus}, {max_gpus}] workers")

    micro = -1
    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityError(
                f"world size {world_size} is not among the valid sizes {valid_gpus} "
                f"for elastic batch {final_bs}")
        # largest micro batch that divides (reference prefers larger micro)
        for mb in sorted(micro_batches, reverse=prefer_larger):
            if final_bs % (mb * world_size) == 0:
                micro = mb
                break
    return final_bs, valid_gpus, micro


def elastic_ladder(ds_config: Dict[str, Any]) -> List[Tuple[int, int, int]]:
    """The full resize plan: every valid ``(world, micro, gas)`` triple for
    the block's chosen elastic batch, ascending by world size. The one list
    the agent resolves launches from and the runtime config validates its
    batch triangle against. Resolves the block ONCE (one validation, one
    scheduler-fingerprint check) and selects each world's micro batch with
    the same largest-dividing rule ``compute_elastic_config`` applies."""
    final_bs, valid, _ = compute_elastic_config(ds_config, 0)
    e = validate_elasticity_block(dict(
        ds_config.get("elasticity", {}) if isinstance(ds_config, dict)
        else ds_config.elasticity or {}))
    prefer_larger = bool(e.get("prefer_larger_batch", True))
    ladder = []
    for w in valid:
        for mb in sorted(e["micro_batch_sizes"], reverse=prefer_larger):
            if final_bs % (mb * w) == 0:
                ladder.append((w, mb, final_bs // (mb * w)))
                break
    return ladder
