from .elasticity import (  # noqa: F401
    ElasticityError,
    compute_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
)
