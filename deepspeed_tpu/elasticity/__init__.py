from .elasticity import (  # noqa: F401
    ELASTICITY_CONFIG_ENV,
    ElasticityError,
    compute_elastic_config,
    elastic_ladder,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
    get_valid_world_sizes,
    validate_elasticity_block,
    world_bounds,
)
