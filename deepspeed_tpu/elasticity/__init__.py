from .elasticity import (  # noqa: F401
    ELASTICITY_CONFIG_ENV,
    ElasticityError,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
)
