"""deepspeed_tpu: a TPU-native training & inference framework with the capability
surface of DeepSpeed (reference: carmocca/DeepSpeed v0.8.1), built on JAX/XLA —
``jax.sharding`` meshes + jit for parallelism, ``jax.lax`` collectives over ICI/DCN
in place of NCCL, Pallas kernels in place of CUDA.

Top-level API parity with ``deepspeed/__init__.py``:
- :func:`initialize` (``deepspeed/__init__.py:52``)
- :func:`init_inference` (``:233``)
- :func:`add_config_arguments` (``:210``)
"""

from __future__ import annotations

import argparse
from typing import Any, Optional, Tuple

__version__ = "0.1.0"

from . import comm  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .comm import init_distributed  # noqa: F401  (deepspeed.init_distributed)
from .runtime import zero  # noqa: F401  (deepspeed.zero parity surface)
from .runtime.pipe.module import LayerSpec, PipelineModule  # noqa: F401
from .models.api import Module  # noqa: F401
from .runtime.config import DeepSpeedConfig  # noqa: F401
from .runtime.engine import DeepSpeedEngine  # noqa: F401
from .runtime.topology import MeshTopology  # noqa: F401
from .utils.logging import log_dist, logger  # noqa: F401


def initialize(
    args: Optional[argparse.Namespace] = None,
    model: Optional[Module] = None,
    optimizer: Any = None,
    model_parameters: Any = None,
    training_data: Any = None,
    lr_scheduler: Any = None,
    topology: Optional[MeshTopology] = None,
    dist_init_required: Optional[bool] = None,
    config: Any = None,
    config_params: Any = None,
    seed: Optional[int] = None,
) -> Tuple[DeepSpeedEngine, Any, Any, Any]:
    """Create a training engine. Parity: ``deepspeed.initialize``
    (``deepspeed/__init__.py:52``) — same return arity
    ``(engine, optimizer, dataloader, lr_scheduler)``.

    ``model`` is a :class:`deepspeed_tpu.Module` (functional init/apply/specs).
    ``config`` is a DeepSpeed-style JSON dict or path (``config_params`` accepted as
    the legacy alias). ``optimizer``/``lr_scheduler`` callables override the config
    blocks (parity with passing a client optimizer/scheduler).
    """
    if model is None:
        raise ValueError("deepspeed_tpu.initialize: model is required")
    cfg = config if config is not None else config_params
    if cfg is None and args is not None:
        cfg = getattr(args, "deepspeed_config", None)
    import jax

    if dist_init_required is None or dist_init_required:
        comm.init_distributed()

    ds_config = cfg if isinstance(cfg, DeepSpeedConfig) else DeepSpeedConfig.load(
        cfg, world_size=jax.device_count())
    from .ops.optimizers import Optimizer as _Opt
    from .runtime.pipe.module import PipelineModule

    if optimizer is not None and not isinstance(optimizer, _Opt):
        raise TypeError(
            "client optimizer must be a deepspeed_tpu.ops.optimizers.Optimizer "
            f"(got {type(optimizer)})")

    # A PipelineModule (heterogeneous layer-spec list) trains on the MPMD
    # interpreter with the engine's real optimizer/precision/checkpoint stack
    # (parity: deepspeed.initialize returning a PipelineEngine,
    # deepspeed/__init__.py:124-148).
    if isinstance(model, PipelineModule):
        from .runtime.pipe.engine import PipelineEngine as _PipeEngineT

        # features the MPMD interpreter does not implement must fail loudly
        # here — DeepSpeedEngine.__init__'s exclusivity checks never run on
        # this path, and a silently inert config is worse than an error
        if ds_config.progressive_layer_drop.enabled:
            raise ValueError(
                "progressive_layer_drop is not supported on the MPMD "
                "PipelineEngine path (use a functional model)")
        if ds_config.zero_optimization.offload_param_device in ("cpu", "nvme"):
            raise ValueError(
                "offload_param (ZeRO-Infinity param streaming) is not "
                "supported on the MPMD PipelineEngine path")
        if topology is not None:
            raise ValueError(
                "topology is not supported with a PipelineModule — the MPMD "
                "PipelineEngine builds its own stage-per-device grid from "
                "config.mesh.dp; use mesh.pp>1 with a functional model for "
                "mesh-based pipelining")
        engine = _PipeEngineT(
            module=model,
            config=ds_config,
            lr_scheduler_fn=lr_scheduler if callable(lr_scheduler) else None,
            client_optimizer=optimizer,
            seed=seed,
        )
        dataloader = None
        if training_data is not None:
            from .runtime.dataloader import DeepSpeedDataLoader

            dataloader = DeepSpeedDataLoader(
                training_data,
                batch_size=engine.micro_batch_size * engine.M * engine.dp)
        return engine, engine.optimizer, dataloader, engine.lr_fn

    # pp > 1 with a pipeline-capable functional model: rebuild it as the SPMD
    # collective-permute pipeline (layer stack sharded over the pp mesh axis)
    # and train it through the standard engine — ZeRO over dp, precision,
    # checkpointing all apply unchanged.
    if ds_config.mesh.pp > 1 and not model.pipelined:
        if model.to_pipeline is None:
            raise ValueError(
                f"mesh.pp={ds_config.mesh.pp} requires a pipeline-capable model: "
                "pass a Module with to_pipeline (models.build_gpt provides one) "
                "or a PipelineModule")
        num_micro = ds_config.pipeline.micro_batches or 2 * ds_config.mesh.pp
        model = model.to_pipeline(ds_config.mesh.pp, num_micro)

    engine = DeepSpeedEngine(
        model=model,
        config=ds_config,
        topology=topology,
        seed=seed,
        lr_scheduler_fn=lr_scheduler if callable(lr_scheduler) else None,
        client_optimizer=optimizer,
    )
    training_dataloader = None
    if training_data is not None:
        from .runtime.dataloader import DeepSpeedDataLoader

        # the engine consumes the per-process slice of the GLOBAL batch:
        # micro_batch x (dp extent handled by this process)
        per_process = (engine.micro_batch_size * engine.topo.data_parallel_size
                       // jax.process_count())
        training_dataloader = DeepSpeedDataLoader(
            training_data, batch_size=per_process)
    return engine, engine.optimizer, training_dataloader, engine.lr_fn


def init_inference(model: Any = None, config: Any = None, **kwargs):
    """Create an inference engine. Parity: ``deepspeed.init_inference``
    (``deepspeed/__init__.py:233``)."""
    from .inference.engine import InferenceEngine, for_gpt
    from .inference.config import DeepSpeedInferenceConfig

    if config is None:
        config = kwargs
    elif kwargs:
        config = {**(config if isinstance(config, dict) else {}), **kwargs}
    inf_cfg = (config if isinstance(config, DeepSpeedInferenceConfig)
               else DeepSpeedInferenceConfig(**config))
    # on-disk checkpoint: stream multi-file safetensors/bin shards leaf-by-leaf
    # — no torch model in memory (parity: the reference's sharded-checkpoint
    # loading, module_inject/load_checkpoint.py:370 + inference/engine.py:280-441)
    if model is None and isinstance(inf_cfg.checkpoint, str):
        from .models.gpt import GPTConfig
        from .module_inject.load_checkpoint import load_hf_checkpoint

        gpt_cfg, params = load_hf_checkpoint(inf_cfg.checkpoint)
        if not isinstance(gpt_cfg, GPTConfig):
            raise ValueError(
                f"checkpoint at {inf_cfg.checkpoint} is a "
                f"{type(gpt_cfg).__name__} architecture — only decoder-LM "
                f"(GPT-family) checkpoints have a generate path; wrap encoder "
                f"models with a custom adapter instead")
        model = for_gpt(gpt_cfg, params)
    # HF transformers model: route through the import policies (the reference's
    # replace_transformer_layer path, module_inject/replace_module.py:302)
    if model is not None and hasattr(model, "state_dict") and hasattr(model, "config") \
            and not hasattr(model, "prefill"):
        from .models.gpt import GPTConfig
        from .module_inject import import_hf_model

        gpt_cfg, params = import_hf_model(model)
        if not isinstance(gpt_cfg, GPTConfig):
            raise ValueError(
                f"{type(model).__name__} is not a decoder LM; init_inference's "
                f"generate path needs a GPT-family model — use the imported "
                f"(config, params) with your own adapter for encoder models")
        model = for_gpt(gpt_cfg, params)
    return InferenceEngine(model, inf_cfg)


def add_config_arguments(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """Parity: ``deepspeed.add_config_arguments`` (``deepspeed/__init__.py:210``)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, always on here)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed JSON config")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse.SUPPRESS)  # legacy alias
    return parser
