"""Measured (runtime) collective accounting from ``jax.profiler`` traces.

Closes the gap VERDICT r3 called out against the reference's per-op runtime
log (``deepspeed/utils/comms_logging.py:56``): the facade's
:class:`~deepspeed_tpu.comm.comm.CommsLogger` counts collectives at TRACE
time (per compiled program, scaled by executed steps) — an estimate. This
module runs a step under the profiler and parses the device timeline, so the
numbers are what the hardware actually executed, including the collectives
GSPMD inserted that never pass through the facade.

Mechanics: ``jax.profiler.trace`` writes a Chrome-trace
(``*.trace.json.gz``) per session; complete events (``ph == "X"``) whose
names are XLA collective thunks (``all-reduce``, ``all-gather``,
``reduce-scatter``, ``all-to-all``, ``collective-permute``, ...) carry the
per-device durations. Each participating device contributes its own event,
so totals are summed across lanes and reported alongside the per-device
average. Collectives fused into larger computations (rare on TPU — XLA keeps
collective thunks discrete) would be invisible; counts here are a floor.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import jax

from ..utils.logging import log_dist

# XLA collective thunk names, optionally prefixed (module scoping) and
# suffixed (.N instance ids, -start/-done pairs for async collectives)
_COLLECTIVE_RE = re.compile(
    r"^(?:[\w-]+[./])?"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|ragged-all-to-all|"
    r"collective-permute|collective-broadcast)"
    r"(-start|-done)?(?:[.\d]*)$")


@dataclass
class WireRecord:
    count: int = 0
    logical_bytes: int = 0  # what full precision would have moved
    wire_bytes: int = 0     # what the quantized format actually moves


@dataclass
class WireLedger:
    """Logical-vs-wire byte ledger for quantized collectives.

    The quantized ops (``comm/quantized.py``) report here at trace time: for
    each op, the bytes the equivalent full-precision collective would have put
    on the wire next to the int payload + scales actually sent. This is the
    observable the ZeRO++-style config knobs are tuned against — per-op
    compression ratios, independent of the facade's enable flag (compression
    evidence must not vanish because comms logging is off).

    ``overlap``: the measured exposed-vs-overlapped collective-time column
    (:func:`profile_overlap` result dict), attached after a profiled step so
    the compression evidence and the latency-hiding evidence render together
    — bytes saved mean nothing if the remaining wire still sits exposed on
    the critical path.
    """

    records: Dict[str, WireRecord] = field(default_factory=dict)
    overlap: Optional[Dict[str, float]] = None
    # host<->HBM DMA column (:class:`HostDmaStats`.to_dict): attached by the
    # streaming offload engine after each step so comms_summary() renders
    # the host wire next to the collective wire
    host_dma: Optional[Dict[str, float]] = None
    # graceful-degradation history: ops demoted off the quantized wire by the
    # health subsystem (resilience/rollback.py WireDemotionController) — kept
    # in the ledger so comms_summary() shows the wire's true state, not just
    # its configured one
    demotions: list = field(default_factory=list)

    def record(self, op_name: str, logical_bytes: int, wire_bytes: int) -> None:
        rec = self.records.setdefault(op_name, WireRecord())
        rec.count += 1
        rec.logical_bytes += int(logical_bytes)
        rec.wire_bytes += int(wire_bytes)

    def record_demotion(self, op: str, step: int, reason: str) -> None:
        """A quantized op fell back to the full-precision wire at ``step``."""
        self.demotions.append({"op": op, "step": int(step), "reason": reason,
                               "repromoted_step": None})

    def record_repromotion(self, op: str, step: int) -> None:
        """The newest open demotion of ``op`` ended at ``step``."""
        for d in reversed(self.demotions):
            if d["op"] == op and d["repromoted_step"] is None:
                d["repromoted_step"] = int(step)
                return

    def demoted_ops(self) -> list:
        """Ops currently on the full-precision wire (open demotions)."""
        return [d["op"] for d in self.demotions
                if d["repromoted_step"] is None]

    def ratio(self, prefix: Optional[str] = None) -> float:
        """Aggregate logical/wire compression ratio over ops matching
        ``prefix`` (all quantized ops when None); 1.0 when nothing matched."""
        logical = wire = 0
        for name, rec in self.records.items():
            if prefix is None or name.startswith(prefix):
                logical += rec.logical_bytes
                wire += rec.wire_bytes
        return logical / wire if wire else 1.0

    def summary_dict(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, rec in sorted(self.records.items()):
            out[name] = {
                "count": rec.count,
                "logical_bytes": rec.logical_bytes,
                "wire_bytes": rec.wire_bytes,
                "ratio": round(rec.logical_bytes / max(1, rec.wire_bytes), 3),
            }
        return out

    def set_overlap(self, overlap: Optional[Dict[str, float]]) -> None:
        """Attach a measured overlap column (:meth:`OverlapStats.to_dict`)."""
        self.overlap = dict(overlap) if overlap else None

    def set_host_dma(self, dma: Optional[Dict[str, float]]) -> None:
        """Attach a host-DMA column (:meth:`HostDmaStats.to_dict`)."""
        self.host_dma = dict(dma) if dma else None

    def summary(self) -> str:
        lines = ["quantized wire accounting (trace-time):"]
        for name, row in self.summary_dict().items():
            lines.append(
                f"  {name:<32} count={row['count']:<5} "
                f"logical={row['logical_bytes']} wire={row['wire_bytes']} "
                f"({row['ratio']}x)")
        if not self.records:
            lines.append("  (no quantized collectives traced)")
        if self.overlap:
            o = self.overlap
            lines.append(
                f"  overlap (measured): collective={o.get('collective_us', 0):.0f}us "
                f"exposed={o.get('exposed_us', 0):.0f}us "
                f"overlapped={o.get('overlapped_us', 0):.0f}us "
                f"({o.get('hidden_frac', 0.0):.0%} hidden)")
        if self.host_dma:
            h = self.host_dma
            lines.append(
                f"  host DMA (offload stream, last step): "
                f"pushed={h.get('push_bytes', 0)}B "
                f"wire={h.get('wire_bytes', 0)}B "
                f"grads={h.get('grad_bytes', 0)}B "
                f"depth={h.get('prefetch_depth', 0)} "
                f"exposed_wait={h.get('exposed_wait_s', 0.0):.3f}s "
                f"({h.get('overlapped_frac', 0.0):.0%} of waits overlapped)")
        for d in self.demotions:
            end = (f"re-promoted at step {d['repromoted_step']}"
                   if d["repromoted_step"] is not None else "STILL DEMOTED")
            lines.append(
                f"  degraded wire: {d['op']} -> full-precision at step "
                f"{d['step']} ({d['reason']}); {end}")
        out = "\n".join(lines)
        log_dist(out)
        return out

    def snapshot(self) -> Dict[str, int]:
        """Per-op trace counts right now — diff two snapshots to attribute
        quantized-wire records to one trace (the static analyzer's
        ``ProgramIR.wire_records`` does exactly this)."""
        return {name: rec.count for name, rec in self.records.items()}

    def delta(self, before: Dict[str, int]) -> Dict[str, int]:
        """Ops recorded since ``before`` (a :meth:`snapshot` result)."""
        return {name: rec.count - before.get(name, 0)
                for name, rec in self.records.items()
                if rec.count > before.get(name, 0)}

    def reset(self) -> None:
        self.records.clear()
        self.demotions.clear()
        self.host_dma = None


wire_ledger = WireLedger()


@dataclass
class HostDmaStats:
    """Per-step host<->HBM DMA accounting for the streaming offload engine
    (``runtime/zero/stream.py``).

    ``push_bytes`` is the logical (compute-dtype) volume pushed host->HBM;
    ``wire_bytes`` what actually moved (smaller under quantized fetch);
    ``grad_bytes`` the device->host gradient fetch volume.
    ``exposed_wait_s`` is the time the host spent BLOCKED on an in-flight
    transfer at a consume point — the step-time cost of the DMA the prefetch
    schedule failed to hide. A push whose wait was under ``READY_EPS_S``
    counts as *overlapped* (the transfer landed entirely under compute);
    ``overlapped_frac`` is the fraction of waits that did — the bench A/B
    observable for streamed vs fetch-on-demand schedules."""

    READY_EPS_S = 1e-3

    pushes: int = 0
    push_bytes: int = 0
    wire_bytes: int = 0
    grad_fetches: int = 0
    grad_bytes: int = 0
    waits: int = 0
    overlapped_waits: int = 0
    exposed_wait_s: float = 0.0
    issue_s: float = 0.0
    step_s: float = 0.0
    prefetch_depth: int = 0
    quantized: bool = False

    def record_push(self, logical_bytes: int, wire_bytes: int) -> None:
        self.pushes += 1
        self.push_bytes += int(logical_bytes)
        self.wire_bytes += int(wire_bytes)

    def record_wait(self, seconds: float) -> None:
        self.waits += 1
        if seconds < self.READY_EPS_S:
            self.overlapped_waits += 1
        self.exposed_wait_s += float(seconds)

    def record_grad_fetch(self, nbytes: int, seconds: float) -> None:
        self.grad_fetches += 1
        self.grad_bytes += int(nbytes)
        self.record_wait(seconds)

    @property
    def overlapped_frac(self) -> float:
        return self.overlapped_waits / self.waits if self.waits else 0.0

    @property
    def ratio(self) -> float:
        """Logical/wire compression of the host->HBM push path."""
        return self.push_bytes / self.wire_bytes if self.wire_bytes else 1.0

    def to_dict(self) -> Dict[str, float]:
        return {
            "pushes": self.pushes,
            "push_bytes": self.push_bytes,
            "wire_bytes": self.wire_bytes,
            "grad_fetches": self.grad_fetches,
            "grad_bytes": self.grad_bytes,
            "waits": self.waits,
            "overlapped_waits": self.overlapped_waits,
            "overlapped_frac": round(self.overlapped_frac, 4),
            "exposed_wait_s": round(self.exposed_wait_s, 4),
            "issue_s": round(self.issue_s, 4),
            "step_s": round(self.step_s, 4),
            "prefetch_depth": self.prefetch_depth,
            "quantized": self.quantized,
            "ratio": round(self.ratio, 3),
        }


@dataclass
class CollectiveStats:
    count: int = 0          # events summed across device lanes
    time_us: float = 0.0    # device time summed across lanes


@dataclass
class CollectiveProfile:
    ops: Dict[str, CollectiveStats] = field(default_factory=dict)
    n_devices: int = 1
    wall_us: float = 0.0

    def summary(self) -> str:
        lines = [f"measured collectives ({self.n_devices} devices, "
                 f"wall {self.wall_us:.0f}us):"]
        for name, st in sorted(self.ops.items()):
            lines.append(
                f"  {name:<20} count={st.count:<6} "
                f"device_time_us={st.time_us:.0f} "
                f"per_device_us={st.time_us / max(1, self.n_devices):.0f}")
        if not self.ops:
            lines.append("  (none observed)")
        return "\n".join(lines)


def _parse_trace_dir(trace_dir: str,
                     n_devices: Optional[int] = None) -> CollectiveProfile:
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(
            f"no trace.json.gz under {trace_dir} — did the profiler run?")
    prof = CollectiveProfile(n_devices=n_devices or jax.device_count())
    t_min, t_max = float("inf"), 0.0
    for path in paths:
        with gzip.open(path, "rt") as f:
            events = json.load(f).get("traceEvents", [])
        for e in events:
            if e.get("ph") != "X":
                continue
            name = e.get("name", "")
            if name.startswith("end:"):
                continue  # CPU-backend paired end markers
            m = _COLLECTIVE_RE.match(name)
            if not m:
                continue
            if m.group(2) == "-done":
                # async pair: the -start event carries the transfer duration;
                # counting -done too would double the op count
                continue
            st = prof.ops.setdefault(m.group(1), CollectiveStats())
            st.count += 1
            st.time_us += float(e.get("dur", 0.0))
            ts = float(e.get("ts", 0.0))
            t_min = min(t_min, ts)
            t_max = max(t_max, ts + float(e.get("dur", 0.0)))
    if prof.ops:
        prof.wall_us = t_max - t_min
    return prof


@dataclass
class OverlapStats:
    """Exposed-vs-overlapped collective time, from the device timeline.

    Per device lane: ``collective_us`` is the union of collective-thunk
    intervals; ``overlapped_us`` the part of that union concurrently covered
    by non-collective device compute on the same device (the wire XLA's
    scheduler actually hid); ``exposed_us`` the rest — the step-time cost of
    communication. ``compute_us`` is the compute-interval union and
    ``busy_us`` the union of ALL device activity, so by construction
    ``busy_us == compute_us + exposed_us`` and
    ``collective_us == exposed_us + overlapped_us`` — the accounting always
    sums to where the step time went. All values are summed across devices.
    """

    collective_us: float = 0.0
    exposed_us: float = 0.0
    overlapped_us: float = 0.0
    compute_us: float = 0.0
    busy_us: float = 0.0
    n_devices: int = 1
    wall_us: float = 0.0

    @property
    def hidden_frac(self) -> float:
        return self.overlapped_us / self.collective_us if self.collective_us else 0.0

    def to_dict(self) -> Dict[str, float]:
        return {
            "collective_us": round(self.collective_us, 1),
            "exposed_us": round(self.exposed_us, 1),
            "overlapped_us": round(self.overlapped_us, 1),
            "compute_us": round(self.compute_us, 1),
            "busy_us": round(self.busy_us, 1),
            "hidden_frac": round(self.hidden_frac, 4),
            "n_devices": self.n_devices,
            "wall_us": round(self.wall_us, 1),
        }

    def summary(self) -> str:
        return (f"collective overlap ({self.n_devices} devices): "
                f"collective={self.collective_us:.0f}us "
                f"exposed={self.exposed_us:.0f}us "
                f"overlapped={self.overlapped_us:.0f}us "
                f"({self.hidden_frac:.0%} hidden under "
                f"{self.compute_us:.0f}us compute)")


def _union(intervals) -> list:
    """Merge [(start, end), ...] into a disjoint sorted union."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _measure(intervals) -> float:
    return sum(e - s for s, e in intervals)


def _intersect(a: list, b: list) -> float:
    """Total overlap between two disjoint sorted interval unions."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_from_events(events, n_devices: Optional[int] = None) -> OverlapStats:
    """Compute :class:`OverlapStats` from chrome-trace ``traceEvents``.

    Groups complete (``ph == "X"``) events by trace pid (one per device
    lane), splits them into collective thunks (async ``-start`` events carry
    the transfer duration; ``-done`` markers are skipped like in
    :func:`_parse_trace_dir`) and everything else (compute), and does the
    interval math per lane. Pure function of the event list — the unit tests
    feed synthetic traces."""
    by_pid: Dict[Any, Dict[str, list]] = {}
    t_min, t_max = float("inf"), 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name", "")
        if name.startswith("end:"):
            continue
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        if dur <= 0:
            continue
        m = _COLLECTIVE_RE.match(name)
        if m and m.group(2) == "-done":
            continue
        lane = by_pid.setdefault(e.get("pid", 0),
                                 {"coll": [], "comp": []})
        lane["coll" if m else "comp"].append((ts, ts + dur))
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    stats = OverlapStats(n_devices=n_devices or max(1, len(by_pid)))
    for lane in by_pid.values():
        coll = _union(lane["coll"])
        comp = _union(lane["comp"])
        busy = _union(lane["coll"] + lane["comp"])
        c_us = _measure(coll)
        hidden = _intersect(coll, comp)
        stats.collective_us += c_us
        stats.overlapped_us += hidden
        stats.exposed_us += c_us - hidden
        stats.compute_us += _measure(comp)
        stats.busy_us += _measure(busy)
    if t_max > 0:
        stats.wall_us = t_max - t_min
    return stats


def _events_from_trace_dir(trace_dir: str) -> list:
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(
            f"no trace.json.gz under {trace_dir} — did the profiler run?")
    events = []
    for path in paths:
        with gzip.open(path, "rt") as f:
            events.extend(json.load(f).get("traceEvents", []))
    return events


def profile_overlap(fn: Callable[[], Any],
                    trace_dir: Optional[str] = None,
                    n_devices: Optional[int] = None,
                    attach: bool = True) -> OverlapStats:
    """Run ``fn()`` under the profiler and return the exposed-vs-overlapped
    collective-time accounting from the device timeline. ``attach=True``
    (default) also attaches the result to :data:`wire_ledger` so
    ``engine.comms_summary()`` and bench rows render the overlap column."""
    own = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="ds_tpu_overlap_")
    try:
        with jax.profiler.trace(d):
            out = fn()
            jax.block_until_ready(out)
        stats = overlap_from_events(
            _events_from_trace_dir(d),
            n_devices=n_devices or jax.device_count())
    finally:
        if own:  # multi-MB chrome traces must not accumulate in /tmp
            shutil.rmtree(d, ignore_errors=True)
    if attach:
        wire_ledger.set_overlap(stats.to_dict())
    log_dist(stats.summary())
    return stats


def profile_collectives(fn: Callable[[], Any],
                        trace_dir: Optional[str] = None,
                        n_devices: Optional[int] = None) -> CollectiveProfile:
    """Run ``fn()`` under the profiler and return the measured collective
    counts/durations from the device timeline. ``fn`` should block on its
    results (the profiler only sees executed work). ``n_devices``: how many
    devices the profiled program actually spans (defaults to all local
    devices) — the per-device averages divide by this."""
    own = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="ds_tpu_comms_")
    try:
        with jax.profiler.trace(d):
            out = fn()
            jax.block_until_ready(out)
        return _parse_trace_dir(d, n_devices=n_devices)
    finally:
        if own:  # multi-MB chrome traces must not accumulate in /tmp
            shutil.rmtree(d, ignore_errors=True)


def verify_comms(engine, batch) -> str:
    """``ds_bench --verify`` / debug surface: run ONE ``train_batch`` under
    the profiler and print measured per-collective counts/time next to the
    facade's trace-time estimate (``engine.comms_summary``). Divergence is
    expected and informative: GSPMD-inserted collectives (ZeRO sharding,
    batch resharding) appear only in the measured column."""
    measured = profile_collectives(lambda: engine.train_batch(batch))
    est = ""
    try:
        from . import comm as _comm

        if _comm.comms_logger.records:
            est = "\ntrace-time estimate (facade ops only, ONE step):\n" + \
                "\n".join(
                    f"  {name:<20} count={rec.count:<6} bytes={rec.bytes}"
                    for name, rec in sorted(_comm.comms_logger.records.items()))
    except Exception:  # accounting disabled — measured side still stands
        pass
    out = measured.summary() + est
    log_dist(out)
    return out
