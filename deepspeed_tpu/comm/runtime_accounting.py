"""Measured (runtime) collective accounting from ``jax.profiler`` traces.

Closes the gap VERDICT r3 called out against the reference's per-op runtime
log (``deepspeed/utils/comms_logging.py:56``): the facade's
:class:`~deepspeed_tpu.comm.comm.CommsLogger` counts collectives at TRACE
time (per compiled program, scaled by executed steps) — an estimate. This
module runs a step under the profiler and parses the device timeline, so the
numbers are what the hardware actually executed, including the collectives
GSPMD inserted that never pass through the facade.

Mechanics: ``jax.profiler.trace`` writes a Chrome-trace
(``*.trace.json.gz``) per session; complete events (``ph == "X"``) whose
names are XLA collective thunks (``all-reduce``, ``all-gather``,
``reduce-scatter``, ``all-to-all``, ``collective-permute``, ...) carry the
per-device durations. Each participating device contributes its own event,
so totals are summed across lanes and reported alongside the per-device
average. Collectives fused into larger computations (rare on TPU — XLA keeps
collective thunks discrete) would be invisible; counts here are a floor.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import jax

from ..utils.logging import log_dist

# XLA collective thunk names, optionally prefixed (module scoping) and
# suffixed (.N instance ids, -start/-done pairs for async collectives)
_COLLECTIVE_RE = re.compile(
    r"^(?:[\w-]+[./])?"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|ragged-all-to-all|"
    r"collective-permute|collective-broadcast)"
    r"(-start|-done)?(?:[.\d]*)$")


@dataclass
class WireRecord:
    count: int = 0
    logical_bytes: int = 0  # what full precision would have moved
    wire_bytes: int = 0     # what the quantized format actually moves


@dataclass
class WireLedger:
    """Logical-vs-wire byte ledger for quantized collectives.

    The quantized ops (``comm/quantized.py``) report here at trace time: for
    each op, the bytes the equivalent full-precision collective would have put
    on the wire next to the int payload + scales actually sent. This is the
    observable the ZeRO++-style config knobs are tuned against — per-op
    compression ratios, independent of the facade's enable flag (compression
    evidence must not vanish because comms logging is off).
    """

    records: Dict[str, WireRecord] = field(default_factory=dict)

    def record(self, op_name: str, logical_bytes: int, wire_bytes: int) -> None:
        rec = self.records.setdefault(op_name, WireRecord())
        rec.count += 1
        rec.logical_bytes += int(logical_bytes)
        rec.wire_bytes += int(wire_bytes)

    def ratio(self, prefix: Optional[str] = None) -> float:
        """Aggregate logical/wire compression ratio over ops matching
        ``prefix`` (all quantized ops when None); 1.0 when nothing matched."""
        logical = wire = 0
        for name, rec in self.records.items():
            if prefix is None or name.startswith(prefix):
                logical += rec.logical_bytes
                wire += rec.wire_bytes
        return logical / wire if wire else 1.0

    def summary_dict(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, rec in sorted(self.records.items()):
            out[name] = {
                "count": rec.count,
                "logical_bytes": rec.logical_bytes,
                "wire_bytes": rec.wire_bytes,
                "ratio": round(rec.logical_bytes / max(1, rec.wire_bytes), 3),
            }
        return out

    def summary(self) -> str:
        lines = ["quantized wire accounting (trace-time):"]
        for name, row in self.summary_dict().items():
            lines.append(
                f"  {name:<32} count={row['count']:<5} "
                f"logical={row['logical_bytes']} wire={row['wire_bytes']} "
                f"({row['ratio']}x)")
        if not self.records:
            lines.append("  (no quantized collectives traced)")
        out = "\n".join(lines)
        log_dist(out)
        return out

    def snapshot(self) -> Dict[str, int]:
        """Per-op trace counts right now — diff two snapshots to attribute
        quantized-wire records to one trace (the static analyzer's
        ``ProgramIR.wire_records`` does exactly this)."""
        return {name: rec.count for name, rec in self.records.items()}

    def delta(self, before: Dict[str, int]) -> Dict[str, int]:
        """Ops recorded since ``before`` (a :meth:`snapshot` result)."""
        return {name: rec.count - before.get(name, 0)
                for name, rec in self.records.items()
                if rec.count > before.get(name, 0)}

    def reset(self) -> None:
        self.records.clear()


wire_ledger = WireLedger()


@dataclass
class CollectiveStats:
    count: int = 0          # events summed across device lanes
    time_us: float = 0.0    # device time summed across lanes


@dataclass
class CollectiveProfile:
    ops: Dict[str, CollectiveStats] = field(default_factory=dict)
    n_devices: int = 1
    wall_us: float = 0.0

    def summary(self) -> str:
        lines = [f"measured collectives ({self.n_devices} devices, "
                 f"wall {self.wall_us:.0f}us):"]
        for name, st in sorted(self.ops.items()):
            lines.append(
                f"  {name:<20} count={st.count:<6} "
                f"device_time_us={st.time_us:.0f} "
                f"per_device_us={st.time_us / max(1, self.n_devices):.0f}")
        if not self.ops:
            lines.append("  (none observed)")
        return "\n".join(lines)


def _parse_trace_dir(trace_dir: str,
                     n_devices: Optional[int] = None) -> CollectiveProfile:
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(
            f"no trace.json.gz under {trace_dir} — did the profiler run?")
    prof = CollectiveProfile(n_devices=n_devices or jax.device_count())
    t_min, t_max = float("inf"), 0.0
    for path in paths:
        with gzip.open(path, "rt") as f:
            events = json.load(f).get("traceEvents", [])
        for e in events:
            if e.get("ph") != "X":
                continue
            name = e.get("name", "")
            if name.startswith("end:"):
                continue  # CPU-backend paired end markers
            m = _COLLECTIVE_RE.match(name)
            if not m:
                continue
            if m.group(2) == "-done":
                # async pair: the -start event carries the transfer duration;
                # counting -done too would double the op count
                continue
            st = prof.ops.setdefault(m.group(1), CollectiveStats())
            st.count += 1
            st.time_us += float(e.get("dur", 0.0))
            ts = float(e.get("ts", 0.0))
            t_min = min(t_min, ts)
            t_max = max(t_max, ts + float(e.get("dur", 0.0)))
    if prof.ops:
        prof.wall_us = t_max - t_min
    return prof


def profile_collectives(fn: Callable[[], Any],
                        trace_dir: Optional[str] = None,
                        n_devices: Optional[int] = None) -> CollectiveProfile:
    """Run ``fn()`` under the profiler and return the measured collective
    counts/durations from the device timeline. ``fn`` should block on its
    results (the profiler only sees executed work). ``n_devices``: how many
    devices the profiled program actually spans (defaults to all local
    devices) — the per-device averages divide by this."""
    own = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="ds_tpu_comms_")
    try:
        with jax.profiler.trace(d):
            out = fn()
            jax.block_until_ready(out)
        return _parse_trace_dir(d, n_devices=n_devices)
    finally:
        if own:  # multi-MB chrome traces must not accumulate in /tmp
            shutil.rmtree(d, ignore_errors=True)


def verify_comms(engine, batch) -> str:
    """``ds_bench --verify`` / debug surface: run ONE ``train_batch`` under
    the profiler and print measured per-collective counts/time next to the
    facade's trace-time estimate (``engine.comms_summary``). Divergence is
    expected and informative: GSPMD-inserted collectives (ZeRO sharding,
    batch resharding) appear only in the measured column."""
    measured = profile_collectives(lambda: engine.train_batch(batch))
    est = ""
    try:
        from . import comm as _comm

        if _comm.comms_logger.records:
            est = "\ntrace-time estimate (facade ops only, ONE step):\n" + \
                "\n".join(
                    f"  {name:<20} count={rec.count:<6} bytes={rec.bytes}"
                    for name, rec in sorted(_comm.comms_logger.records.items()))
    except Exception:  # accounting disabled — measured side still stands
        pass
    out = measured.summary() + est
    log_dist(out)
    return out
