"""Quantized collectives: block-int8/int4 wire formats for ZeRO traffic.

The ZeRO++ result (PAPERS.md) is that the collectives dominating sharded-training
step time — stage-3 parameter all-gathers and the dp gradient reduce-scatter —
tolerate block-quantized wire formats with negligible quality loss, cutting comm
volume ~4x; EQuARX shows the same block-quantized exchange is practical *inside*
XLA. This module is that subsystem for the TPU-native stack:

- **Primitives**: :func:`quantize_blockwise` / :func:`dequantize_blockwise` —
  per-block affine (scale + zero-point) quantization over trailing-dimension
  blocks, 8-bit or packed 4-bit payloads, optional stochastic rounding, and a
  shared error-feedback residual step (:func:`error_feedback_step`) used by both
  the int collectives here and the 1-bit compressed allreduce
  (:mod:`deepspeed_tpu.runtime.comm.compressed`).
- **Axis collectives** (call inside ``shard_map``, drop-in shaped like the
  facade's :func:`~deepspeed_tpu.comm.comm.all_gather` /
  :func:`~deepspeed_tpu.comm.comm.reduce_scatter` /
  :func:`~deepspeed_tpu.comm.comm.all_to_all`): :func:`qall_gather`,
  :func:`qreduce_scatter` (dequantize-then-reduce via all-to-all chunks — the
  reduction itself stays fp32, only the wire is int), :func:`qall_to_all`.
- **GSPMD helper** (call inside plain ``jit``): :func:`quantized_reshard` —
  quantize, ``with_sharding_constraint`` the *int payload* to the target spec so
  XLA's inserted collective moves int8/int4 bytes instead of fp32/bf16, then
  dequantize. Straight-through backward (``custom_vjp`` identity), so parameter
  gathers in the forward stay differentiable. This is how quantization composes
  with the repo's declarative ZeRO (collectives are GSPMD-inserted, not called).

Accounting: every op records logical bytes (what full precision would have put
on the wire) and wire bytes (int payload + per-block scales/zero-points) at
trace time, into both the facade's :class:`~deepspeed_tpu.comm.comm.CommsLogger`
and the measured-side ledger
(:data:`deepspeed_tpu.comm.runtime_accounting.wire_ledger`), so the compression
ratio is observable per-op.

Wire format per block of ``B`` elements: ``B`` bytes (int8) or ``B/2`` (int4)
payload + 4-byte fp32 scale + 4-byte fp32 zero-point. At the default B=256 that
is a 3.88x reduction vs fp32, 1.94x vs bf16.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .comm import comms_logger
from .runtime_accounting import wire_ledger

AxisName = Union[str, Sequence[str]]

DEFAULT_BLOCK = 256
SUPPORTED_BITS = (4, 8)


# --------------------------------------------------------------------------- config
@dataclasses.dataclass(frozen=True)
class QuantizedCommConfig:
    """Resolved quantized-collective knobs (from the ``zero_optimization`` block)."""

    weights: bool = False    # zero_quantized_weights: fwd param gathers + MoE a2a
    gradients: bool = False  # zero_quantized_gradients: dp grad reduce-scatter
    bits: int = 8
    block_size: int = DEFAULT_BLOCK
    stochastic: bool = False
    error_feedback: bool = False

    def __post_init__(self):
        if self.bits not in SUPPORTED_BITS:
            raise ValueError(
                f"zero_quantize_bits must be one of {SUPPORTED_BITS}, "
                f"got {self.bits}")
        if self.block_size < 8 or self.block_size % 2:
            raise ValueError(
                f"zero_quantize_block_size must be an even int >= 8, "
                f"got {self.block_size}")

    @property
    def enabled(self) -> bool:
        return self.weights or self.gradients

    @classmethod
    def from_zero_config(cls, zero_cfg: Any) -> "QuantizedCommConfig":
        g = lambda k, d: getattr(zero_cfg, k, d)  # noqa: E731
        return cls(
            weights=bool(g("zero_quantized_weights", False)),
            gradients=bool(g("zero_quantized_gradients", False)),
            bits=int(g("zero_quantize_bits", 8)),
            block_size=int(g("zero_quantize_block_size", DEFAULT_BLOCK)),
            stochastic=bool(g("zero_quantize_stochastic", False)),
            error_feedback=bool(g("zero_quantize_error_feedback", False)),
        )


def active_quantization() -> Optional[QuantizedCommConfig]:
    """The quantization config bound for the current trace, or None.

    The engine binds its ``zero_optimization`` block around tracing (the same
    :func:`~deepspeed_tpu.runtime.zero.gather.gather_window` binding the stage-3
    gather knobs ride); model-level call sites (MoE dispatch, layer scans) read
    it here so quantization follows the engine config without plumbing."""
    from ..runtime.zero.gather import _active_cfg

    cfg = _active_cfg()
    if cfg is None:
        return None
    q = QuantizedCommConfig.from_zero_config(cfg)
    return q if q.enabled else None


# --------------------------------------------------------------------------- accounting
def _record(op_name: str, logical_bytes: int, wire_bytes: int) -> None:
    comms_logger.record(op_name, logical_bytes, wire_bytes=wire_bytes)
    wire_ledger.record(op_name, logical_bytes, wire_bytes)


def _payload_bytes(*arrays) -> int:
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in arrays)


# --------------------------------------------------------------------------- primitives
def effective_block(n_last: int, block_size: int) -> int:
    """Block size actually used for a trailing dim of ``n_last``: the requested
    size, shrunk for short rows so padding never dominates (a [.., 32] leaf
    quantized with 256-blocks would pad 8x and INFLATE the wire). Kept even so
    int4 packing stays byte-aligned."""
    eff = min(int(block_size), int(n_last) + (int(n_last) % 2))
    return max(eff, 2)


def quantization_shrinks(n_last: int, bits: int, block_size: int,
                         logical_itemsize: int) -> bool:
    """Whether the quantized wire (payload + per-block scale/zero-point) is
    actually smaller than the full-precision payload for this row length."""
    eff = effective_block(n_last, block_size)
    return bits / 8.0 + 8.0 / eff < float(logical_itemsize)


def _pad_last(x: jnp.ndarray, multiple: int) -> jnp.ndarray:
    n = x.shape[-1]
    pad = (-n) % multiple
    if pad == 0:
        return x
    cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    # edge padding keeps the tail block's [min, max] range tight (zero padding
    # would widen it and inflate that block's quantization step)
    return jnp.pad(x, cfg, mode="edge")


def quantize_blockwise(
    x: jnp.ndarray,
    bits: int = 8,
    block_size: int = DEFAULT_BLOCK,
    stochastic: bool = False,
    rng: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-block affine quantization over trailing-dimension blocks.

    Returns ``(q, scale, zero_point)``: ``q`` uint8 ``[..., n_pad]`` (int8) or
    ``[..., n_pad/2]`` (int4, two values per byte); ``scale``/``zero_point``
    fp32 ``[..., n_blocks]``. ``x_hat = q * scale + zero_point`` per block.
    ``stochastic=True`` rounds ``floor(v + u)``, ``u ~ U[0,1)`` (unbiased —
    the right choice for gradients); requires ``rng``.
    """
    if bits not in SUPPORTED_BITS:
        raise ValueError(f"bits must be one of {SUPPORTED_BITS}, got {bits}")
    levels = (1 << bits) - 1
    block_size = effective_block(x.shape[-1], block_size)
    x32 = _pad_last(x.astype(jnp.float32), block_size)
    lead = x32.shape[:-1]
    nb = x32.shape[-1] // block_size
    xb = x32.reshape(lead + (nb, block_size))
    mn = jnp.min(xb, axis=-1)
    mx = jnp.max(xb, axis=-1)
    scale = jnp.maximum((mx - mn) / levels, jnp.float32(1e-12))
    v = (xb - mn[..., None]) / scale[..., None]
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding requires an rng key")
        v = jnp.floor(v + jax.random.uniform(rng, v.shape, jnp.float32))
    else:
        v = jnp.round(v)
    q = jnp.clip(v, 0, levels).astype(jnp.uint8).reshape(lead + (nb * block_size,))
    if bits == 4:
        q = (q[..., 0::2] | (q[..., 1::2] << 4)).astype(jnp.uint8)
    return q, scale, mn


def dequantize_blockwise(
    q: jnp.ndarray,
    scale: jnp.ndarray,
    zero_point: jnp.ndarray,
    bits: int = 8,
    block_size: int = DEFAULT_BLOCK,
    orig_size: Optional[int] = None,
) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (fp32 output, trailing padding
    trimmed to ``orig_size`` when given). The block extent is derived from the
    payload/scale shapes, so it stays consistent with whatever effective block
    the quantizer picked; ``block_size`` is accepted for signature symmetry."""
    del block_size
    lead = q.shape[:-1]
    if bits == 4:
        lo = (q & 0xF).astype(jnp.uint8)
        hi = (q >> 4).astype(jnp.uint8)
        q = jnp.stack([lo, hi], axis=-1).reshape(lead + (q.shape[-1] * 2,))
    nb = scale.shape[-1]
    block = q.shape[-1] // nb
    xb = q.reshape(lead + (nb, block)).astype(jnp.float32)
    x = (xb * scale[..., None] + zero_point[..., None]).reshape(
        lead + (nb * block,))
    if orig_size is not None and orig_size != x.shape[-1]:
        x = x[..., :orig_size]
    return x


# numpy mirrors of the blockwise pair — the HOST side of the quantized
# host<->HBM DMA path (runtime/zero/stream.py pushes int8 payloads instead of
# bf16/fp32, GatheredParameters(quantized=True) dequantizes fetched payloads).
# Same effective-block / edge-pad / round-half-even semantics as the jnp pair,
# so a host-quantized push dequantized on device round-trips identically.
def np_quantize_blockwise(
    x: np.ndarray,
    bits: int = 8,
    block_size: int = DEFAULT_BLOCK,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host (numpy) :func:`quantize_blockwise`: returns ``(q, scale, zp)``
    with the same shapes/dtypes the jnp quantizer produces (deterministic
    rounding only — stochastic rounding is a device-side concern)."""
    if bits not in SUPPORTED_BITS:
        raise ValueError(f"bits must be one of {SUPPORTED_BITS}, got {bits}")
    levels = (1 << bits) - 1
    block_size = effective_block(x.shape[-1], block_size)
    x32 = np.asarray(x, np.float32)
    pad = (-x32.shape[-1]) % block_size
    if pad:
        x32 = np.pad(x32, [(0, 0)] * (x32.ndim - 1) + [(0, pad)], mode="edge")
    lead = x32.shape[:-1]
    nb = x32.shape[-1] // block_size
    xb = x32.reshape(lead + (nb, block_size))
    mn = np.min(xb, axis=-1).astype(np.float32)
    mx = np.max(xb, axis=-1).astype(np.float32)
    scale = np.maximum((mx - mn) / levels, np.float32(1e-12))
    v = (xb - mn[..., None]) / scale[..., None]
    q = np.clip(np.round(v), 0, levels).astype(np.uint8).reshape(
        lead + (nb * block_size,))
    if bits == 4:
        q = (q[..., 0::2] | (q[..., 1::2] << 4)).astype(np.uint8)
    return q, scale, mn


def np_dequantize_blockwise(
    q: np.ndarray,
    scale: np.ndarray,
    zero_point: np.ndarray,
    bits: int = 8,
    orig_size: Optional[int] = None,
) -> np.ndarray:
    """Host (numpy) :func:`dequantize_blockwise` (fp32 output, trailing
    padding trimmed to ``orig_size``). The block extent is derived from the
    payload/scale shapes, exactly like the jnp dequantizer."""
    lead = q.shape[:-1]
    if bits == 4:
        q = np.stack([q & 0xF, q >> 4], axis=-1).reshape(
            lead + (q.shape[-1] * 2,))
    nb = scale.shape[-1]
    block = q.shape[-1] // nb
    xb = q.reshape(lead + (nb, block)).astype(np.float32)
    x = (xb * np.asarray(scale, np.float32)[..., None]
         + np.asarray(zero_point, np.float32)[..., None]).reshape(
        lead + (nb * block,))
    if orig_size is not None and orig_size != x.shape[-1]:
        x = x[..., :orig_size]
    return np.ascontiguousarray(x)


# 1-bit (sign) quantizer — the wire format of the compressed allreduce; lives
# here so the error-feedback machinery is shared with the int collectives.
def pack_signs(x: jnp.ndarray) -> jnp.ndarray:
    """[n] float -> [n/8] uint8 of sign bits (1 = non-negative). n % 8 == 0."""
    bits = (x >= 0).astype(jnp.uint8)
    return jnp.packbits(bits)


def unpack_signs(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """[n/8] uint8 -> [n] float32 of ±1."""
    bits = jnp.unpackbits(packed)[:n]
    return 2.0 * bits.astype(jnp.float32) - 1.0


def quantize_1bit(buf: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit magnitude-preserving quantization: packed signs + one fp32 scale
    ``||buf|| / sqrt(n)`` (the 1-bit Adam wire format)."""
    n = buf.shape[-1]
    scale = jnp.linalg.norm(buf) / np.sqrt(n)
    return pack_signs(buf), scale


def dequantize_1bit(packed: jnp.ndarray, scale: jnp.ndarray, n: int) -> jnp.ndarray:
    return scale * unpack_signs(packed, n)


def error_feedback_step(buf, quantize_fn, dequantize_fn):
    """THE error-feedback residual update (single implementation for the 1-bit
    allreduce and the int8/int4 reduce ops): compress ``buf``, keep what the
    wire format lost. Caller folds the returned residual into the next step's
    ``buf``. Returns ``(payload, new_residual)`` where ``payload`` is whatever
    ``quantize_fn`` produced (passed to ``dequantize_fn`` verbatim)."""
    payload = quantize_fn(buf)
    new_residual = buf - dequantize_fn(payload)
    return payload, new_residual


# --------------------------------------------------------------------------- axis collectives
def qall_gather(
    x: jnp.ndarray,
    axis_name: AxisName,
    axis: int = 0,
    tiled: bool = True,
    bits: int = 8,
    block_size: int = DEFAULT_BLOCK,
    op_name: str = "qall_gather",
):
    """Quantized all-gather (inside ``shard_map``), drop-in shaped like
    :func:`deepspeed_tpu.comm.comm.all_gather`: each rank's shard travels as
    int8/int4 blocks + scales and is dequantized on arrival."""
    q, s, z = quantize_blockwise(x, bits=bits, block_size=block_size)
    _record(f"{op_name}[{axis_name}]", _payload_bytes(x), _payload_bytes(q, s, z))
    Q = lax.all_gather(q, axis_name, axis=0, tiled=False)
    S = lax.all_gather(s, axis_name, axis=0, tiled=False)
    Z = lax.all_gather(z, axis_name, axis=0, tiled=False)
    deq = dequantize_blockwise(Q, S, Z, bits=bits, block_size=block_size,
                               orig_size=x.shape[-1]).astype(x.dtype)
    # deq: [W, *x.shape]; lax.all_gather puts the world dim at ``axis``
    # (tiled=False) or concatenates along it (tiled=True) — mirror both
    stacked = jnp.moveaxis(deq, 0, axis)
    if not tiled:
        return stacked  # [..., W @ axis, ...]
    W = deq.shape[0]
    shape = list(x.shape)
    shape[axis] = shape[axis] * W
    return stacked.reshape(shape)


def qreduce_scatter(
    x: jnp.ndarray,
    axis_name: AxisName,
    axis: int = 0,
    bits: int = 8,
    block_size: int = DEFAULT_BLOCK,
    stochastic: bool = False,
    rng: Optional[jax.Array] = None,
    residual: Optional[jnp.ndarray] = None,
    mean: bool = False,
    op_name: str = "qreduce_scatter",
):
    """Quantized reduce-scatter (inside ``shard_map``), drop-in shaped like
    :func:`deepspeed_tpu.comm.comm.reduce_scatter`.

    Mechanics (the ZeRO++ gradient exchange): split the local buffer into
    ``W`` chunks along ``axis``, quantize each, all-to-all so rank ``i``
    receives every rank's chunk ``i``, dequantize, and reduce in fp32 — only
    the wire is int, the arithmetic is not. ``residual``: a same-shaped fp32
    error-feedback buffer; when given, it is folded into ``x`` before
    quantization and the call returns ``(result, new_residual)``.
    ``mean=True`` divides by the axis extent (gradient averaging).
    """
    W = int(lax.psum(1, axis_name))  # axis extent (static under shard_map)
    buf = x.astype(jnp.float32)
    if residual is not None:
        buf = buf + residual
    xm = jnp.moveaxis(buf, axis, 0)
    if xm.shape[0] % W:
        raise ValueError(
            f"qreduce_scatter: dim {axis} extent {xm.shape[0]} not divisible "
            f"by axis size {W}")
    chunks = xm.reshape((W, xm.shape[0] // W) + xm.shape[1:])
    if stochastic and rng is not None:
        rng = jax.random.fold_in(rng, lax.axis_index(axis_name))
    q, s, z = quantize_blockwise(chunks, bits=bits, block_size=block_size,
                                 stochastic=stochastic, rng=rng)
    _record(f"{op_name}[{axis_name}]", _payload_bytes(x), _payload_bytes(q, s, z))
    recv_q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    recv_s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=False)
    recv_z = lax.all_to_all(z, axis_name, split_axis=0, concat_axis=0, tiled=False)
    deq = dequantize_blockwise(recv_q, recv_s, recv_z, bits=bits,
                               block_size=block_size,
                               orig_size=chunks.shape[-1])
    out = jnp.sum(deq, axis=0)
    if mean:
        out = out / W
    out = jnp.moveaxis(out, 0, axis).astype(x.dtype)
    if residual is None:
        return out
    sent = dequantize_blockwise(q, s, z, bits=bits, block_size=block_size,
                                orig_size=chunks.shape[-1])
    sent = jnp.moveaxis(sent.reshape(xm.shape), 0, axis)
    return out, buf - sent


def qall_to_all(
    x: jnp.ndarray,
    axis_name: AxisName,
    split_axis: int = 0,
    concat_axis: int = 0,
    bits: int = 8,
    block_size: int = DEFAULT_BLOCK,
    op_name: str = "qall_to_all",
):
    """Quantized all-to-all (inside ``shard_map``), drop-in shaped like
    :func:`deepspeed_tpu.comm.comm.all_to_all` — the MoE dispatch / Ulysses
    exchange with an int wire. ``split_axis``/``concat_axis`` must not be the
    trailing (feature) dimension: blocks live there and must not be split."""
    last = x.ndim - 1
    if split_axis % x.ndim == last or concat_axis % x.ndim == last:
        raise ValueError(
            "qall_to_all: split/concat over the trailing dimension would cut "
            "quantization blocks; move features to the last axis")
    q, s, z = quantize_blockwise(x, bits=bits, block_size=block_size)
    _record(f"{op_name}[{axis_name}]", _payload_bytes(x), _payload_bytes(q, s, z))
    Q = lax.all_to_all(q, axis_name, split_axis=split_axis,
                       concat_axis=concat_axis, tiled=True)
    S = lax.all_to_all(s, axis_name, split_axis=split_axis,
                       concat_axis=concat_axis, tiled=True)
    Z = lax.all_to_all(z, axis_name, split_axis=split_axis,
                       concat_axis=concat_axis, tiled=True)
    return dequantize_blockwise(Q, S, Z, bits=bits, block_size=block_size,
                                orig_size=x.shape[-1]).astype(x.dtype)


# --------------------------------------------------------------------------- grad buckets
def grad_bucket_reduce(tree, resid, scale, *, bits: int = 8,
                       block_size: int = DEFAULT_BLOCK,
                       axis_name: AxisName = "dp",
                       op_name: str = "qgrad_bucket"):
    """Identity on ``tree`` in forward; the *backward* runs this bucket's
    quantized dp gradient exchange on the cotangents (mean reduce-scatter +
    all-gather, the ZeRO++ exchange of :func:`qreduce_scatter` /
    :func:`qall_gather`), inside whatever scan the forward sits in.

    Applied per layer by :func:`~deepspeed_tpu.runtime.zero.gather
    .zero3_layer_scan` under a bound
    :class:`~deepspeed_tpu.runtime.zero.gather.GradBucketContext`, this splits
    the monolithic post-backward gradient exchange into per-layer buckets
    emitted *inside the backward scan body* — each bucket's collectives are
    data-independent of the neighboring layers' backward matmuls, so XLA's
    async-collective scheduler can hide the gradient wire under backward
    compute instead of exposing one monolithic exchange at the end.

    ``resid``: this bucket's error-feedback residual (any shape whose size
    covers the padded flat bucket), or None. Its returned "cotangent" IS the
    updated residual — the caller reads it out of ``jax.grad`` (gradients are
    just values; the tap repurposes the dead residual-input slot to thread
    per-bucket EF state through the backward scan without new plumbing).
    ``scale``: traced loss scale the cotangents carry; the residual is kept in
    unscaled units so it survives dynamic loss-scale changes. Cotangent of
    ``scale`` is reported as zero (the caller never differentiates wrt it).
    """

    @jax.custom_vjp
    def tap(t, r, s):
        return t

    def tap_fwd(t, r, s):
        return t, (r, s)

    def tap_bwd(res, g):
        r, s = res
        leaves, treedef = jax.tree_util.tree_flatten(g)
        sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
        flat = jnp.concatenate(
            [l.astype(jnp.float32).ravel() for l in leaves])
        W = int(lax.psum(1, axis_name))
        n = flat.shape[0]
        npad = ((n + W - 1) // W) * W
        flat = jnp.pad(flat, (0, npad - n))
        kw = dict(bits=bits, block_size=block_size, mean=True,
                  op_name=f"{op_name}_rs")
        s_val = s if s is not None else jnp.float32(1.0)
        if r is not None:
            if int(np.prod(r.shape)) != npad:
                raise ValueError(
                    f"grad_bucket_reduce: residual size {r.shape} != padded "
                    f"bucket size {npad} (pad the per-bucket residual to a "
                    f"multiple of the dp extent {W})")
            red, new_r = qreduce_scatter(
                flat, axis_name, residual=r.reshape(-1) * s_val, **kw)
            d_resid = (new_r / s_val).reshape(r.shape).astype(r.dtype)
        else:
            red = qreduce_scatter(flat, axis_name, **kw)
            d_resid = None
        full = qall_gather(red, axis_name, axis=0, tiled=True, bits=bits,
                           block_size=block_size, op_name=f"{op_name}_ag")
        out, off = [], 0
        for l, sz in zip(leaves, sizes):
            out.append(full[off:off + sz].reshape(l.shape).astype(l.dtype))
            off += sz
        d_tree = jax.tree_util.tree_unflatten(treedef, out)
        d_scale = jnp.zeros_like(s) if s is not None else None
        return d_tree, d_resid, d_scale

    tap.defvjp(tap_fwd, tap_bwd)
    return tap(tree, resid, scale)


# --------------------------------------------------------------------------- GSPMD helper
def _normalize_entries(spec, rank: int) -> Tuple:
    entries = tuple(spec) if spec is not None else ()
    entries = entries + (None,) * (rank - len(entries))
    return entries[:rank]


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def quantized_reshard(x, spec: P, bits: int = 8,
                      block_size: int = DEFAULT_BLOCK,
                      op_name: str = "qreshard"):
    """Reshard ``x`` to ``spec`` with an int wire (inside plain ``jit``).

    Quantizes, constrains the *payload* to ``spec`` — XLA's inserted collective
    (all-gather for a ZeRO-3 window entry, all-to-all for MoE dispatch) then
    moves int8/int4 bytes — and dequantizes at the destination sharding.
    Backward is straight-through identity: cotangents reshard at full
    precision (gradient wire compression is ``zero_quantized_gradients``' job,
    a different code path), and parameters gathered this way stay trainable.
    """
    return _qreshard_impl(x, spec, bits, block_size, op_name)


def _qreshard_impl(x, spec, bits, block_size, op_name):
    from ..models.api import maybe_shard

    if x.ndim == 0 or not quantization_shrinks(
            x.shape[-1], bits, block_size, x.dtype.itemsize):
        # short rows (scalars, tiny biases, narrow bf16 leaves): the per-block
        # scale/zero-point overhead would inflate the wire — ship full precision
        entries = _normalize_entries(spec, x.ndim)
        return maybe_shard(x, P(*entries))
    q, s, z = quantize_blockwise(x, bits=bits, block_size=block_size)
    _record(f"{op_name}{tuple(spec)}", _payload_bytes(x), _payload_bytes(q, s, z))
    entries = _normalize_entries(spec, x.ndim)
    q = maybe_shard(q, P(*entries))
    # per-block scales: same leading placement, trailing (block) dim replicated
    sspec = P(*entries[:-1], None) if x.ndim else P()
    s = maybe_shard(s, sspec)
    z = maybe_shard(z, sspec)
    out = dequantize_blockwise(q, s, z, bits=bits, block_size=block_size,
                               orig_size=x.shape[-1]).astype(x.dtype)
    return maybe_shard(out, P(*entries))


def _qreshard_fwd(x, spec, bits, block_size, op_name):
    return _qreshard_impl(x, spec, bits, block_size, op_name), None


def _qreshard_bwd(spec, bits, block_size, op_name, _res, g):
    return (g,)


quantized_reshard.defvjp(_qreshard_fwd, _qreshard_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def quantized_matmul_reshard(h, w, spec: P, bits: int = 8,
                             block_size: int = DEFAULT_BLOCK,
                             op_name: str = "qmatmul_reshard"):
    """``h @ w`` where ``w`` arrives over the quantized wire and is consumed
    *without materializing a dequantized fp copy*: quantize shard-locally,
    constrain the int payload to ``spec`` (XLA's inserted all-gather moves
    uint8 + per-block scales), then feed the payload straight into the
    dequant-fused matmul (:mod:`deepspeed_tpu.ops.pallas.dequant_matmul` —
    per-VMEM-tile dequantization on TPU, XLA reshape fallback elsewhere).

    ``h``: [..., D]; ``w``: [D, F]; returns [..., F]. Backward is EQuARX-style
    split: ``d_h = g @ w_hat^T`` recomputes ``w_hat`` from the saved *int*
    payload (the only weight residual held between forward and backward —
    4x smaller than the fp copy autodiff would otherwise save), and
    ``d_w = h^T @ g`` passes straight through the quantize/dequantize pair
    (the same straight-through rule as :func:`quantized_reshard`).
    """
    out, _ = _qmatmul_fwd(h, w, spec, bits, block_size, op_name)
    return out


def _qmatmul_fwd(h, w, spec, bits, block_size, op_name):
    from ..models.api import maybe_shard
    from ..ops.pallas.dequant_matmul import dequant_matmul

    D, F = w.shape
    lead = h.shape[:-1]
    h2 = h.reshape(-1, D)
    if not quantization_shrinks(F, bits, block_size, w.dtype.itemsize):
        entries = _normalize_entries(spec, w.ndim)
        wg = maybe_shard(w, P(*entries))
        return (h2 @ wg.astype(h.dtype)).reshape(lead + (F,)), (h2, w)
    q, s, z = quantize_blockwise(w, bits=bits, block_size=block_size)
    _record(f"{op_name}{tuple(spec)}", _payload_bytes(w), _payload_bytes(q, s, z))
    entries = _normalize_entries(spec, w.ndim)
    q = maybe_shard(q, P(*entries))
    sspec = P(*entries[:-1], None)
    s = maybe_shard(s, sspec)
    z = maybe_shard(z, sspec)
    out = dequant_matmul(h2.astype(jnp.float32), q, s, z, orig_size=F,
                         bits=bits).astype(h.dtype)
    # zero-size marker carries w's dtype through the residual pytree (a bare
    # np.dtype is not a traceable leaf)
    return out.reshape(lead + (F,)), (h2, (q, s, z, jnp.zeros((0, F), w.dtype)))


def _qmatmul_bwd(spec, bits, block_size, op_name, res, g):
    h2, wres = res
    lead = g.shape[:-1]
    g2 = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
    if isinstance(wres, tuple):
        q, s, z, marker = wres
        wdtype = marker.dtype
        # the int payload is the only weight residual; the fp view exists
        # transiently for the two backward matmuls
        w_hat = dequantize_blockwise(q, s, z, bits=bits,
                                     orig_size=marker.shape[-1])
    else:
        wdtype = wres.dtype
        w_hat = wres.astype(jnp.float32)
    d_h = (g2 @ w_hat.T).astype(h2.dtype).reshape(lead + (h2.shape[-1],))
    d_w = (h2.astype(jnp.float32).T @ g2).astype(wdtype)
    return d_h, d_w


quantized_matmul_reshard.defvjp(
    lambda h, w, spec, bits, block_size, op_name:
        _qmatmul_fwd(h, w, spec, bits, block_size, op_name),
    _qmatmul_bwd)


def quantized_reshard_tree(tree, specs, bits: int = 8,
                           block_size: int = DEFAULT_BLOCK,
                           op_name: str = "qreshard"):
    """:func:`quantized_reshard` over a pytree of (array, PartitionSpec)."""
    return jax.tree_util.tree_map(
        lambda x, sp: quantized_reshard(x, sp, bits, block_size, op_name),
        tree, specs,
        is_leaf=lambda v: v is None)


def wire_bytes_per_element(bits: int, block_size: int) -> float:
    """Wire bytes per element (payload + amortized scale/zero-point) — the
    denominator of the advertised compression ratio."""
    return bits / 8.0 + 8.0 / block_size


__all__ = [
    "QuantizedCommConfig",
    "active_quantization",
    "quantize_blockwise",
    "dequantize_blockwise",
    "np_quantize_blockwise",
    "np_dequantize_blockwise",
    "pack_signs",
    "unpack_signs",
    "quantize_1bit",
    "dequantize_1bit",
    "error_feedback_step",
    "qall_gather",
    "qreduce_scatter",
    "qall_to_all",
    "grad_bucket_reduce",
    "quantized_reshard",
    "quantized_matmul_reshard",
    "quantized_reshard_tree",
    "wire_bytes_per_element",
    "DEFAULT_BLOCK",
    "SUPPORTED_BITS",
]
